"""Model export — the ``paddle.jit.to_static`` analogue, done the XLA way.

Reference: ``ppfleetx/utils/export.py:301-336`` traces the dygraph model to a
static program and writes ``.pdmodel``/``.pdiparams``; ``tools/export.py``
drives it. Here the portable artifact is a serialized ``jax.export`` module
(StableHLO bytes, multi-platform cpu+tpu) plus the parameter pytree:

    {out_dir}/module.bin     — serialized Exported (deserialize + .call)
    {out_dir}/params.npz     — flat parameter arrays keyed by tree path
    {out_dir}/meta.json      — treedef + input signature description

``load_exported`` restores both halves; ``InferenceEngine`` consumes them.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Sequence

import jax
import numpy as np

from fleetx_tpu.utils.log import logger

_SEP = "/"


def _flatten_params(params: Any) -> dict[str, np.ndarray]:
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    out = {}
    for path, leaf in flat:
        key = _SEP.join(getattr(p, "key", str(getattr(p, "idx", p)))
                        for p in path)
        out[key] = np.asarray(leaf)
    return out


def export_model(fn: Callable, example_args: Sequence[Any], out_dir: str,
                 params: Any, platforms: Sequence[str] = ("cpu", "tpu")) -> None:
    """AOT-export ``fn(params, *inputs)`` and save with its parameters."""
    os.makedirs(out_dir, exist_ok=True)
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)
        if not isinstance(x, jax.ShapeDtypeStruct) else x,
        (params,) + tuple(example_args))
    exp = jax.export.export(jax.jit(fn), platforms=list(platforms))(*abstract)
    with open(os.path.join(out_dir, "module.bin"), "wb") as f:
        f.write(exp.serialize())
    np.savez(os.path.join(out_dir, "params.npz"), **_flatten_params(params))
    meta = {
        "in_avals": [str(a) for a in jax.tree.leaves(abstract)],
        "platforms": list(platforms),
    }
    with open(os.path.join(out_dir, "meta.json"), "w") as f:
        json.dump(meta, f, indent=2)
    logger.info("exported model to %s (platforms=%s)", out_dir, list(platforms))


def _unflatten_params(arrays: dict[str, np.ndarray]) -> Any:
    tree: dict = {}
    for key, val in arrays.items():
        node = tree
        parts = key.split(_SEP)
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = val
    return tree


def load_exported(out_dir: str) -> tuple[Any, Any]:
    """→ (exported_module, params). ``exported_module.call(params, *inputs)``."""
    with open(os.path.join(out_dir, "module.bin"), "rb") as f:
        exp = jax.export.deserialize(f.read())
    arrays = np.load(os.path.join(out_dir, "params.npz"))
    params = _unflatten_params({k: arrays[k] for k in arrays.files})
    return exp, params
