"""Cached artifact fetching (reference ``ppfleetx/utils/download.py:43-117``).

``cached_path`` resolves a local path, ``file://`` URL, or http(s) URL to a
file under the cache dir (``FLEETX_CACHE`` env or ``~/.cache/fleetx_tpu``),
downloading at most once. Downloads stream to a temp file and rename
atomically, so concurrent processes never see partial artifacts. In
air-gapped environments http(s) fetches fail loudly with the cache path to
pre-populate.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import urllib.error
import urllib.parse
import urllib.request

from fleetx_tpu.observability.metrics import get_registry
from fleetx_tpu.resilience.policy import call_with_retry
from fleetx_tpu.utils.log import logger


class _PermanentDownloadError(Exception):
    """A client-side HTTP failure (404/403/...) — deliberately NOT an
    ``OSError`` so the retry policy classifies it as fatal: re-fetching a
    dead URL only delays the air-gap guidance below."""

DEFAULT_CACHE = os.path.join(os.path.expanduser("~"), ".cache", "fleetx_tpu")


def cache_dir() -> str:
    return os.environ.get("FLEETX_CACHE", DEFAULT_CACHE)


def cached_path(url_or_path: str, sub_dir: str = "") -> str:
    """→ local file path; downloads http(s) URLs into the cache once."""
    parsed = urllib.parse.urlparse(url_or_path)
    if parsed.scheme in ("", "file"):
        path = parsed.path if parsed.scheme == "file" else url_or_path
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        return path

    name = os.path.basename(parsed.path) or "download"
    key = hashlib.md5(url_or_path.encode()).hexdigest()[:8]
    target_dir = os.path.join(cache_dir(), sub_dir)
    os.makedirs(target_dir, exist_ok=True)
    target = os.path.join(target_dir, f"{key}_{name}")
    if os.path.exists(target):
        return target

    tmp = target + f".tmp.{os.getpid()}"
    logger.info("downloading %s -> %s", url_or_path, target)

    def _fetch_once():
        # raises OSError subclasses (URLError, timeouts, disk errors) —
        # exactly what the retry policy classifies as transient; permanent
        # HTTP client errors (4xx other than 429) are re-raised as fatal
        try:
            with urllib.request.urlopen(url_or_path, timeout=60) as resp, \
                    open(tmp, "wb") as out:
                shutil.copyfileobj(resp, out)
            os.replace(tmp, target)
        except urllib.error.HTTPError as e:
            if 400 <= e.code < 500 and e.code != 429:
                raise _PermanentDownloadError(f"HTTP {e.code}: {e}") from e
            raise  # 5xx / 429 stay OSError-transient
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)

    try:
        # transient network/disk blips retry under the process-wide policy
        # (resilience/policy.py); exhausted retries fall through to the
        # air-gap guidance below
        call_with_retry(_fetch_once, desc=f"download {url_or_path}",
                        counter=get_registry().counter(
                            "download_retries_total"))
    except Exception as e:
        raise RuntimeError(
            f"could not download {url_or_path} ({e}); in air-gapped "
            f"environments place the file at {target} manually") from e
    return target
