"""Cached artifact fetching (reference ``ppfleetx/utils/download.py:43-117``).

``cached_path`` resolves a local path, ``file://`` URL, or http(s) URL to a
file under the cache dir (``FLEETX_CACHE`` env or ``~/.cache/fleetx_tpu``),
downloading at most once. Downloads stream to a temp file and rename
atomically, so concurrent processes never see partial artifacts. In
air-gapped environments http(s) fetches fail loudly with the cache path to
pre-populate.

Content integrity (docs/resilience.md "Integrity"): an optional expected
``sha256`` per artifact is verified after every download AND on cache
hits — previously only the cache *key* was hashed, never the content, so
a bit-rotted cache entry or a tampered mirror fed the tokenizer silently.
A mismatch retries the download once through the existing policy (a
truncated transfer is transient-shaped), then fails fatal.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import urllib.error
import urllib.parse
import urllib.request

from fleetx_tpu.observability.metrics import get_registry
from fleetx_tpu.resilience.policy import call_with_retry
from fleetx_tpu.utils.log import logger


class _PermanentDownloadError(Exception):
    """A client-side HTTP failure (404/403/...) — deliberately NOT an
    ``OSError`` so the retry policy classifies it as fatal: re-fetching a
    dead URL only delays the air-gap guidance below."""

DEFAULT_CACHE = os.path.join(os.path.expanduser("~"), ".cache", "fleetx_tpu")


def cache_dir() -> str:
    return os.environ.get("FLEETX_CACHE", DEFAULT_CACHE)


def _sha256_file(path: str) -> str:
    """Streaming sha256 hex digest of a file."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            h.update(chunk)
    return h.hexdigest()


def cached_path(url_or_path: str, sub_dir: str = "",
                sha256: str = None) -> str:
    """→ local file path; downloads http(s) URLs into the cache once.

    ``sha256`` (hex digest) pins the artifact's CONTENT: local files and
    cache hits are verified before being handed out (a corrupt cache
    entry is evicted and re-downloaded), and every download is verified
    after the fetch — one mismatch retries through the policy, a second
    fails fatal (``_PermanentDownloadError``): re-fetching a mirror that
    keeps serving wrong bytes only delays the incident report.
    """
    expected = sha256.lower() if sha256 else None
    parsed = urllib.parse.urlparse(url_or_path)
    if parsed.scheme in ("", "file"):
        path = parsed.path if parsed.scheme == "file" else url_or_path
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        if expected:
            got = _sha256_file(path)
            if got != expected:
                raise RuntimeError(
                    f"sha256 mismatch for local artifact {path}: expected "
                    f"{expected}, got {got}")
        return path

    name = os.path.basename(parsed.path) or "download"
    key = hashlib.md5(url_or_path.encode()).hexdigest()[:8]
    target_dir = os.path.join(cache_dir(), sub_dir)
    os.makedirs(target_dir, exist_ok=True)
    target = os.path.join(target_dir, f"{key}_{name}")
    if os.path.exists(target):
        if not expected:
            return target
        got = _sha256_file(target)
        if got == expected:
            return target
        # bit-rotted / tampered cache entry: evict and re-download (the
        # cache key hashes only the URL, never the content)
        logger.warning("cached artifact %s fails sha256 verification "
                       "(expected %s, got %s) — evicting and "
                       "re-downloading", target, expected, got)
        get_registry().counter("download_checksum_mismatches").inc()
        os.remove(target)

    tmp = target + f".tmp.{os.getpid()}"
    logger.info("downloading %s -> %s", url_or_path, target)
    checksum_failures = [0]

    def _fetch_once():
        # raises OSError subclasses (URLError, timeouts, disk errors) —
        # exactly what the retry policy classifies as transient; permanent
        # HTTP client errors (4xx other than 429) are re-raised as fatal
        try:
            with urllib.request.urlopen(url_or_path, timeout=60) as resp, \
                    open(tmp, "wb") as out:
                shutil.copyfileobj(resp, out)
            if expected:
                got = _sha256_file(tmp)
                if got != expected:
                    checksum_failures[0] += 1
                    get_registry().counter(
                        "download_checksum_mismatches").inc()
                    if checksum_failures[0] > 1:
                        # the source keeps serving wrong bytes: fatal —
                        # this is corruption or tampering, not a blip
                        raise _PermanentDownloadError(
                            f"sha256 mismatch for {url_or_path} after "
                            f"retry: expected {expected}, got {got}")
                    # first mismatch: transient-shaped (truncated
                    # transfer), retried once via the policy
                    raise OSError(
                        f"sha256 mismatch for {url_or_path}: expected "
                        f"{expected}, got {got}")
            os.replace(tmp, target)
        except urllib.error.HTTPError as e:
            if 400 <= e.code < 500 and e.code != 429:
                raise _PermanentDownloadError(f"HTTP {e.code}: {e}") from e
            raise  # 5xx / 429 stay OSError-transient
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)

    try:
        # transient network/disk blips retry under the process-wide policy
        # (resilience/policy.py); exhausted retries fall through to the
        # air-gap guidance below
        call_with_retry(_fetch_once, desc=f"download {url_or_path}",
                        counter=get_registry().counter(
                            "download_retries_total"))
    except Exception as e:
        raise RuntimeError(
            f"could not download {url_or_path} ({e}); in air-gapped "
            f"environments place the file at {target} manually") from e
    return target
