"""Jitted paged-attention decode/prefill steps with static shapes.

The serving runtime's device side: two XLA programs, compiled ONCE per
engine, that the continuous-batching scheduler calls every step —

- ``prefill``: one chunk of ONE request's prompt (``[1, prefill_chunk]``,
  ragged tail masked) is forwarded, its K/V scattered into the request's
  pages, and the last valid position's logits/sampled token returned so
  the final chunk yields the first generated token (TTFT);
- ``decode``: one token for EVERY slot of the static ``[max_batch]``
  decode batch — inactive slots point at the null page and are masked, so
  requests join/leave the batch at step boundaries without changing any
  shape. Continuous batching therefore **never retraces**
  (``tests/test_zz_serving.py`` pins the jit cache size at 1).

The forward re-implements the ``models/gpt/model.py`` decode math over the
RAW parameter pytree (scanned-layer layout) instead of ``model.apply``:
the dense ``DecodeCache`` threads a single scalar write index through the
whole batch, which cannot express per-request ragged lengths — the thing
continuous batching is. Math is kept line-for-line parallel (f32
layernorms, cfg-dtype matmuls, f32 softmax, gelu ``approximate=True``) so
greedy decode is token-identical to one-shot ``generation.generate``.

Decode attention has two compiled forms, chosen ONCE at
``make_step_fns`` time (so the jit caches still hold one entry each):
the ``ops/paged_attention.py`` Pallas kernel that walks block tables
in-kernel (scalar-prefetched page ids, online-softmax f32 accumulation —
no dense page view ever materialises), or — when
``paged_kernel_enabled`` rejects the geometry — the original gathered
view ``pool[block_tables] → [B, pages_per_req·page_size, heads,
head_dim]`` fused by XLA. Prefill always takes the gather (its queries
span a whole chunk, not one token). Host-side machinery is identical on
both paths, and greedy decode is token-identical either way
(``tests/test_zz_serving.py`` pins parity AND which path compiled).

Quantized decode (``ServingConfig.quantize_decode``): int8-style fake-quant
on the decode activations (``Quantization.activation_bits`` →
``GPTConfig.qat_act_bits`` — wired by PR 2 but consumed by no inference
path until now) and weights (``qat_bits``), mirroring the training QAT
placement in ``models/gpt/model.py``; drift is parity-bounded on the CPU
mesh by ``tests/test_zz_serving.py``.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp

from fleetx_tpu.models.gpt import generation as G
from fleetx_tpu.ops import paged_attention as PA


def paged_kernel_enabled(cfg: Any, *, page_size: int, num_pages: int,
                         pages_per_req: int,
                         pool_sharding: Optional[Any] = None) -> bool:
    """Static kernel-vs-gather decision for one engine's geometry.

    True when the Pallas page-walk kernel serves decode: the shape
    predicate admits the (heads, head_dim, page) tiling, and — under a
    mesh that actually shards the pool — the per-device ``shard_map``
    wrapping applies too. Consulted once per engine; the result is baked
    into the decode program so the no-retrace pin is untouched.
    """
    if not PA.paged_attention_supported(
            num_heads=cfg.num_attention_heads, head_dim=cfg.head_dim,
            page_size=page_size, pages_per_req=pages_per_req,
            dtype=cfg.dtype):
        return False
    if pool_sharding is not None:
        mesh = pool_sharding.mesh
        sharded = any(dict(mesh.shape).get(a, 1) > 1
                      for a in ("fsdp", "tensor"))
        if sharded and not PA.paged_sharded_supported(
                mesh, num_heads=cfg.num_attention_heads,
                num_pages=num_pages):
            return False
    return True


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Engine-wide sampling knobs (static: baked into the two programs)."""

    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 0.0


def _quant(x: jax.Array, bits: int, enabled: bool, axis=None) -> jax.Array:
    """Config-gated fake-quant (identity when the decode path is fp)."""
    if not enabled:
        return x
    from fleetx_tpu.ops.quantization import fake_quant

    return fake_quant(x, bits, axis=axis)


def _layer_norm(p: dict, x: jax.Array, cfg: Any) -> jax.Array:
    """f32 layernorm matching ``models/gpt/model.py:LayerNorm``."""
    x32 = x.astype(jnp.float32)
    mean = x32.mean(-1, keepdims=True)
    var = ((x32 - mean) ** 2).mean(-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + cfg.layer_norm_epsilon)
    return (y * p["scale"] + p["bias"]).astype(cfg.dtype)


def _paged_attention(q: jax.Array, kd: jax.Array, vd: jax.Array,
                     q_pos: jax.Array) -> jax.Array:
    """Decode attention over the gathered page view (mirrors
    ``MultiHeadAttention._decode_attention``).

    ``q`` ``[B, S, heads, hd]``, ``kd``/``vd`` ``[B, K, heads, hd]``
    (K = pages_per_req · page_size), ``q_pos`` ``[B, S]`` absolute token
    positions. Every key slot at a position ≤ the query's is a written
    prefix slot; everything else (unwritten tail, null-page filler) is
    masked to the dtype's min, which underflows to an exact 0 in the f32
    softmax — identical math to the dense cache's masked softmax.
    """
    hd = q.shape[-1]
    scores = jnp.einsum("bqnd,bknd->bnqk", q, kd) / \
        jnp.sqrt(hd).astype(q.dtype)
    k_pos = jnp.arange(kd.shape[1])
    mask = k_pos[None, None, :] <= q_pos[:, :, None]          # [B, S, K]
    scores = jnp.where(mask[:, None], scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32),
                           axis=-1).astype(q.dtype)
    return jnp.einsum("bnqk,bknd->bqnd", probs, vd)


def _forward(params: Any, cfg: Any, tokens: jax.Array, positions: jax.Array,
             pool_k: jax.Array, pool_v: jax.Array, block_tables: jax.Array,
             quantize: bool, paged_kernel: bool = False,
             mesh: Optional[Any] = None
             ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Forward a ``[B, S]`` token block through the paged decode stack.

    Writes the block's K/V into the pool (scatter by block table), then
    runs attention per layer: the Pallas page-walk kernel when
    ``paged_kernel`` is set (decode only — ``S == 1``), the gathered page
    view otherwise. Returns ``(hidden [B, S, h], pool_k, pool_v)``.
    ``positions`` are absolute token positions (invalid slots must
    already be redirected to the null page via ``block_tables``-aware
    ``positions``/page math by the caller-built scatter indices below).
    """
    B, S = tokens.shape
    ps = pool_k.shape[2]
    gpt = params["gpt"]
    emb = gpt["embeddings"]

    wte = emb["word_embeddings"].astype(cfg.dtype)
    wpe = emb["position_embeddings"].astype(cfg.dtype)
    safe_pos = jnp.clip(positions, 0, cfg.max_position_embeddings - 1)
    x = wte[tokens] + wpe[safe_pos]

    # scatter targets, shared by every layer: page id + in-page offset per
    # (row, slot). Negative positions mark invalid slots → null page 0.
    page_slot = jnp.clip(positions // ps, 0, block_tables.shape[1] - 1)
    pages = jnp.take_along_axis(block_tables, page_slot, axis=1)
    pages = jnp.where(positions >= 0, pages, 0)
    offs = jnp.clip(positions % ps, 0, ps - 1)
    q_pos = jnp.maximum(positions, 0)

    nh, hd = cfg.num_attention_heads, cfg.head_dim
    act_bits, w_bits = cfg.qat_act_bits, cfg.qat_bits

    def layer(x, scanned):
        lp, pk_l, pv_l = scanned
        residual = x
        y = _layer_norm(lp["ln1"], x, cfg)

        y_in = _quant(y, act_bits, quantize)
        qkv_k = _quant(lp["attn"]["qkv_kernel"].astype(cfg.dtype), w_bits,
                       quantize, axis=0)
        qkv = jnp.einsum("bsh,hcnd->bcsnd", y_in, qkv_k)
        qkv = qkv + lp["attn"]["qkv_bias"].astype(cfg.dtype)[:, None]
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]          # [B, S, nh, hd]

        pk_l = pk_l.at[pages, offs].set(k)
        pv_l = pv_l.at[pages, offs].set(v)
        if paged_kernel and S == 1:
            # in-kernel block-table walk (ops/paged_attention.py): the
            # pool is read page-by-page via scalar-prefetched ids — the
            # dense [B, pages_per_req·page_size, nh, hd] view is never
            # materialised. positions[:, 0] is each row's query position
            # (< 0 = inactive slot → all pages masked, exact-zero out).
            attn = PA.paged_attention_sharded(
                q[:, 0], pk_l, pv_l, block_tables, positions[:, 0],
                mesh=mesh)[:, None]
        else:
            kd = pk_l[block_tables].reshape(B, -1, nh, hd)
            vd = pv_l[block_tables].reshape(B, -1, nh, hd)
            attn = _paged_attention(q, kd, vd, q_pos)

        attn = _quant(attn, act_bits, quantize)
        out_k = _quant(lp["attn"]["out_kernel"].astype(cfg.dtype), w_bits,
                       quantize, axis=(0, 1))
        y = jnp.einsum("bsnd,ndh->bsh", attn, out_k)
        y = y + lp["attn"]["out_bias"].astype(cfg.dtype)
        x = residual + y

        residual = x
        y = _layer_norm(lp["ln2"], x, cfg)
        y_in = _quant(y, act_bits, quantize)
        wi = _quant(lp["mlp"]["wi_kernel"].astype(cfg.dtype), w_bits,
                    quantize, axis=0)
        y = jnp.einsum("bsh,hm->bsm", y_in, wi) + \
            lp["mlp"]["wi_bias"].astype(cfg.dtype)
        y = jax.nn.gelu(y, approximate=True)
        y = _quant(y, act_bits, quantize)
        wo = _quant(lp["mlp"]["wo_kernel"].astype(cfg.dtype), w_bits,
                    quantize, axis=0)
        y = jnp.einsum("bsm,mh->bsh", y, wo) + \
            lp["mlp"]["wo_bias"].astype(cfg.dtype)
        x = residual + y
        return x, (pk_l, pv_l)

    x = x.astype(cfg.dtype)
    x, (pool_k, pool_v) = jax.lax.scan(
        layer, x, (gpt["layers"], pool_k, pool_v))
    x = _layer_norm(gpt["ln_f"], x, cfg)
    return x, pool_k, pool_v


def _logits(params: Any, cfg: Any, x_last: jax.Array) -> jax.Array:
    """Tied-embedding LM head on the selected positions → f32 ``[B, V]``."""
    wte = params["gpt"]["embeddings"]["word_embeddings"].astype(cfg.dtype)
    return jnp.einsum("bh,vh->bv", x_last, wte).astype(jnp.float32)


def _sample(logits: jax.Array, rng: jax.Array,
            sp: SamplingParams) -> jax.Array:
    """Greedy argmax or the sampling-transform chain shared with
    ``generation.generate`` (temperature → top-k → top-p → categorical)."""
    if not sp.do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    l = G.apply_temperature(logits, sp.temperature)
    l = G.apply_top_k(l, sp.top_k)
    l = G.apply_top_p(l, sp.top_p)
    return jax.random.categorical(rng, l, axis=-1).astype(jnp.int32)


def make_step_fns(cfg: Any, *, max_batch: int, pages_per_req: int,
                  prefill_chunk: int, sampling: SamplingParams,
                  quantize: bool = False,
                  pool_sharding: Optional[Any] = None,
                  paged_kernel: bool = False) -> dict:
    """Build the two jitted serving programs for one engine.

    Returns ``{"prefill": fn, "decode": fn}``; both donate the pool
    buffers (the engine rebinds them every call) and carry fully static
    shapes — ``max_batch``/``pages_per_req``/``prefill_chunk`` are baked
    in, so the jit caches hold exactly one entry each for the engine's
    lifetime. ``pool_sharding`` (a ``NamedSharding``) keeps the pools
    constrained to their mesh placement through every step.
    ``paged_kernel`` bakes the decode-attention path in (callers gate on
    ``paged_kernel_enabled`` — this function obeys, it doesn't decide).
    """
    mesh = pool_sharding.mesh if pool_sharding is not None else None

    def constrain(pool):
        if pool_sharding is None:
            return pool
        return jax.lax.with_sharding_constraint(pool, pool_sharding)

    def prefill(params, pool_k, pool_v, tokens, block_table, start, n_valid,
                rng):
        """One prompt chunk for one request: ``tokens`` ``[1, C]`` with
        ``n_valid`` real entries starting at absolute position ``start``;
        returns the pools plus the last valid position's sampled token and
        f32 logits (meaningful on the request's final chunk)."""
        idx = jnp.arange(prefill_chunk)[None, :]
        positions = jnp.where(idx < n_valid, start + idx, -1)
        x, pool_k, pool_v = _forward(params, cfg, tokens, positions,
                                     pool_k, pool_v, block_table, quantize)
        last = jnp.clip(n_valid - 1, 0, prefill_chunk - 1)
        x_last = jax.lax.dynamic_index_in_dim(x[0], last, axis=0,
                                              keepdims=False)[None]
        logits = _logits(params, cfg, x_last)
        return (constrain(pool_k), constrain(pool_v),
                _sample(logits, rng, sampling), logits)

    def decode(params, pool_k, pool_v, tokens, block_tables, lens, rng):
        """One decode step for the full static batch: ``tokens``/``lens``
        ``[max_batch]`` (inactive slots carry ``lens < 0`` and null-page
        block tables); returns pools + sampled tokens + f32 logits."""
        positions = jnp.where(lens >= 0, lens, -1)[:, None]
        x, pool_k, pool_v = _forward(params, cfg, tokens[:, None], positions,
                                     pool_k, pool_v, block_tables, quantize,
                                     paged_kernel=paged_kernel, mesh=mesh)
        logits = _logits(params, cfg, x[:, 0])
        return (constrain(pool_k), constrain(pool_v),
                _sample(logits, rng, sampling), logits)

    del max_batch, pages_per_req  # shapes arrive via the arrays themselves
    return {
        "prefill": jax.jit(prefill, donate_argnums=(1, 2)),
        "decode": jax.jit(decode, donate_argnums=(1, 2)),
    }
