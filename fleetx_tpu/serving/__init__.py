"""Production serving runtime: continuous batching over a paged KV cache.

The training side of the FleetX blueprint has had a request-LESS inference
path since the seed (``core/engine/inference_engine.py`` — stateless batch
predict); this package is the request-LEVEL runtime the ROADMAP's "serve
heavy traffic from millions of users" north star needs (docs/serving.md):

- ``paged_cache``  — fixed-size KV pages in a preallocated pool with
  per-request block tables (the "Compiler-First State Space Duality and
  Portable O(1) Autoregressive Caching" blueprint, PAPERS.md);
- ``decode``       — jitted chunk-prefill + one-token decode steps with
  STATIC batch/page shapes, so continuous batching never retraces;
- ``engine``       — the continuous-batching scheduler: requests join
  in-flight decode at step boundaries, long prompts chunk-prefill without
  stalling the decode batch, admission refuses what the pool cannot hold;
- ``server``       — one engine replica behind a JSON-lines TCP front with
  graceful drain on the PR 4/6 preemption latch;
- ``router``       — round-robin + least-outstanding request router over N
  supervised replicas, re-dispatching on replica loss;
- ``bench``        — Poisson-load serving bench whose tokens/s +
  tail-latency JSON joins ``tools/perf_gate.py``.
"""

__all__ = ["ServingConfig", "ServingEngine", "PageAllocator", "init_pool",
           "NULL_PAGE"]

#: package export → defining submodule; resolved on first attribute access
#: (PEP 562) so importing ``fleetx_tpu.serving.router`` — the stdlib-only
#: fleet front that must start in <1s — never pays the engine's jax import
_EXPORTS = {
    "ServingConfig": "engine", "ServingEngine": "engine",
    "PageAllocator": "paged_cache", "init_pool": "paged_cache",
    "NULL_PAGE": "paged_cache",
}


def __getattr__(name: str):
    """Lazy package exports (keeps the router import path jax-free)."""
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f"{__name__}.{module}"), name)
