"""Request router over N serving replicas — stdlib-only, jax-free.

Sits in front of the supervised replica fleet (one ``tools/supervise.py``
per replica, docs/serving.md "Fleet layout") and owns the loss-free
re-dispatch contract: a request the router has ACCEPTED is retried against
surviving replicas until some replica completes it — replica crashes
(connection reset, supervisor restarting the process) and graceful drains
(the explicit ``"draining"`` response) both just mark the backend penalised
for a cooldown and move the request on. Decode requests are pure functions
of (params, prompt), so re-dispatch is idempotent by construction.

Placement policy: **least-outstanding** with round-robin tie-break — the
cheapest estimator of per-replica queue depth that needs no backend
cooperation (each replica already exports its own queue gauges).

This module deliberately imports no jax so ``python -m
fleetx_tpu.serving.router`` starts in milliseconds — the router must come
up before (and outlive) the replicas it fronts.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from typing import Optional

#: seconds a failed/draining backend is skipped before being retried
#: (a supervisor restart needs a few seconds to bring the replica back)
PENALTY_S = 1.0

#: total seconds the router keeps retrying one accepted request before
#: answering "no backend" — covers a full supervisor restart cycle
DISPATCH_DEADLINE_S = 120.0


def _read_line(conn: socket.socket) -> bytes:
    """Read one newline-terminated frame (the shared half of the wire
    protocol — ``serving/server.py`` documents it; this copy keeps the
    router importable without the jax-adjacent server module)."""
    buf = b""
    while not buf.endswith(b"\n"):
        chunk = conn.recv(4096)
        if not chunk:
            break  # EOF mid-frame — caller decides if that is an error
        buf += chunk
    return buf


class Backend:
    """One replica address + its health/placement bookkeeping."""

    def __init__(self, host: str, port: int):
        self.addr = (host, int(port))
        self.outstanding = 0
        self.penalized_until = 0.0
        self.dispatched = 0
        self.failures = 0

    def available(self, now: float) -> bool:
        """Whether placement may pick this backend right now."""
        return now >= self.penalized_until

    def penalize(self, now: float, seconds: float = PENALTY_S) -> None:
        """Skip this backend for ``seconds`` (crash or drain observed)."""
        self.penalized_until = now + seconds
        self.failures += 1


class Router:
    """Round-robin + least-outstanding front over the replica fleet."""

    def __init__(self, backends: list, host: str = "127.0.0.1",
                 port: int = 0, request_timeout: float = 120.0):
        self.backends = [Backend(h, p) for h, p in backends]
        assert self.backends, "router needs at least one backend"
        self.host = host
        self.port = int(port)
        self.request_timeout = float(request_timeout)
        self._rr = 0
        self._lock = threading.Lock()
        self._listener: Optional[socket.socket] = None
        self._stop = threading.Event()
        self.retries = 0

    # ------------------------------------------------------------ placement
    def pick(self) -> Optional[Backend]:
        """Least outstanding among available backends, round-robin ties;
        None when every backend is inside its penalty window."""
        now = time.monotonic()
        with self._lock:
            avail = [b for b in self.backends if b.available(now)]
            if not avail:
                return None
            best = min(b.outstanding for b in avail)
            tied = [b for b in avail if b.outstanding == best]
            choice = tied[self._rr % len(tied)]
            self._rr += 1
            choice.outstanding += 1
            choice.dispatched += 1
            return choice

    def _release(self, backend: Backend) -> None:
        with self._lock:
            backend.outstanding = max(backend.outstanding - 1, 0)

    # ------------------------------------------------------------- dispatch
    def dispatch(self, payload: dict) -> dict:
        """Forward one request, re-dispatching across backends until a
        replica completes it or the deadline passes."""
        deadline = time.monotonic() + DISPATCH_DEADLINE_S
        while time.monotonic() < deadline:
            backend = self.pick()
            if backend is None:
                time.sleep(0.05)  # whole fleet penalised — restart window
                continue
            try:
                resp = self._forward(backend, payload)
            except (OSError, ValueError):
                # transport failure OR a torn/garbled response line (a
                # replica killed mid-write) — both mean "this backend did
                # not complete the request": penalise and re-dispatch
                backend.penalize(time.monotonic())
                self.retries += 1
                continue
            finally:
                self._release(backend)
            if resp.get("error") == "draining":
                # graceful reclaim: stop placing onto this backend and
                # retry the request elsewhere, losing nothing
                backend.penalize(time.monotonic())
                self.retries += 1
                continue
            return resp
        return {"id": payload.get("id"), "error": "no backend available"}

    def _forward(self, backend: Backend, payload: dict) -> dict:
        with socket.create_connection(backend.addr,
                                      timeout=self.request_timeout) as conn:
            conn.sendall((json.dumps(payload) + "\n").encode("utf-8"))
            conn.settimeout(self.request_timeout)
            buf = _read_line(conn)
        if not buf.strip():
            raise ConnectionError(f"empty response from {backend.addr}")
        # a torn line (replica died mid-write) raises ValueError → retry
        return json.loads(buf.decode("utf-8"))

    # -------------------------------------------------------------- serving
    def start(self) -> int:
        """Bind the front socket + accept thread; returns the bound port."""
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self._listener.listen(128)
        self.port = self._listener.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="router-accept").start()
        return self.port

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(self.request_timeout)
            buf = _read_line(conn)
            if not buf.strip():
                return
            payload = json.loads(buf.decode("utf-8"))
            resp = self.dispatch(payload)
            conn.sendall((json.dumps(resp) + "\n").encode("utf-8"))
        except (OSError, ValueError):
            pass  # client went away / bad JSON — nothing to answer
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        """Tear down the front listener."""
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass


def main(argv=None) -> int:
    """``python -m fleetx_tpu.serving.router --port P --backends h:p,h:p``."""
    import argparse

    ap = argparse.ArgumentParser(description="fleetx serving router")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--backends", required=True,
                    help="comma-separated host:port replica list")
    args = ap.parse_args(argv)
    backends = []
    for spec in args.backends.split(","):
        h, _, p = spec.strip().rpartition(":")
        backends.append((h or "127.0.0.1", int(p)))
    router = Router(backends, host=args.host, port=args.port)
    port = router.start()
    print(f"[router] listening on {args.host}:{port} over "
          f"{len(backends)} backend(s)", flush=True)
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        router.close()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
