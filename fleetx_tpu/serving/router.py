"""Request router over N serving replicas — stdlib-only, jax-free.

Sits in front of the supervised replica fleet (one ``tools/supervise.py``
per replica, docs/serving.md "Fleet layout") and owns the loss-free
re-dispatch contract: a request the router has ACCEPTED is retried against
surviving replicas until some replica completes it — replica crashes
(connection reset, supervisor restarting the process) and graceful drains
(the explicit ``"draining"`` response) both just mark the backend penalised
for a cooldown and move the request on. Decode requests are pure functions
of (params, prompt), so re-dispatch is idempotent by construction.

Placement policy: **least-outstanding** with round-robin tie-break — the
cheapest estimator of per-replica queue depth that needs no backend
cooperation (each replica already exports its own queue gauges).

The router is also the fleet's observer (docs/serving.md
"Observability"): it counts dispatches / re-dispatches / penalties /
drain refusals, keeps a bounded per-request dispatch journal, and — when
``--fleet-out`` is given — periodically polls every backend's ``stats``
verb, merging the snapshots ``gang.merge_snapshots``-style (counters
summed, TTFT/ITL pooled count-weighted with the worst replica
attributed, fleet requests-per-chip) into ``FLEET_RECORD_SCHEMA``
records appended to a JSONL sink. Its own front answers two verbs:
``{"verb": "stats"}`` returns a fresh fleet record, and ``{"verb":
"trace", "id": ...}`` merges the router journal with every live
replica's timeline for that id — so a re-dispatched request's full story
(dispatch → drain refusal → re-dispatch → lifecycle) reads as one
time-sorted event list.

This module deliberately imports no jax so ``python -m
fleetx_tpu.serving.router`` starts in milliseconds — the router must come
up before (and outlive) the replicas it fronts. The observability
imports it does take (schema, sinks) are stdlib-only.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, Optional

from fleetx_tpu.observability import tsan

#: seconds a failed/draining backend is skipped before being retried
#: (a supervisor restart needs a few seconds to bring the replica back)
PENALTY_S = 1.0

#: total seconds the router keeps retrying one accepted request before
#: answering "no backend" — covers a full supervisor restart cycle
DISPATCH_DEADLINE_S = 120.0

#: seconds between fleet stats sweeps when a fleet sink is configured
DEFAULT_POLL_INTERVAL_S = 1.0

#: timeout for one stats/trace side-channel round trip (read-only verbs
#: answered at a step boundary — far faster than a generate request)
VERB_TIMEOUT_S = 10.0

#: fleet records carry the same version as serving snapshots
FLEET_SCHEMA_VERSION = 2

#: router-owned dispatch counters, merged into every fleet record
ROUTER_COUNTERS = ("dispatched_total", "redispatched_total",
                   "penalties_total", "drain_refusals_total",
                   "no_backend_total", "completed_total")


def _read_line(conn: socket.socket) -> bytes:
    """Read one newline-terminated frame (the shared half of the wire
    protocol — ``serving/server.py`` documents it; this copy keeps the
    router importable without the jax-adjacent server module)."""
    buf = b""
    while not buf.endswith(b"\n"):
        chunk = conn.recv(4096)
        if not chunk:
            break  # EOF mid-frame — caller decides if that is an error
        buf += chunk
    return buf


class Backend:
    """One replica address + its health/placement bookkeeping."""

    def __init__(self, host: str, port: int):
        self.addr = (host, int(port))
        self.outstanding = 0
        self.penalized_until = 0.0
        self.dispatched = 0
        self.failures = 0

    def available(self, now: float) -> bool:
        """Whether placement may pick this backend right now."""
        return now >= self.penalized_until

    def penalize(self, now: float, seconds: float = PENALTY_S) -> None:
        """Skip this backend for ``seconds`` (crash or drain observed)."""
        self.penalized_until = now + seconds
        self.failures += 1


def _addr_str(addr: tuple) -> str:
    """``(host, port)`` → the ``host:port`` replica label fleet records
    and traces attribute to."""
    return f"{addr[0]}:{addr[1]}"


class RequestJournal:
    """Bounded request-id → router-side dispatch events.

    The router's half of a request's merged trace: which backend each
    attempt went to, drain refusals, transport retries, completion.
    Insertion-ordered eviction over ``max_requests`` ids (the flight-ring
    stance), each id's event list itself a bounded deque.
    """

    def __init__(self, max_requests: int = 1024,
                 events_per_request: int = 64):
        self.max_requests = max(int(max_requests), 1)
        self.events_per_request = max(int(events_per_request), 8)
        self._lock = tsan.lock("router.journal")
        self._events: "OrderedDict[str, deque]" = OrderedDict()

    def note(self, rid, name: str, **data) -> None:
        """Append one router event for ``rid`` (None ids are unjournaled:
        the reply still reaches the client, there is just no trace key)."""
        if rid is None:
            return
        evt = {**data, "t": time.time(), "name": name, "source": "router"}
        with self._lock:
            evts = self._events.get(str(rid))
            if evts is None:
                evts = deque(maxlen=self.events_per_request)
                self._events[str(rid)] = evts
                while len(self._events) > self.max_requests:
                    self._events.popitem(last=False)
            evts.append(evt)

    def events(self, rid) -> list:
        """Copy of one id's journal (empty list when unknown/evicted)."""
        with self._lock:
            return list(self._events.get(str(rid)) or ())


def merge_fleet_snapshots(snaps: Dict[str, dict], replicas_total: int,
                          router_counters: Optional[dict] = None) -> dict:
    """N per-replica ``serving_snapshot()`` dicts → one fleet record.

    The serving-side twin of ``observability/gang.py:_merge_window``:
    monotonic counters are summed, the TTFT/ITL histogram summaries are
    pooled count-weighted (fleet mean) with the tail taken from — and
    attributed to — the worst replica, occupancy is averaged AND max'd
    with attribution, and requests-per-chip divides fleet completions by
    fleet chips. ``snaps`` maps replica label → snapshot; replicas that
    failed to report simply aren't in it (``replicas_reported`` records
    the actual coverage). Gauges that are null on a replica (scheduler
    gauges "unavailable") contribute nothing rather than a fake zero.
    The shape is ``observability/schema.py:FLEET_RECORD_SCHEMA``.
    """
    replicas = sorted(snaps)

    def _sum_int(key: str) -> int:
        return int(sum(int(snaps[r].get(key) or 0) for r in replicas))

    def _present(key: str) -> Dict[str, float]:
        return {r: snaps[r][key] for r in replicas
                if isinstance(snaps[r].get(key), (int, float))
                and not isinstance(snaps[r].get(key), bool)}

    record: dict = {
        "ts": max([float(snaps[r].get("ts") or 0.0) for r in replicas],
                  default=time.time()),
        "scope": "fleet",
        "schema_version": FLEET_SCHEMA_VERSION,
        "replicas_total": int(replicas_total),
        "replicas_reported": len(replicas),
        "requests_admitted": _sum_int("requests_admitted"),
        "requests_completed": _sum_int("requests_completed"),
        "requests_refused": _sum_int("requests_refused"),
        "tokens_total": _sum_int("tokens_total"),
        "tokens_per_sec": sum(_present("tokens_per_sec").values())
        if replicas else None,
    }
    chips = sum(int(snaps[r].get("chips") or 1) for r in replicas)
    record["chips_total"] = chips
    record["requests_per_chip"] = \
        (record["requests_completed"] / chips) if chips else None
    qd = _present("queue_depth")
    record["queue_depth"] = int(sum(qd.values())) if qd else None
    ar = _present("active_requests")
    record["active_requests"] = int(sum(ar.values())) if ar else None
    occ = _present("page_occupancy")
    if occ:
        record["page_occupancy_mean"] = sum(occ.values()) / len(occ)
        worst = max(occ, key=lambda r: occ[r])
        record["page_occupancy_max"] = float(occ[worst])
        record["page_occupancy_max_replica"] = worst
    for name in ("ttft", "itl"):
        hists = {r: snaps[r].get(name) or {} for r in replicas}
        counts = {r: int(h.get("count") or 0) for r, h in hists.items()}
        total = sum(counts.values())
        if not total:
            continue
        record[f"{name}_mean_s"] = sum(
            float(hists[r].get("mean") or 0.0) * counts[r]
            for r in replicas) / total
        worst = max((r for r in replicas if counts[r]),
                    key=lambda r: float(hists[r].get("p99") or 0.0))
        record[f"{name}_p99_s"] = float(hists[worst].get("p99") or 0.0)
        record[f"{name}_p99_replica"] = worst
    att = _present("slo_attainment")
    if att:
        record["slo_attainment"] = min(att.values())
    for name in ROUTER_COUNTERS:
        if router_counters and name in router_counters:
            record[name] = int(router_counters[name])
    return record


class Router:
    """Round-robin + least-outstanding front over the replica fleet."""

    def __init__(self, backends: list, host: str = "127.0.0.1",
                 port: int = 0, request_timeout: float = 120.0,
                 fleet_out: Optional[str] = None,
                 poll_interval: float = DEFAULT_POLL_INTERVAL_S):
        self.backends = [Backend(h, p) for h, p in backends]
        assert self.backends, "router needs at least one backend"
        self.host = host
        self.port = int(port)
        self.request_timeout = float(request_timeout)
        self.fleet_out = fleet_out
        self.poll_interval = float(poll_interval)
        self._rr = 0
        self._lock = tsan.lock("router.placement")
        self._listener: Optional[socket.socket] = None
        self._stop = threading.Event()
        self.retries = 0
        self.counters = {name: 0 for name in ROUTER_COUNTERS}
        self.journal = RequestJournal()
        self.last_fleet: Optional[dict] = None
        self._fleet_sink = None

    def _count(self, name: str) -> None:
        with self._lock:
            self.counters[name] += 1

    def router_counters(self) -> dict:
        """Copy of the dispatch counters (merged into fleet records)."""
        with self._lock:
            return dict(self.counters)

    # ------------------------------------------------------------ placement
    def pick(self) -> Optional[Backend]:
        """Least outstanding among available backends, round-robin ties;
        None when every backend is inside its penalty window."""
        now = time.monotonic()
        with self._lock:
            avail = [b for b in self.backends if b.available(now)]
            if not avail:
                return None
            best = min(b.outstanding for b in avail)
            tied = [b for b in avail if b.outstanding == best]
            choice = tied[self._rr % len(tied)]
            self._rr += 1
            choice.outstanding += 1
            choice.dispatched += 1
            return choice

    def _release(self, backend: Backend) -> None:
        with self._lock:
            backend.outstanding = max(backend.outstanding - 1, 0)

    def _note_failure(self, backend: Backend) -> None:
        """Penalise a backend and count the retry under the placement lock
        — ``pick()`` reads the penalty window under the same lock, and the
        retry counter is bumped from every per-connection handler."""
        with self._lock:
            backend.penalize(time.monotonic())
            self.retries += 1

    # ------------------------------------------------------------- dispatch
    def dispatch(self, payload: dict) -> dict:
        """Forward one request, re-dispatching across backends until a
        replica completes it or the deadline passes."""
        rid = payload.get("id")
        deadline = time.monotonic() + DISPATCH_DEADLINE_S
        attempts = 0
        while time.monotonic() < deadline:
            backend = self.pick()
            if backend is None:
                time.sleep(0.05)  # whole fleet penalised — restart window
                continue
            addr = _addr_str(backend.addr)
            attempts += 1
            self._count("dispatched_total")
            if attempts > 1:
                self._count("redispatched_total")
            self.journal.note(rid, "dispatch", backend=addr,
                              attempt=attempts)
            try:
                resp = self._forward(backend, payload)
            except (OSError, ValueError):
                # transport failure OR a torn/garbled response line (a
                # replica killed mid-write) — both mean "this backend did
                # not complete the request": penalise and re-dispatch
                self._note_failure(backend)
                self._count("penalties_total")
                self.journal.note(rid, "transport_retry", backend=addr)
                continue
            finally:
                self._release(backend)
            if resp.get("error") == "draining":
                # graceful reclaim: stop placing onto this backend and
                # retry the request elsewhere, losing nothing
                self._note_failure(backend)
                self._count("penalties_total")
                self._count("drain_refusals_total")
                self.journal.note(rid, "drain_refusal", backend=addr)
                continue
            self._count("completed_total")
            self.journal.note(rid, "completed", backend=addr,
                              error=resp.get("error"))
            return resp
        self._count("no_backend_total")
        self.journal.note(rid, "no_backend")
        return {"id": rid, "error": "no backend available"}

    def _forward(self, backend: Backend, payload: dict) -> dict:
        return self._ask(backend.addr, payload,
                         timeout=self.request_timeout)

    def _ask(self, addr: tuple, payload: dict,
             timeout: float = VERB_TIMEOUT_S) -> dict:
        """One JSON-line round trip (``OSError``/``ValueError`` on
        transport failure or a torn line — callers decide the retry)."""
        with socket.create_connection(addr, timeout=timeout) as conn:
            conn.sendall((json.dumps(payload) + "\n").encode("utf-8"))
            conn.settimeout(timeout)
            buf = _read_line(conn)
        if not buf.strip():
            raise ConnectionError(f"empty response from {addr}")
        # a torn line (replica died mid-write) raises ValueError → retry
        return json.loads(buf.decode("utf-8"))

    # --------------------------------------------------------------- verbs
    def poll_fleet(self) -> dict:
        """One ``stats`` sweep over the backends → a merged fleet record.

        Partial coverage is tolerated by construction: a draining or
        crashed replica just doesn't report this window, and
        ``replicas_reported`` says so.
        """
        snaps: Dict[str, dict] = {}
        for backend in self.backends:
            addr = _addr_str(backend.addr)
            try:
                resp = self._ask(backend.addr, {"verb": "stats"})
            except (OSError, ValueError):
                continue
            if not isinstance(resp, dict) or resp.get("error"):
                continue
            snaps[addr] = resp
        record = merge_fleet_snapshots(
            snaps, replicas_total=len(self.backends),
            router_counters=self.router_counters())
        self.last_fleet = record
        return record

    def trace(self, rid: str) -> dict:
        """Merge the router journal with every live replica's timeline
        for one id, time-sorted — the fleet view of where the request's
        latency went, drain refusals and re-dispatches included."""
        events = self.journal.events(rid)
        sources = ["router"] if events else []
        attribution = None
        for backend in self.backends:
            try:
                resp = self._ask(backend.addr,
                                 {"verb": "trace", "id": rid})
            except (OSError, ValueError):
                continue  # draining/crashed replica: its half is gone
            if resp.get("error") or not isinstance(resp.get("events"),
                                                   list):
                continue
            addr = _addr_str(backend.addr)
            events.extend({**e, "source": addr} for e in resp["events"])
            sources.append(addr)
            if isinstance(resp.get("attribution"), dict):
                attribution = resp["attribution"]
        if not events:
            return {"id": rid, "error": "unknown request id"}
        events.sort(key=lambda e: e.get("t") or 0.0)
        out = {"id": rid, "events": events, "sources": sources}
        if attribution is not None:
            out["attribution"] = attribution
        return out

    def _poll_loop(self) -> None:
        from fleetx_tpu.observability.schema import validate_fleet_record

        while not self._stop.wait(self.poll_interval):
            record = self.poll_fleet()
            problems = validate_fleet_record(record)
            if problems:  # a merge bug must not poison the JSONL stream
                print(f"[router] dropping invalid fleet record: "
                      f"{problems}", flush=True)
                continue
            with self._lock:  # close() swaps the sink out under the lock
                sink = self._fleet_sink
            if sink is not None:
                try:
                    sink.emit(record)
                except (OSError, ValueError):
                    pass  # sink closed mid-shutdown — record is dropped

    # -------------------------------------------------------------- serving
    def start(self) -> int:
        """Bind the front socket + accept thread; returns the bound port."""
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self._listener.listen(128)
        self.port = self._listener.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="router-accept").start()
        if self.fleet_out:
            # stdlib-only sink reuse (sinks.py imports jax lazily now):
            # the fleet stream is line-buffered JSONL like every other
            from fleetx_tpu.observability.sinks import JsonlSink

            self._fleet_sink = JsonlSink(self.fleet_out)
            threading.Thread(target=self._poll_loop, daemon=True,
                             name="router-fleet-poll").start()
        return self.port

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(self.request_timeout)
            buf = _read_line(conn)
            if not buf.strip():
                return
            payload = json.loads(buf.decode("utf-8"))
            verb = payload.get("verb") if isinstance(payload, dict) \
                else None
            if verb == "stats":
                resp = self.poll_fleet()
            elif verb == "trace":
                resp = self.trace(str(payload.get("id")))
            else:
                resp = self.dispatch(payload)
            conn.sendall((json.dumps(resp) + "\n").encode("utf-8"))
        except (OSError, ValueError):
            pass  # client went away / bad JSON — nothing to answer
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        """Tear down the front listener and the fleet sink."""
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._lock:  # the poll loop reads the sink under the lock
            sink, self._fleet_sink = self._fleet_sink, None
        if sink is not None:
            try:
                sink.close()
            except OSError:
                pass


def main(argv=None) -> int:
    """``python -m fleetx_tpu.serving.router --port P --backends h:p,h:p``."""
    import argparse

    ap = argparse.ArgumentParser(description="fleetx serving router")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--backends", required=True,
                    help="comma-separated host:port replica list")
    ap.add_argument("--fleet-out", default=None,
                    help="append merged fleet records (JSONL, "
                         "FLEET_RECORD_SCHEMA) to this path")
    ap.add_argument("--poll-interval", type=float,
                    default=DEFAULT_POLL_INTERVAL_S,
                    help="seconds between backend stats sweeps")
    args = ap.parse_args(argv)
    backends = []
    for spec in args.backends.split(","):
        h, _, p = spec.strip().rpartition(":")
        backends.append((h or "127.0.0.1", int(p)))
    router = Router(backends, host=args.host, port=args.port,
                    fleet_out=args.fleet_out,
                    poll_interval=args.poll_interval)
    port = router.start()
    print(f"[router] listening on {args.host}:{port} over "
          f"{len(backends)} backend(s)"
          + (f", fleet records → {args.fleet_out}" if args.fleet_out
             else ""), flush=True)
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        router.close()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
