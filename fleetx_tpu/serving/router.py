"""Request router over N serving replicas — stdlib-only, jax-free.

Sits in front of the supervised replica fleet (one ``tools/supervise.py``
per replica, docs/serving.md "Fleet layout") and owns the loss-free
re-dispatch contract: a request the router has ACCEPTED is retried against
surviving replicas until some replica completes it — replica crashes
(connection reset, supervisor restarting the process) and graceful drains
(the explicit ``"draining"`` response) both just mark the backend penalised
for a cooldown and move the request on. Decode requests are pure functions
of (params, prompt), so re-dispatch is idempotent by construction.

Placement policy: **least-outstanding** with round-robin tie-break — the
cheapest estimator of per-replica queue depth that needs no backend
cooperation (each replica already exports its own queue gauges).

Health policy (docs/serving.md "Fault tolerance"): each backend carries a
**circuit breaker** instead of a flat penalty timer. ``closed`` serves
normally; a transport failure, torn response, hung probe or drain refusal
opens it (``breaker_opens_total``); an ``open`` backend takes no traffic
until a background health probe (the cheap ``ping`` verb, plus the
``stats`` sweep when a fleet sink runs) OBSERVES it answering again —
recovery is observed, never assumed from a timer — which half-opens it;
``half_open`` admits exactly ONE trial request, whose success closes the
breaker (``breaker_closes_total``) and whose failure re-opens it.
Dispatch carries a per-request retry budget with jittered exponential
backoff (``resilience/policy.py``), and **hedged dispatch**: after
``hedge_ms`` of silence from the chosen replica the same request races a
second one, the first complete answer wins, and the loser is torn down
through the ``cancel`` verb — decode is idempotent, so hedging is
loss-free and buys back the straggler tail.

The router is also the fleet's observer (docs/serving.md
"Observability"): it counts dispatches / re-dispatches / penalties /
drain refusals, keeps a bounded per-request dispatch journal, and — when
``--fleet-out`` is given — periodically polls every backend's ``stats``
verb, merging the snapshots ``gang.merge_snapshots``-style (counters
summed, TTFT/ITL pooled count-weighted with the worst replica
attributed, fleet requests-per-chip) into ``FLEET_RECORD_SCHEMA``
records appended to a JSONL sink. Its own front answers two verbs:
``{"verb": "stats"}`` returns a fresh fleet record, and ``{"verb":
"trace", "id": ...}`` merges the router journal with every live
replica's timeline for that id — so a re-dispatched request's full story
(dispatch → drain refusal → re-dispatch → lifecycle) reads as one
time-sorted event list.

This module deliberately imports no jax so ``python -m
fleetx_tpu.serving.router`` starts in milliseconds — the router must come
up before (and outlive) the replicas it fronts. The observability
imports it does take (schema, sinks) are stdlib-only.
"""

from __future__ import annotations

import dataclasses
import json
import queue
import socket
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, Optional

from fleetx_tpu.observability import tsan
from fleetx_tpu.resilience.policy import RetryPolicy

#: seconds between fleet stats sweeps when a fleet sink is configured
DEFAULT_POLL_INTERVAL_S = 1.0

#: fleet records carry the same version as serving snapshots
FLEET_SCHEMA_VERSION = 2

#: breaker states (docs/serving.md "Fault tolerance")
CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

#: router-owned dispatch counters, merged into every fleet record
ROUTER_COUNTERS = ("dispatched_total", "redispatched_total",
                   "penalties_total", "drain_refusals_total",
                   "no_backend_total", "completed_total",
                   "breaker_opens_total", "breaker_closes_total",
                   "hedges_total", "hedge_cancels_total")


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """The ``Serving.router`` YAML block — every knob that used to be a
    module constant, eagerly validated in ``process_serving_config`` and
    forwarded by ``tools/serve.py --router`` (docs/serving.md "Fault
    tolerance")."""

    #: minimum seconds an opened breaker holds before probes may test the
    #: backend again (a supervisor restart needs a moment to rebind)
    penalty_s: float = 1.0
    #: total seconds one accepted request is retried before "no backend"
    dispatch_deadline_s: float = 120.0
    #: timeout for one ping/stats/trace/cancel side-channel round trip
    verb_timeout_s: float = 10.0
    #: per-forward data-request timeout (covers replica queue time)
    request_timeout_s: float = 120.0
    #: milliseconds of primary silence before a hedge fires; 0 disables
    hedge_ms: float = 250.0
    #: dispatch attempts one request may consume across backends
    retry_budget: int = 8
    #: seconds between background health-probe sweeps
    probe_interval_s: float = 0.25
    #: consecutive failures that open a closed breaker
    breaker_threshold: int = 1

    def __post_init__(self):
        for key in ("penalty_s", "dispatch_deadline_s", "verb_timeout_s",
                    "request_timeout_s", "probe_interval_s"):
            assert float(getattr(self, key)) > 0, \
                f"Serving.router.{key} must be > 0"
        assert float(self.hedge_ms) >= 0, \
            "Serving.router.hedge_ms must be >= 0 (0 disables hedging)"
        for key in ("retry_budget", "breaker_threshold"):
            assert int(getattr(self, key)) >= 1, \
                f"Serving.router.{key} must be >= 1"

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "RouterConfig":
        """Build from the YAML block (unknown keys rejected eagerly)."""
        d = dict(d or {})
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        assert not unknown, \
            f"unknown Serving.router keys: {sorted(unknown)}"
        return cls(**{k: v for k, v in d.items() if v is not None})


def _read_line(conn: socket.socket) -> bytes:
    """Read one newline-terminated frame (the shared half of the wire
    protocol — ``serving/server.py`` documents it; this copy keeps the
    router importable without the jax-adjacent server module)."""
    buf = b""
    while not buf.endswith(b"\n"):
        chunk = conn.recv(4096)
        if not chunk:
            break  # EOF mid-frame — caller decides if that is an error
        buf += chunk
    return buf


class Backend:
    """One replica address + its breaker/placement bookkeeping.

    All mutable fields are guarded by the router's placement lock
    (``tsan.lock("router.placement")``) — handler threads, the hedge
    racers and the probe loop all touch them."""

    def __init__(self, host: str, port: int):
        self.addr = (host, int(port))
        self.outstanding = 0
        self.dispatched = 0
        self.failures = 0
        self.state = CLOSED
        self.consecutive_failures = 0
        self.opened_at = 0.0
        # half-open admits exactly ONE in-flight trial request; the flag
        # is set by pick() under the placement lock, so two handler
        # threads racing the same recovering backend cannot both get it
        self.trial_in_flight = False

    def can_accept(self) -> bool:
        """Whether placement may pick this backend right now."""
        if self.state == CLOSED:
            return True
        if self.state == HALF_OPEN:
            return not self.trial_in_flight
        return False


def _addr_str(addr: tuple) -> str:
    """``(host, port)`` → the ``host:port`` replica label fleet records
    and traces attribute to."""
    return f"{addr[0]}:{addr[1]}"


class RequestJournal:
    """Bounded request-id → router-side dispatch events.

    The router's half of a request's merged trace: which backend each
    attempt went to, drain refusals, transport retries, completion.
    Insertion-ordered eviction over ``max_requests`` ids (the flight-ring
    stance), each id's event list itself a bounded deque.
    """

    def __init__(self, max_requests: int = 1024,
                 events_per_request: int = 64):
        self.max_requests = max(int(max_requests), 1)
        self.events_per_request = max(int(events_per_request), 8)
        self._lock = tsan.lock("router.journal")
        self._events: "OrderedDict[str, deque]" = OrderedDict()

    def note(self, rid, name: str, **data) -> None:
        """Append one router event for ``rid`` (None ids are unjournaled:
        the reply still reaches the client, there is just no trace key)."""
        if rid is None:
            return
        evt = {**data, "t": time.time(), "name": name, "source": "router"}
        with self._lock:
            evts = self._events.get(str(rid))
            if evts is None:
                evts = deque(maxlen=self.events_per_request)
                self._events[str(rid)] = evts
                while len(self._events) > self.max_requests:
                    self._events.popitem(last=False)
            evts.append(evt)

    def events(self, rid) -> list:
        """Copy of one id's journal (empty list when unknown/evicted)."""
        with self._lock:
            return list(self._events.get(str(rid)) or ())


def merge_fleet_snapshots(snaps: Dict[str, dict], replicas_total: int,
                          router_counters: Optional[dict] = None,
                          breakers: Optional[dict] = None) -> dict:
    """N per-replica ``serving_snapshot()`` dicts → one fleet record.

    The serving-side twin of ``observability/gang.py:_merge_window``:
    monotonic counters are summed, the TTFT/ITL histogram summaries are
    pooled count-weighted (fleet mean) with the tail taken from — and
    attributed to — the worst replica, occupancy is averaged AND max'd
    with attribution, and requests-per-chip divides fleet completions by
    fleet chips. ``snaps`` maps replica label → snapshot; replicas that
    failed to report simply aren't in it (``replicas_reported`` records
    the actual coverage). Gauges that are null on a replica (scheduler
    gauges "unavailable") contribute nothing rather than a fake zero.
    The shape is ``observability/schema.py:FLEET_RECORD_SCHEMA``.
    """
    replicas = sorted(snaps)

    def _sum_int(key: str) -> int:
        return int(sum(int(snaps[r].get(key) or 0) for r in replicas))

    def _present(key: str) -> Dict[str, float]:
        return {r: snaps[r][key] for r in replicas
                if isinstance(snaps[r].get(key), (int, float))
                and not isinstance(snaps[r].get(key), bool)}

    record: dict = {
        "ts": max([float(snaps[r].get("ts") or 0.0) for r in replicas],
                  default=time.time()),
        "scope": "fleet",
        "schema_version": FLEET_SCHEMA_VERSION,
        "replicas_total": int(replicas_total),
        "replicas_reported": len(replicas),
        "requests_admitted": _sum_int("requests_admitted"),
        "requests_completed": _sum_int("requests_completed"),
        "requests_refused": _sum_int("requests_refused"),
        "deadline_sheds": _sum_int("deadline_sheds"),
        "tokens_total": _sum_int("tokens_total"),
        "tokens_per_sec": sum(_present("tokens_per_sec").values())
        if replicas else None,
    }
    chips = sum(int(snaps[r].get("chips") or 1) for r in replicas)
    record["chips_total"] = chips
    record["requests_per_chip"] = \
        (record["requests_completed"] / chips) if chips else None
    qd = _present("queue_depth")
    record["queue_depth"] = int(sum(qd.values())) if qd else None
    ar = _present("active_requests")
    record["active_requests"] = int(sum(ar.values())) if ar else None
    occ = _present("page_occupancy")
    if occ:
        record["page_occupancy_mean"] = sum(occ.values()) / len(occ)
        worst = max(occ, key=lambda r: occ[r])
        record["page_occupancy_max"] = float(occ[worst])
        record["page_occupancy_max_replica"] = worst
    for name in ("ttft", "itl"):
        hists = {r: snaps[r].get(name) or {} for r in replicas}
        counts = {r: int(h.get("count") or 0) for r, h in hists.items()}
        total = sum(counts.values())
        if not total:
            continue
        record[f"{name}_mean_s"] = sum(
            float(hists[r].get("mean") or 0.0) * counts[r]
            for r in replicas) / total
        worst = max((r for r in replicas if counts[r]),
                    key=lambda r: float(hists[r].get("p99") or 0.0))
        record[f"{name}_p99_s"] = float(hists[worst].get("p99") or 0.0)
        record[f"{name}_p99_replica"] = worst
    att = _present("slo_attainment")
    if att:
        record["slo_attainment"] = min(att.values())
    for name in ROUTER_COUNTERS:
        if router_counters and name in router_counters:
            record[name] = int(router_counters[name])
    if breakers:
        # per-backend breaker states: the drill reads the
        # open→half_open→closed walk straight off the record stream
        record["breakers"] = {str(a): str(s) for a, s in breakers.items()}
    return record


class Router:
    """Breaker-gated least-outstanding front over the replica fleet."""

    def __init__(self, backends: list, host: str = "127.0.0.1",
                 port: int = 0, request_timeout: Optional[float] = None,
                 fleet_out: Optional[str] = None,
                 poll_interval: float = DEFAULT_POLL_INTERVAL_S,
                 config: Optional[RouterConfig] = None):
        self.cfg = config or RouterConfig()
        if request_timeout is not None:  # legacy kwarg wins over the block
            self.cfg = dataclasses.replace(
                self.cfg, request_timeout_s=float(request_timeout))
        self.request_timeout = float(self.cfg.request_timeout_s)
        self.backends = [Backend(h, p) for h, p in backends]
        assert self.backends, "router needs at least one backend"
        self.host = host
        self.port = int(port)
        self.fleet_out = fleet_out
        self.poll_interval = float(poll_interval)
        self._rr = 0
        self._lock = tsan.lock("router.placement")
        self._listener: Optional[socket.socket] = None
        self._stop = threading.Event()
        self.retries = 0
        self.counters = {name: 0 for name in ROUTER_COUNTERS}
        self.journal = RequestJournal()
        self.last_fleet: Optional[dict] = None
        self._fleet_sink = None
        # the all-breakers-open wait: jittered exponential backoff
        # (resilience/policy.py) in place of the old fixed 50 ms spin —
        # a thundering herd of handler threads de-synchronises instead of
        # hammering pick() in lockstep
        self._spin = RetryPolicy(max_attempts=1_000_000, backoff_s=0.02,
                                 max_backoff_s=max(self.cfg.penalty_s, 0.1),
                                 jitter=0.5)

    def _count(self, name: str) -> None:
        with self._lock:
            self.counters[name] += 1

    def router_counters(self) -> dict:
        """Copy of the dispatch counters (merged into fleet records)."""
        with self._lock:
            return dict(self.counters)

    def breaker_states(self) -> dict:
        """``addr → closed|open|half_open`` snapshot (fleet records)."""
        with self._lock:
            return {_addr_str(b.addr): b.state for b in self.backends}

    # ------------------------------------------------------------ placement
    def pick(self, exclude: tuple = ()) -> Optional[Backend]:
        """Least outstanding among accepting backends, round-robin ties;
        None when every breaker is open (or holds an in-flight trial).
        A half-open choice takes its single trial slot atomically here,
        under the placement lock."""
        with self._lock:
            avail = [b for b in self.backends
                     if b not in exclude and b.can_accept()]
            if not avail:
                return None
            best = min(b.outstanding for b in avail)
            tied = [b for b in avail if b.outstanding == best]
            choice = tied[self._rr % len(tied)]
            self._rr += 1
            choice.outstanding += 1
            choice.dispatched += 1
            if choice.state == HALF_OPEN:
                choice.trial_in_flight = True
            return choice

    def _release(self, backend: Backend) -> None:
        with self._lock:
            backend.outstanding = max(backend.outstanding - 1, 0)

    def _breaker_failure(self, backend: Backend) -> None:
        """One observed failure (transport, torn line, drain refusal,
        hung/failed probe): open the breaker once the threshold is hit; a
        failed half-open trial goes straight back to open."""
        with self._lock:
            backend.failures += 1
            backend.consecutive_failures += 1
            if backend.state == HALF_OPEN:
                backend.state = OPEN
                backend.opened_at = time.monotonic()
                backend.trial_in_flight = False
                self.counters["breaker_opens_total"] += 1
            elif backend.state == CLOSED and backend.consecutive_failures \
                    >= int(self.cfg.breaker_threshold):
                backend.state = OPEN
                backend.opened_at = time.monotonic()
                self.counters["breaker_opens_total"] += 1

    def _note_failure(self, backend: Backend) -> None:
        """A dispatch-path failure: breaker bookkeeping + retry count."""
        self._breaker_failure(backend)
        with self._lock:
            self.retries += 1

    def _note_success(self, backend: Backend) -> None:
        """A completed round trip: reset the failure streak; a half-open
        trial success (or a completion that outlived the breaker opening)
        closes the breaker."""
        with self._lock:
            backend.consecutive_failures = 0
            if backend.state in (HALF_OPEN, OPEN):
                backend.state = CLOSED
                backend.trial_in_flight = False
                self.counters["breaker_closes_total"] += 1

    def _note_probe_success(self, backend: Backend) -> None:
        """A ping/stats answer from an open backend: recovery OBSERVED —
        half-open it so the next request runs the trial."""
        with self._lock:
            backend.consecutive_failures = 0
            if backend.state == OPEN:
                backend.state = HALF_OPEN
                backend.trial_in_flight = False

    # ------------------------------------------------------------- dispatch
    def dispatch(self, payload: dict) -> dict:
        """Forward one request, re-dispatching across backends until a
        replica completes it, the dispatch deadline passes, or the retry
        budget is spent."""
        rid = payload.get("id")
        deadline = time.monotonic() + float(self.cfg.dispatch_deadline_s)
        attempts = 0
        idle_waits = 0
        while time.monotonic() < deadline:
            if attempts >= int(self.cfg.retry_budget):
                # budget spent: a classified refusal beats grinding the
                # fleet with a request that keeps losing backends
                self._count("no_backend_total")
                self.journal.note(rid, "budget_exhausted",
                                  attempts=attempts)
                return {"id": rid,
                        "error": f"retry budget exhausted "
                                 f"({attempts} attempts)"}
            backend = self.pick()
            if backend is None:
                # every breaker open (or trial-busy): wait out the
                # restart window on jittered exponential backoff
                idle_waits += 1
                time.sleep(self._spin.sleep_for(idle_waits))
                continue
            idle_waits = 0
            addr = _addr_str(backend.addr)
            attempts += 1
            self._count("dispatched_total")
            if attempts > 1:
                self._count("redispatched_total")
            self.journal.note(rid, "dispatch", backend=addr,
                              attempt=attempts)
            resp = self._race(backend, payload, rid)
            if resp is None:
                continue  # every racer failed/refused — re-dispatch
            self._count("completed_total")
            self.journal.note(rid, "completed", backend=resp[1],
                              error=resp[0].get("error"))
            return resp[0]
        self._count("no_backend_total")
        self.journal.note(rid, "no_backend")
        return {"id": rid, "error": "no backend available"}

    def _attempt(self, backend: Backend, payload: dict, rid,
                 results: "queue.Queue") -> None:
        """One forward on one backend, outcome classified inline — runs
        on its own thread so a hung racer can't hold the dispatch loop.
        Breaker bookkeeping happens HERE, not in the collector: a loser
        whose transport failure lands after the race concluded (the
        blackholed-replica shape) still opens its breaker."""
        addr = _addr_str(backend.addr)
        try:
            resp = self._forward(backend, payload)
        except (OSError, ValueError):
            # transport failure OR a torn/garbled response line (a
            # replica killed mid-write) — both mean "this backend did
            # not complete the request": open-count and let the
            # collector re-dispatch
            self._note_failure(backend)
            self._count("penalties_total")
            self.journal.note(rid, "transport_retry", backend=addr)
            results.put((backend, None))
        else:
            if isinstance(resp, dict) and resp.get("error") == "draining":
                # graceful reclaim: stop placing onto this backend and
                # retry the request elsewhere, losing nothing
                self._note_failure(backend)
                self._count("penalties_total")
                self._count("drain_refusals_total")
                self.journal.note(rid, "drain_refusal", backend=addr)
                results.put((backend, None))
            else:
                self._note_success(backend)
                results.put((backend, resp))
        finally:
            self._release(backend)

    def _race(self, backend: Backend, payload: dict, rid):
        """One dispatch attempt with hedging: after ``hedge_ms`` of
        silence from ``backend`` the same request races one extra
        replica; first complete answer wins and the loser is torn down
        via the ``cancel`` verb (decode is idempotent — loss-free).
        Returns ``(response, winner_addr)`` or None when every racer
        failed/refused (the caller re-dispatches)."""
        results: "queue.Queue" = queue.Queue()
        racers: list = []

        def launch(b) -> None:
            racers.append(b)
            threading.Thread(target=self._attempt,
                             args=(b, payload, rid, results),
                             daemon=True, name="router-dispatch").start()

        launch(backend)
        hedge_s = float(self.cfg.hedge_ms) / 1000.0
        started = time.monotonic()
        deadline = started + self.request_timeout
        done: list = []
        while len(done) < len(racers):
            now = time.monotonic()
            if now >= deadline:
                return None  # racers still out will teach breakers late
            wait = deadline - now
            if hedge_s > 0 and len(racers) == 1:
                wait = min(wait, max(started + hedge_s - now, 0.0))
            try:
                b, resp = results.get(timeout=max(wait, 0.001))
            except queue.Empty:
                if hedge_s > 0 and len(racers) == 1 \
                        and time.monotonic() - started >= hedge_s:
                    second = self.pick(exclude=tuple(racers))
                    if second is not None:
                        self._count("hedges_total")
                        self.journal.note(rid, "hedge",
                                          backend=_addr_str(second.addr))
                        launch(second)
                continue
            done.append(b)
            if resp is not None:
                for loser in racers:
                    if loser is not b and loser not in done:
                        self._cancel_on(loser, rid)
                return resp, _addr_str(b.addr)
        return None

    def _cancel_on(self, backend: Backend, rid) -> None:
        """Fire-and-forget ``cancel`` to a hedge loser: the replica frees
        the request's slot at its next step boundary. A cancel that loses
        its own race to completion is harmless — decode is idempotent and
        the router already returned the winner."""
        self._count("hedge_cancels_total")
        self.journal.note(rid, "hedge_cancel",
                          backend=_addr_str(backend.addr))
        if rid is None:
            return  # unjournaled request: the replica can't look it up

        def run() -> None:
            try:
                self._ask(backend.addr, {"verb": "cancel", "id": str(rid)})
            except (OSError, ValueError):
                pass  # loser is crashing/hung — its breaker handles it

        threading.Thread(target=run, daemon=True,
                         name="router-hedge-cancel").start()

    def _forward(self, backend: Backend, payload: dict) -> dict:
        return self._ask(backend.addr, payload,
                         timeout=self.request_timeout)

    def _ask(self, addr: tuple, payload: dict,
             timeout: Optional[float] = None) -> dict:
        """One JSON-line round trip (``OSError``/``ValueError`` on
        transport failure or a torn line — callers decide the retry).
        Default timeout is the configured verb timeout."""
        if timeout is None:
            timeout = float(self.cfg.verb_timeout_s)
        with socket.create_connection(addr, timeout=timeout) as conn:
            conn.sendall((json.dumps(payload) + "\n").encode("utf-8"))
            conn.settimeout(timeout)
            buf = _read_line(conn)
        if not buf.strip():
            raise ConnectionError(f"empty response from {addr}")
        # a torn line (replica died mid-write) raises ValueError → retry
        return json.loads(buf.decode("utf-8"))

    # --------------------------------------------------------------- probes
    def probe_once(self) -> None:
        """One health sweep: ``ping`` every backend. The replica answers
        ping on its handler thread — never queued behind decode — so a
        busy replica stays closed while a hung/blackholed one fails the
        probe and opens WITHOUT having to burn a live request. An open
        backend past its ``penalty_s`` holdoff that answers again is
        half-opened: recovery observed, never assumed from a timer."""
        now = time.monotonic()
        for backend in self.backends:
            with self._lock:
                state = backend.state
                opened_at = backend.opened_at
            if state == OPEN and \
                    now - opened_at < float(self.cfg.penalty_s):
                continue  # holdoff: a supervisor restart needs a moment
            try:
                resp = self._ask(backend.addr, {"verb": "ping"})
            except (OSError, ValueError):
                self._breaker_failure(backend)
                continue
            if isinstance(resp, dict) and resp.get("ok") is True \
                    and not resp.get("draining"):
                self._note_probe_success(backend)
            else:
                self._breaker_failure(backend)

    def _probe_loop(self) -> None:
        while not self._stop.wait(float(self.cfg.probe_interval_s)):
            self.probe_once()

    # --------------------------------------------------------------- verbs
    def poll_fleet(self) -> dict:
        """One ``stats`` sweep over the backends → a merged fleet record.

        Partial coverage is tolerated by construction: a draining or
        crashed replica just doesn't report this window, and
        ``replicas_reported`` says so.
        """
        snaps: Dict[str, dict] = {}
        for backend in self.backends:
            addr = _addr_str(backend.addr)
            try:
                resp = self._ask(backend.addr, {"verb": "stats"})
            except (OSError, ValueError):
                continue
            if not isinstance(resp, dict) or resp.get("error"):
                continue
            snaps[addr] = resp
            # a stats answer is as good as a ping: recovery observed
            self._note_probe_success(backend)
        record = merge_fleet_snapshots(
            snaps, replicas_total=len(self.backends),
            router_counters=self.router_counters(),
            breakers=self.breaker_states())
        self.last_fleet = record
        return record

    def trace(self, rid: str) -> dict:
        """Merge the router journal with every live replica's timeline
        for one id, time-sorted — the fleet view of where the request's
        latency went, drain refusals and re-dispatches included."""
        events = self.journal.events(rid)
        sources = ["router"] if events else []
        attribution = None
        for backend in self.backends:
            try:
                resp = self._ask(backend.addr,
                                 {"verb": "trace", "id": rid})
            except (OSError, ValueError):
                continue  # draining/crashed replica: its half is gone
            if resp.get("error") or not isinstance(resp.get("events"),
                                                   list):
                continue
            addr = _addr_str(backend.addr)
            events.extend({**e, "source": addr} for e in resp["events"])
            sources.append(addr)
            if isinstance(resp.get("attribution"), dict):
                attribution = resp["attribution"]
        if not events:
            return {"id": rid, "error": "unknown request id"}
        events.sort(key=lambda e: e.get("t") or 0.0)
        out = {"id": rid, "events": events, "sources": sources}
        if attribution is not None:
            out["attribution"] = attribution
        return out

    def _poll_loop(self) -> None:
        from fleetx_tpu.observability.schema import validate_fleet_record

        while not self._stop.wait(self.poll_interval):
            record = self.poll_fleet()
            problems = validate_fleet_record(record)
            if problems:  # a merge bug must not poison the JSONL stream
                print(f"[router] dropping invalid fleet record: "
                      f"{problems}", flush=True)
                continue
            with self._lock:  # close() swaps the sink out under the lock
                sink = self._fleet_sink
            if sink is not None:
                try:
                    sink.emit(record)
                except (OSError, ValueError):
                    pass  # sink closed mid-shutdown — record is dropped

    # -------------------------------------------------------------- serving
    def start(self) -> int:
        """Bind the front socket + accept thread; returns the bound port."""
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self._listener.listen(128)
        self.port = self._listener.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="router-accept").start()
        # breakers need probes to observe recovery (and to catch a
        # blackholed replica before it eats a live request) — the sweep
        # runs for every started router, fleet sink or not
        threading.Thread(target=self._probe_loop, daemon=True,
                         name="router-health-probe").start()
        if self.fleet_out:
            # stdlib-only sink reuse (sinks.py imports jax lazily now):
            # the fleet stream is line-buffered JSONL like every other
            from fleetx_tpu.observability.sinks import JsonlSink

            self._fleet_sink = JsonlSink(self.fleet_out)
            threading.Thread(target=self._poll_loop, daemon=True,
                             name="router-fleet-poll").start()
        return self.port

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(self.request_timeout)
            buf = _read_line(conn)
            if not buf.strip():
                return
            payload = json.loads(buf.decode("utf-8"))
            verb = payload.get("verb") if isinstance(payload, dict) \
                else None
            if verb == "stats":
                resp = self.poll_fleet()
            elif verb == "trace":
                resp = self.trace(str(payload.get("id")))
            else:
                resp = self.dispatch(payload)
            conn.sendall((json.dumps(resp) + "\n").encode("utf-8"))
        except (OSError, ValueError):
            pass  # client went away / bad JSON — nothing to answer
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        """Tear down the front listener and the fleet sink."""
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._lock:  # the poll loop reads the sink under the lock
            sink, self._fleet_sink = self._fleet_sink, None
        if sink is not None:
            try:
                sink.close()
            except OSError:
                pass


def main(argv=None) -> int:
    """``python -m fleetx_tpu.serving.router --port P --backends h:p,h:p``."""
    import argparse

    ap = argparse.ArgumentParser(description="fleetx serving router")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--backends", required=True,
                    help="comma-separated host:port replica list")
    ap.add_argument("--fleet-out", default=None,
                    help="append merged fleet records (JSONL, "
                         "FLEET_RECORD_SCHEMA) to this path")
    ap.add_argument("--poll-interval", type=float,
                    default=DEFAULT_POLL_INTERVAL_S,
                    help="seconds between backend stats sweeps")
    ap.add_argument("--router-config", default=None,
                    help="JSON dict of Serving.router knobs "
                         "(RouterConfig fields — tools/serve.py "
                         "forwards the YAML block this way)")
    args = ap.parse_args(argv)
    backends = []
    for spec in args.backends.split(","):
        h, _, p = spec.strip().rpartition(":")
        backends.append((h or "127.0.0.1", int(p)))
    config = RouterConfig.from_dict(json.loads(args.router_config)) \
        if args.router_config else None
    router = Router(backends, host=args.host, port=args.port,
                    fleet_out=args.fleet_out,
                    poll_interval=args.poll_interval,
                    config=config)
    port = router.start()
    print(f"[router] listening on {args.host}:{port} over "
          f"{len(backends)} backend(s)"
          + (f", fleet records → {args.fleet_out}" if args.fleet_out
             else ""), flush=True)
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        router.close()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
