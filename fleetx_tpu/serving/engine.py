"""Continuous-batching serving engine over the paged KV cache.

One ``ServingEngine`` owns the device state (params + page pools + the two
jitted step programs from ``serving/decode.py``) and the host state (slot
table, block tables, page allocator, request queues). The scheduler runs
the vLLM-style loop, one ``step()`` per iteration:

1. **admit** — waiting requests take a free decode slot + a **lazy** page
   grant: the prompt's pages plus ``alloc_watermark`` headroom pages
   (vLLM-style; ``lazy_alloc: false`` restores the old reserve-up-front
   ``ceil((prompt + max_new) / page_size)`` for A/B measurement).
   Requests the pool could NEVER hold are refused at ``submit`` (OOM
   admission refusal), requests that merely don't fit *right now* wait;
2. **prefill** — ONE chunk (``prefill_chunk`` tokens) of the oldest
   prefilling request is forwarded; long prompts therefore spread over
   several steps instead of stalling the decode batch, and the final
   chunk's logits yield the request's first token (TTFT);
3. **decode** — one token for every RUNNING slot in a single static-shape
   step; each running request's block table grows one page at a time as
   its length crosses page boundaries, and when the pool runs dry the
   YOUNGEST live request is **preempted**: pages freed, state reset,
   re-enqueued at the head of the admission queue (decode is idempotent —
   the re-run regenerates the same greedy tokens, the loss-free-recovery
   property the router's re-dispatch already relies on). New requests
   join at the next step boundary, finished ones (eos /
   ``max_new_tokens``) free their pages and leave — no retrace in any
   direction.

Telemetry rides the PR 1 metrics registry (``serving_ttft`` /
``serving_inter_token`` histograms; queue-depth / active-request /
page-occupancy gauges), serving events land in the PR 8 flight ring, and
``serving_snapshot()`` emits the record shape
``observability/schema.py:SERVING_RECORD_SCHEMA`` validates.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Optional

import jax
import numpy as np

# the serving engine is a sharded path (pool over fsdp/tensor), and the
# mesh substrate pins jax_threefry_partitionable at import — BEFORE any
# seeded param init, so a replica's init matches the trainer's and every
# sibling replica's regardless of which modules loaded first
# (parallel/mesh.py documents the layout-variance this prevents)
import fleetx_tpu.parallel.mesh  # noqa: F401  (imported for its config pin)
from fleetx_tpu.observability import flight, tsan
from fleetx_tpu.observability.flight import EventRing
from fleetx_tpu.observability.metrics import get_registry
from fleetx_tpu.observability.slo import SLORegistry
from fleetx_tpu.serving.decode import (SamplingParams, make_step_fns,
                                       paged_kernel_enabled)
from fleetx_tpu.serving.paged_cache import (NULL_PAGE, PageAllocator,
                                            init_pool, pool_shardings)
from fleetx_tpu.utils.log import logger

#: request lifecycle states
WAITING, PREFILL, RUNNING, FINISHED, REFUSED = (
    "waiting", "prefill", "running", "finished", "refused")


@dataclasses.dataclass
class ServingConfig:
    """The ``Serving:`` YAML section (docs/serving.md "Sizing the pool")."""

    max_batch: int = 8          # decode slots (static batch dim)
    page_size: int = 16         # tokens per KV page
    num_pages: int = 64         # pool pages INCLUDING the reserved null page
    max_seq_len: int = 0        # 0 → model max_position_embeddings
    prefill_chunk: int = 32     # prompt tokens forwarded per step
    quantize_decode: bool = False  # int8-act decode (Quantization bits)
    # decode attention path: when True AND ``ops/paged_attention.py``'s
    # support predicates admit this (head geometry, VMEM tile budget,
    # pool divisibility on a sharded mesh), decode runs the in-kernel
    # Pallas paged attention — no ``[B, pages*page_size]`` gather
    # materialization. Falls back to the gather path otherwise. The
    # choice is made ONCE at engine construction so the jit cache stays
    # pinned at one decode program (the no-retrace contract).
    paged_kernel: bool = True
    # page lifecycle: True (default) admits on prompt pages +
    # ``alloc_watermark`` headroom and grows page-by-page during decode,
    # preempting the youngest request when the pool runs dry; False
    # restores reserve-up-front (``prompt + max_new`` pages at admission)
    # for A/B measurement
    lazy_alloc: bool = True
    alloc_watermark: int = 1    # headroom pages granted at lazy admission
    # checkpoint directory to restore params from (tools/serve.py feeds it
    # through the PR 7 integrity-verified loader, restoring each leaf
    # DIRECTLY onto its registry sharding when the replica runs a mesh);
    # None = seeded init
    ckpt_dir: Optional[str] = None
    # LoRA adapter artifact directory (finetune/checkpoint.py): verified
    # against the base weights + registry fingerprint, then merged — the
    # decode programs run the fine-tuned weights at zero adapter cost
    # (docs/finetune.md); requires ckpt_dir
    adapter_dir: Optional[str] = None
    # per-request lifecycle tracing (docs/serving.md "Observability"):
    # how many finished/refused timelines stay retrievable behind the
    # ``trace`` verb, and the per-timeline event-ring capacity
    trace_requests: int = 256
    trace_events: int = 128
    # declarative SLO targets (observability/slo.py) — the ``Serving.slo``
    # YAML block; None disables SLO evaluation entirely
    slo: Optional[dict] = None
    # admission-queue bound (docs/serving.md "Fault tolerance"): submissions
    # past this many waiting requests are refused ``overloaded`` with a
    # ``retry_after_s`` hint instead of queueing unboundedly; 0 = unbounded
    max_queue: int = 256
    # router behaviour block (``Serving.router``) — consumed by
    # ``serving/router.py``, validated eagerly in ``process_serving_config``
    # and forwarded by ``tools/serve.py --router``; the engine itself
    # never reads it
    router: Optional[dict] = None

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "ServingConfig":
        """Build from a YAML ``Serving`` section (unknown keys rejected)."""
        known = {f.name for f in dataclasses.fields(cls)}
        d = dict(d or {})
        unknown = set(d) - known
        assert not unknown, f"unknown Serving config keys: {sorted(unknown)}"
        return cls(**{k: v for k, v in d.items() if v is not None})


@dataclasses.dataclass
class ServingRequest:
    """One in-flight generation request and its bookkeeping."""

    id: str
    prompt: list
    max_new_tokens: int
    callback: Optional[Callable] = None
    state: str = WAITING
    slot: int = -1
    pages: list = dataclasses.field(default_factory=list)
    prefill_pos: int = 0
    tokens: list = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    submitted_at: float = 0.0
    first_token_at: Optional[float] = None
    last_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    # admission recency: monotonically minted at every (re-)admission —
    # the preemption policy's youngest-first ordering key
    admit_seq: int = -1
    preemptions: int = 0
    # client deadline (seconds from submission); None = no deadline. An
    # admission-time refusal classifies it (``overloaded``/``unmeetable``)
    # and fills ``retry_after_s``; an in-flight expiry sheds the request
    # at the next decode-tick boundary (``deadline_shed``)
    deadline_s: Optional[float] = None
    retry_after_s: Optional[float] = None

    @property
    def ttft_s(self) -> Optional[float]:
        """Seconds from submission to the first generated token."""
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at


#: lifecycle event taxonomy (docs/serving.md "Observability") — the order
#: a healthy request walks them; ``refused`` replaces the admitted→finished
#: span for drain/OOM refusals, ``drain`` marks a replica preemption
#: landing while the request was live, ``page_grow`` stamps each lazy
#: block-table extension, and ``preempted`` marks a pool-pressure swap-out
#: (the request loops back to ``admitted`` afterwards)
TIMELINE_EVENTS = ("queued", "admitted", "prefill_chunk", "first_token",
                   "decode_tick", "page_grow", "preempted", "finished",
                   "refused", "drain", "deadline_shed")

#: milestone events whose first timestamp is pinned outside the ring so
#: attribution survives decode-tick eviction on long generations
_MILESTONES = ("queued", "admitted", "first_token", "finished", "refused")


class RequestTimeline:
    """One request's bounded lifecycle event ring + derived attribution.

    Events ride an ``observability/flight.py``-style ``EventRing``: a
    long decode drops its oldest ticks (counted, never silent) while the
    milestone timestamps are pinned on the object, so the queue/prefill/
    decode decomposition stays exact however many events fell off.
    """

    def __init__(self, rid: str, capacity: int = 128):
        self.id = str(rid)
        self.ring = EventRing(capacity)
        self.state = "open"  # open | finished | refused
        self._marks: dict = {}
        self._pages = 0
        self._chunks = 0
        self._ticks = 0

    def note(self, name: str, **data: Any) -> None:
        """Append one wall-clock-stamped lifecycle event."""
        evt = {**data, "t": time.time(), "name": name}
        if name in _MILESTONES and name not in self._marks:
            self._marks[name] = evt["t"]
            if name == "admitted":
                self._pages = int(data.get("pages") or 0)
        if name == "prefill_chunk":
            self._chunks += 1
        elif name == "decode_tick":
            self._ticks += 1
        self.ring.append(evt)

    def events(self) -> list:
        """Snapshot of the event ring, oldest first."""
        return self.ring.snapshot()

    def attribution(self) -> dict:
        """Per-phase latency decomposition from the milestone timestamps.

        ``queue_s`` (queued→admitted) + ``prefill_s`` (admitted→first
        token) = ``ttft_s``, then ``decode_s`` (first token→finished) —
        the request-path analogue of ``perf.py``'s step-time
        decomposition: TTFT regressions name their phase. Spans whose
        endpoints haven't happened are None, never a fake zero.
        """
        t = self._marks

        def span(a: str, b: str) -> Optional[float]:
            return (t[b] - t[a]) if a in t and b in t else None

        total = span("queued", "finished")
        if total is None:
            total = span("queued", "refused")
        return {
            "queue_s": span("queued", "admitted"),
            "prefill_s": span("admitted", "first_token"),
            "decode_s": span("first_token", "finished"),
            "ttft_s": span("queued", "first_token"),
            "total_s": total,
            "pages": self._pages,
            "prefill_chunks": self._chunks,
            "decode_ticks": self._ticks,
        }

    def to_dict(self) -> dict:
        """The ``trace`` verb's JSON payload for this request."""
        return {
            "id": self.id, "state": self.state, "events": self.events(),
            "events_total": self.ring.total,
            "events_dropped": self.ring.dropped,
            "attribution": self.attribution(),
        }


class TimelineStore:
    """Bounded id → timeline map behind the ``trace`` verb.

    The engine thread writes; connection-handler threads read
    concurrently, so every map mutation holds the lock (the per-timeline
    rings carry their own). Finished timelines stay retrievable until
    ``max_requests`` newer requests evict them, insertion-ordered — the
    flight-ring stance applied per request.
    """

    def __init__(self, max_requests: int = 256,
                 events_per_request: int = 128):
        self.max_requests = max(int(max_requests), 1)
        self.events_per_request = max(int(events_per_request), 8)
        self._lock = tsan.lock("serving.timelines")
        self._timelines: "OrderedDict[str, RequestTimeline]" = OrderedDict()

    def open(self, rid: str) -> RequestTimeline:
        """Get-or-create the timeline for one request id."""
        with self._lock:
            tl = self._timelines.get(str(rid))
            if tl is None:
                tl = RequestTimeline(rid, self.events_per_request)
                self._timelines[str(rid)] = tl
                while len(self._timelines) > self.max_requests:
                    self._timelines.popitem(last=False)
            return tl

    def get(self, rid: str) -> Optional[RequestTimeline]:
        """The timeline for ``rid`` (None when unknown or evicted)."""
        with self._lock:
            return self._timelines.get(str(rid))

    def note(self, rid: str, name: str, **data: Any) -> None:
        """Append one event onto an existing timeline (no-op on unknown
        ids — a timeline evicted mid-flight must not resurrect empty)."""
        tl = self.get(rid)
        if tl is not None:
            tl.note(name, **data)

    def live(self) -> list:
        """Every still-open timeline (the drain/crash dump set)."""
        with self._lock:
            return [tl for tl in self._timelines.values()
                    if tl.state == "open"]


class ServingEngine:
    """Request-level decode runtime (see module docstring for the loop)."""

    def __init__(self, model_cfg: Any, params: Any,
                 serving: Optional[ServingConfig] = None,
                 sampling: Optional[SamplingParams] = None,
                 eos_token_id: int = 50256, mesh: Optional[Any] = None,
                 seed: int = 0):
        from flax.core import meta

        self.cfg = model_cfg
        self.serving = serving or ServingConfig()
        self.sampling = sampling or SamplingParams()
        self.eos_token_id = int(eos_token_id)
        self.mesh = mesh
        sc = self.serving
        self.max_seq_len = int(sc.max_seq_len) or model_cfg.max_position_embeddings
        assert self.max_seq_len <= model_cfg.max_position_embeddings, \
            "Serving.max_seq_len exceeds the model's position table"
        self.pages_per_req = -(-self.max_seq_len // sc.page_size)

        self.params = meta.unbox(params)
        self.allocator = PageAllocator(sc.num_pages, sc.page_size)
        self.pool_k, self.pool_v = init_pool(model_cfg, sc.num_pages,
                                             sc.page_size)
        sharding = None
        if mesh is not None:
            sharding = pool_shardings(mesh)
            self.pool_k = jax.device_put(self.pool_k, sharding)
            self.pool_v = jax.device_put(self.pool_v, sharding)
        # kernel-vs-gather is decided HERE, once: the support predicates
        # are static functions of the config/pool/mesh, so the decode
        # program compiles exactly one attention path and the jit cache
        # stays pinned at one entry (test_serving pins this)
        self.paged_kernel_active = bool(sc.paged_kernel) and \
            paged_kernel_enabled(
                model_cfg, page_size=sc.page_size, num_pages=sc.num_pages,
                pages_per_req=self.pages_per_req, pool_sharding=sharding)
        self._fns = make_step_fns(
            model_cfg, max_batch=sc.max_batch,
            pages_per_req=self.pages_per_req,
            prefill_chunk=sc.prefill_chunk, sampling=self.sampling,
            quantize=bool(sc.quantize_decode), pool_sharding=sharding,
            paged_kernel=self.paged_kernel_active)

        # host-side scheduler state
        self._slots: list = [None] * sc.max_batch
        self._block_tables = np.full((sc.max_batch, self.pages_per_req),
                                     NULL_PAGE, np.int32)
        self._lens = np.full((sc.max_batch,), -1, np.int32)
        self._last_tokens = np.zeros((sc.max_batch,), np.int32)
        self._waiting: deque = deque()
        self._prefilling: deque = deque()
        self._rng = jax.random.PRNGKey(int(seed))
        self.draining = False
        self.steps = 0
        self._started_at = time.monotonic()
        self.metrics = get_registry()
        # monotonic id mint: never reset (reset_stats() zeroing the
        # request counter used to recycle ids across bench windows,
        # silently merging two requests' timelines and router bookkeeping)
        self._rid_counter = 0
        # admission recency mint for the preempt-youngest policy; never
        # reset, so ordering survives bench-window stat resets too
        self._admit_seq = 0
        # engine-local gauge freshness: the registry is process-global, so
        # a prior engine's gauge values must not read as THIS engine's
        self._gauges_current = False
        self.timelines = TimelineStore(sc.trace_requests, sc.trace_events)
        self.slo = SLORegistry.from_config(sc.slo, registry=self.metrics)
        # chips this replica occupies: its mesh size, or one device for an
        # unsharded replica — the denominator of requests-per-chip
        self.n_chips = int(mesh.size) if mesh is not None else 1
        # scheduler state is engine-thread-confined by design: handler
        # threads must go through the server's submission queue, never
        # call submit()/step() directly. FLEETX_TSAN=1 enforces that.
        tsan.register_object(self, "serving-engine")
        logger.info(
            "serving engine: max_batch=%d pages=%d x %d tokens "
            "(capacity %d token slots/layer), prefill_chunk=%d, "
            "quantize_decode=%s, decode=%s, alloc=%s",
            sc.max_batch, self.allocator.usable_pages,
            sc.page_size, self.allocator.usable_pages * sc.page_size,
            sc.prefill_chunk, bool(sc.quantize_decode),
            "paged_kernel" if self.paged_kernel_active else "gather",
            "lazy" if sc.lazy_alloc else "reserve")

    # ------------------------------------------------------------ submission
    def submit(self, prompt: list, max_new_tokens: int,
               request_id: Optional[str] = None,
               callback: Optional[Callable] = None,
               deadline_s: Optional[float] = None) -> ServingRequest:
        """Queue one request; refusals (drain / permanent OOM / deadline)
        come back with ``state == REFUSED`` and ``error`` set, never
        queued. ``deadline_s`` makes admission deadline-aware: a request
        whose projected completion exceeds its deadline is refused up
        front — ``unmeetable`` (its own service time alone blows the
        deadline; retrying won't help until the deadline grows) or
        ``overloaded`` (the queue ahead of it does; ``retry_after_s``
        names the projected drain)."""
        tsan.note_access(self, "submit")
        rid = request_id if request_id is not None \
            else f"req{self._rid_counter}"
        self._rid_counter += 1
        req = ServingRequest(id=str(rid), prompt=[int(t) for t in prompt],
                             max_new_tokens=int(max_new_tokens),
                             callback=callback, submitted_at=time.monotonic(),
                             deadline_s=(float(deadline_s)
                                         if deadline_s is not None else None))
        self.metrics.counter("serving_requests_total").inc()
        self.timelines.open(req.id).note(
            "queued", prompt_len=len(req.prompt),
            max_new=req.max_new_tokens)
        need_tokens = len(req.prompt) + req.max_new_tokens
        need_pages = self.allocator.pages_needed(need_tokens)
        if self.draining:
            return self._refuse(req, "draining")
        if not req.prompt or need_tokens > self.max_seq_len or \
                not self.allocator.fits_ever(need_pages):
            return self._refuse(
                req, f"oom: request needs {need_pages} pages / "
                     f"{need_tokens} tokens; pool holds "
                     f"{self.allocator.usable_pages} pages of "
                     f"{self.allocator.page_size}")
        max_queue = int(self.serving.max_queue or 0)
        if max_queue and len(self._waiting) >= max_queue:
            service, eta = self.projected_completion_s(
                len(req.prompt), req.max_new_tokens)
            req.retry_after_s = round(max(
                (eta or 0.0) - (service or 0.0), 0.05), 3)
            self.metrics.counter("serving_refusals_overloaded").inc()
            return self._refuse(
                req, f"overloaded: admission queue full "
                     f"({len(self._waiting)} >= {max_queue})")
        if req.deadline_s is not None:
            service, eta = self.projected_completion_s(
                len(req.prompt), req.max_new_tokens)
            if service is not None and service > req.deadline_s:
                req.retry_after_s = round(service, 3)
                self.metrics.counter("serving_refusals_unmeetable").inc()
                return self._refuse(
                    req, f"unmeetable: projected service {service:.3f}s "
                         f"exceeds deadline {req.deadline_s:.3f}s")
            if eta is not None and eta > req.deadline_s:
                req.retry_after_s = round(eta - service, 3)
                self.metrics.counter("serving_refusals_overloaded").inc()
                return self._refuse(
                    req, f"overloaded: projected completion {eta:.3f}s "
                         f"(queue {len(self._waiting)}) exceeds deadline "
                         f"{req.deadline_s:.3f}s")
        self._waiting.append(req)
        flight.note("serving", "submit", id=req.id,
                    prompt_len=len(req.prompt))
        return req

    def _measured_mean(self, name: str) -> Optional[float]:
        """Mean of a registry histogram, None before any observation."""
        h = self.metrics.histogram(name)
        count = int(getattr(h, "total_count", 0) or 0)
        if count <= 0:
            return None
        return float(h.total_sum) / count

    def projected_completion_s(self, prompt_len: int, max_new: int):
        """``(service_s, eta_s)`` estimate for a fresh submission.

        ``service_s`` is the request's own cost — prefill chunks at the
        measured mean ``serving_prefill_step`` plus ``max_new`` tokens at
        the measured mean inter-token latency. ``eta_s`` adds the queue
        ahead of it: every waiting/prefilling request's own service
        estimate, divided by the decode batch width (decode is batched,
        so queued work drains ``max_batch``-wide, not serially). Both are
        None until the engine has measured at least one prefill chunk and
        one decode tick — admission never refuses on guesswork."""
        pf = self._measured_mean("serving_prefill_step")
        itl = self._measured_mean("serving_inter_token")
        if pf is None or itl is None:
            return None, None
        chunk = max(int(self.serving.prefill_chunk), 1)

        def est(plen: int, new: int) -> float:
            return -(-plen // chunk) * pf + new * itl

        service = est(max(int(prompt_len), 1), max(int(max_new), 1))
        ahead = sum(est(max(len(r.prompt), 1), max(r.max_new_tokens, 1))
                    for r in list(self._waiting) + list(self._prefilling))
        eta = service + ahead / max(int(self.serving.max_batch), 1)
        return service, eta

    def _refuse(self, req: ServingRequest, why: str) -> ServingRequest:
        req.state, req.error = REFUSED, why
        req.finished_at = time.monotonic()
        self.metrics.counter("serving_requests_refused").inc()
        tl = self.timelines.get(req.id)
        if tl is not None:
            tl.note("refused", why=why)
            tl.state = "refused"
        flight.note("serving", "refuse", id=req.id, why=why)
        if req.callback:
            req.callback(req)
        return req

    # -------------------------------------------------------------- schedule
    def _admit(self) -> None:
        """Waiting → prefill while a slot AND a page grant fit (strict
        FIFO: head-of-line blocking keeps admission fair).

        The grant is the admission policy: lazy (default) asks for the
        prompt's pages plus ``alloc_watermark`` headroom — decode grows
        the rest page-by-page in ``_grow_or_preempt`` — while
        ``lazy_alloc: false`` reserves the worst case up front. Both are
        capped at the worst case, so a zero-decode request never
        over-reserves."""
        sc = self.serving
        while self._waiting:
            req = self._waiting[0]
            try:
                slot = self._slots.index(None)
            except ValueError:
                return
            worst = self.allocator.pages_needed(
                len(req.prompt) + req.max_new_tokens)
            if sc.lazy_alloc:
                need = min(self.allocator.pages_needed(len(req.prompt))
                           + max(int(sc.alloc_watermark), 0), worst)
            else:
                need = worst
            pages = self.allocator.alloc(need)
            if pages is None:
                return
            self._waiting.popleft()
            req.state, req.slot, req.pages = PREFILL, slot, pages
            req.admit_seq = self._admit_seq
            self._admit_seq += 1
            self._slots[slot] = req
            self._block_tables[slot] = NULL_PAGE
            self._block_tables[slot, :need] = pages
            self._lens[slot] = -1  # joins the decode batch after prefill
            self._prefilling.append(req)
            self.timelines.note(req.id, "admitted", slot=slot, pages=need,
                                occupancy=self.allocator.occupancy())
            flight.note("serving", "admit", id=req.id, slot=slot,
                        pages=need)

    def _next_rng(self) -> jax.Array:
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _prefill_step(self) -> bool:
        """Forward one chunk of the oldest prefilling request."""
        if not self._prefilling:
            return False
        req = self._prefilling[0]
        sc = self.serving
        pos = req.prefill_pos
        chunk = req.prompt[pos:pos + sc.prefill_chunk]
        n_valid = len(chunk)
        tokens = np.zeros((1, sc.prefill_chunk), np.int32)
        tokens[0, :n_valid] = chunk
        table = self._block_tables[req.slot:req.slot + 1]
        with self.metrics.timer("serving_prefill_step"):
            self.pool_k, self.pool_v, tok, _ = self._fns["prefill"](
                self.params, self.pool_k, self.pool_v, tokens, table,
                np.int32(pos), np.int32(n_valid), self._next_rng())
            req.prefill_pos = pos + n_valid
            self.timelines.note(req.id, "prefill_chunk",
                                chunk=pos // max(sc.prefill_chunk, 1),
                                tokens=n_valid)
            if req.prefill_pos >= len(req.prompt):
                first = int(jax.device_get(tok)[0])
                self._prefilling.popleft()
                now = time.monotonic()
                req.first_token_at = req.last_token_at = now
                self.metrics.histogram("serving_ttft").record(req.ttft_s)
                self.timelines.note(req.id, "first_token", token=first)
                self._emit(req, first)
                if req.state != FINISHED:
                    req.state = RUNNING
                    self._lens[req.slot] = len(req.prompt)
                    self._last_tokens[req.slot] = first
                flight.note("serving", "first_token", id=req.id)
        return True

    def _grow_or_preempt(self) -> None:
        """Extend each RUNNING request's block table to cover the token
        the next decode step will write; when the pool is dry, preempt
        the YOUNGEST live request and retry.

        Preempting youngest (highest ``admit_seq``) keeps the oldest
        request making forward progress, which bounds the scheme: each
        preemption frees at least one page, live requests always hold at
        least one, and the head of the FIFO eventually finishes — no
        livelock. A request can preempt ITSELF (it was the youngest);
        it simply sits out this decode step and re-enters the queue."""
        for req in list(self._slots):
            if req is None or req.state != RUNNING:
                continue  # freed or preempted earlier in this pass
            need = self.allocator.pages_needed(int(self._lens[req.slot]) + 1)
            while len(req.pages) < need:
                got = self.allocator.alloc(1)
                if got is not None:
                    self._block_tables[req.slot, len(req.pages)] = got[0]
                    req.pages.extend(got)
                    self.timelines.note(
                        req.id, "page_grow", pages=len(req.pages),
                        occupancy=self.allocator.occupancy())
                    continue
                victim = self._youngest_live()
                if victim is None:
                    break  # unreachable: req itself is live
                self._preempt(victim)
                if victim is req:
                    break

    def _youngest_live(self) -> Optional[ServingRequest]:
        """The most recently admitted request still holding pages."""
        live = [r for r in self._slots if r is not None]
        return max(live, key=lambda r: r.admit_seq, default=None)

    def _preempt(self, req: ServingRequest) -> None:
        """Swap ``req`` out: free its pages and re-enqueue it at the HEAD
        of the admission queue with all generation state reset — decode
        is deterministic (greedy or seeded), so the re-run regenerates
        the same tokens and the caller never observes the eviction beyond
        latency."""
        tsan.note_access(self, "preempt")
        pages_freed = len(req.pages)
        self.allocator.free(req.pages)
        slot = req.slot
        self._slots[slot] = None
        self._block_tables[slot] = NULL_PAGE
        self._lens[slot] = -1
        self._last_tokens[slot] = 0
        if req in self._prefilling:
            self._prefilling.remove(req)
        req.state, req.slot, req.pages = WAITING, -1, []
        req.prefill_pos = 0
        req.tokens = []
        req.first_token_at = None
        req.last_token_at = None
        req.preemptions += 1
        # head-of-queue re-entry: victims are picked youngest-first, so
        # appendleft keeps the relative admission order among them
        self._waiting.appendleft(req)
        self.metrics.counter("serving_requests_preempted").inc()
        self.timelines.note(req.id, "preempted", pages_freed=pages_freed,
                            occupancy=self.allocator.occupancy(),
                            preemptions=req.preemptions)
        flight.note("serving", "preempt", id=req.id,
                    pages_freed=pages_freed)

    def _shed_expired(self) -> None:
        """Drop every request whose deadline already passed — queued OR
        in-flight — at the decode-tick boundary (the only point where a
        slot can be reclaimed without tearing a step in half). Sheds are
        classified refusals: the caller gets an error response, never
        silence, and the ``serving_deadline_sheds`` counter + the
        ``deadline_shed`` timeline event make every one attributable."""
        now = time.monotonic()

        def expired(r: ServingRequest) -> bool:
            return r.deadline_s is not None and \
                now - r.submitted_at > r.deadline_s

        for req in [r for r in self._waiting if expired(r)]:
            self._waiting.remove(req)
            self._shed(req, now)
        for req in list(self._slots):
            if req is not None and req.state in (PREFILL, RUNNING) \
                    and expired(req):
                self._shed(req, now)

    def _release_slot(self, req: ServingRequest) -> None:
        """Free any slot/pages ``req`` holds (shed/cancel teardown)."""
        if req.slot >= 0:
            self.allocator.free(req.pages)
            slot = req.slot
            self._slots[slot] = None
            self._block_tables[slot] = NULL_PAGE
            self._lens[slot] = -1
            self._last_tokens[slot] = 0
            if req in self._prefilling:
                self._prefilling.remove(req)
        req.slot, req.pages = -1, []

    def _shed(self, req: ServingRequest, now: float) -> None:
        """Refuse one expired request, freeing any slot/pages it holds."""
        tsan.note_access(self, "shed")
        age = now - req.submitted_at
        self._release_slot(req)
        req.state = REFUSED
        req.error = (f"deadline_shed: expired {age:.3f}s into a "
                     f"{req.deadline_s:.3f}s deadline")
        req.finished_at = now
        self.metrics.counter("serving_deadline_sheds").inc()
        self.metrics.counter("serving_requests_refused").inc()
        tl = self.timelines.get(req.id)
        if tl is not None:
            tl.note("deadline_shed", age_s=round(age, 4),
                    deadline_s=req.deadline_s,
                    tokens_dropped=len(req.tokens))
            tl.state = "refused"
        flight.note("serving", "deadline_shed", id=req.id,
                    age_s=round(age, 4), deadline_s=req.deadline_s)
        if req.callback:
            req.callback(req)

    def cancel(self, request_id: str) -> bool:
        """Cancel one queued or in-flight request (the ``cancel`` verb —
        hedged dispatch tears down the losing replica's copy with this).
        Runs on the engine thread via the server's control queue, so the
        teardown lands at a step boundary like every other slot
        transition. Returns False when the id is unknown, already
        finished, or already refused."""
        tsan.note_access(self, "cancel")
        rid = str(request_id)
        req = next((r for r in self._waiting if r.id == rid), None)
        if req is not None:
            self._waiting.remove(req)
        else:
            req = next((r for r in self._slots
                        if r is not None and r.id == rid
                        and r.state in (PREFILL, RUNNING)), None)
        if req is None:
            return False
        self._release_slot(req)
        req.state, req.error = REFUSED, "cancelled"
        req.finished_at = time.monotonic()
        self.metrics.counter("serving_requests_refused").inc()
        tl = self.timelines.get(req.id)
        if tl is not None:
            tl.note("refused", why="cancelled")
            tl.state = "refused"
        flight.note("serving", "cancel", id=req.id)
        if req.callback:
            req.callback(req)
        return True

    def _decode_step(self) -> bool:
        """One token for every RUNNING slot (static batch; masked rows)."""
        self._shed_expired()
        if self.serving.lazy_alloc:
            self._grow_or_preempt()
        running = [r for r in self._slots
                   if r is not None and r.state == RUNNING]
        if not running:
            return False
        with self.metrics.timer("serving_decode_step"):
            self.pool_k, self.pool_v, toks, _ = self._fns["decode"](
                self.params, self.pool_k, self.pool_v, self._last_tokens,
                self._block_tables, self._lens, self._next_rng())
            toks = jax.device_get(toks)
            now = time.monotonic()
            for req in running:
                tok = int(toks[req.slot])
                self._lens[req.slot] += 1  # the step wrote position `lens`
                self.metrics.histogram("serving_inter_token").record(
                    now - req.last_token_at)
                req.last_token_at = now
                self.timelines.note(req.id, "decode_tick",
                                    pos=int(self._lens[req.slot]))
                self._emit(req, tok)
                if req.state != FINISHED:
                    self._last_tokens[req.slot] = tok
        return True

    def _emit(self, req: ServingRequest, token: int) -> None:
        """Record one generated token and finish on eos / length."""
        req.tokens.append(token)
        self.metrics.counter("serving_tokens_total").inc()
        if token == self.eos_token_id or \
                len(req.tokens) >= req.max_new_tokens:
            self._finish(req)

    def _finish(self, req: ServingRequest) -> None:
        req.state = FINISHED
        req.finished_at = time.monotonic()
        self.allocator.free(req.pages)
        slot = req.slot
        self._slots[slot] = None
        self._block_tables[slot] = NULL_PAGE
        self._lens[slot] = -1
        self._last_tokens[slot] = 0
        self.metrics.counter("serving_requests_completed").inc()
        tl = self.timelines.get(req.id)
        if tl is not None:
            tl.note("finished", new_tokens=len(req.tokens),
                    pages_freed=len(req.pages),
                    occupancy=self.allocator.occupancy())
            tl.state = "finished"
        flight.note("serving", "finish", id=req.id,
                    new_tokens=len(req.tokens))
        if req.callback:
            req.callback(req)

    # ------------------------------------------------------------------ loop
    def step(self) -> bool:
        """One scheduler iteration; True when any device work ran."""
        tsan.note_access(self, "step")
        self._admit()
        worked = self._prefill_step()
        worked = self._decode_step() or worked
        if worked:
            self.steps += 1
        self._update_gauges()
        return worked

    def has_work(self) -> bool:
        """Anything queued, prefilling or decoding?"""
        return bool(self._waiting or self._prefilling
                    or any(r is not None for r in self._slots))

    def run_until_drained(self, max_steps: int = 100_000) -> None:
        """Step until every queued request has finished (tests/bench)."""
        steps = 0
        while self.has_work():
            self.step()
            steps += 1
            assert steps < max_steps, "serving loop failed to drain"

    def begin_drain(self) -> None:
        """Stop admitting NEW submissions; everything already queued or in
        flight runs to completion (the graceful-preemption contract)."""
        if not self.draining:
            self.draining = True
            flight.note("serving", "drain",
                        active=sum(r is not None for r in self._slots),
                        queued=len(self._waiting))
            # stamp the preemption onto every live timeline, then spill
            # them into the flight ring: the post-mortem (and the router's
            # merged trace) sees exactly where each request was when the
            # reclaim landed
            for tl in self.timelines.live():
                tl.note("drain")
            self.dump_timelines()
            logger.warning("serving engine draining: finishing %d in-flight "
                           "request(s)", sum(r is not None
                                             for r in self._slots)
                           + len(self._waiting))

    def dump_timelines(self) -> int:
        """Spill every live timeline into the flight ring (crash/drain
        evidence for ``flight.dump``); returns how many were spilled."""
        live = self.timelines.live()
        for tl in live:
            flight.note("serving_timeline", tl.id, state=tl.state,
                        events=tl.events(), dropped=tl.ring.dropped,
                        attribution=tl.attribution())
        return len(live)

    def request_trace(self, rid: str) -> Optional[dict]:
        """The ``trace`` verb's payload for one request id: the bounded
        event timeline + the phase attribution (None when the id is
        unknown or already evicted from the timeline store)."""
        tl = self.timelines.get(rid)
        return tl.to_dict() if tl is not None else None

    # ------------------------------------------------------------- telemetry
    def reset_stats(self) -> None:
        """Zero the serving counters/histograms and restart the throughput
        clock — the bench calls this after its warmup request so compile
        time never pollutes tokens/s or the latency quantiles."""
        for name in ("serving_requests_total", "serving_requests_completed",
                     "serving_requests_refused", "serving_requests_preempted",
                     "serving_tokens_total", "serving_deadline_sheds",
                     "serving_refusals_overloaded",
                     "serving_refusals_unmeetable"):
            self.metrics.counter(name).reset()
        for name in ("serving_ttft", "serving_inter_token",
                     "serving_prefill_step", "serving_decode_step"):
            h = self.metrics.histogram(name)
            h.reset()
            h.total_count = 0
            h.total_sum = 0.0
        self._started_at = time.monotonic()

    def _used_slots(self) -> int:
        """Token positions actually written across live requests."""
        used = int(self._lens[self._lens >= 0].sum())
        used += sum(r.prefill_pos for r in self._prefilling)
        return used

    def _update_gauges(self) -> None:
        self._gauges_current = True
        self.metrics.gauge("serving_queue_depth").set(len(self._waiting))
        self.metrics.gauge("serving_active_requests").set(
            sum(r is not None for r in self._slots))
        self.metrics.gauge("serving_page_occupancy").set(
            self.allocator.occupancy())
        self.metrics.gauge("serving_kv_fragmentation").set(
            self.allocator.internal_fragmentation(self._used_slots()))

    def serving_snapshot(self) -> dict:
        """One JSON-ready record in the ``SERVING_RECORD_SCHEMA`` shape."""
        m = self.metrics
        wall = max(time.monotonic() - self._started_at, 1e-9)
        ttft = m.histogram("serving_ttft").summary()
        itl = m.histogram("serving_inter_token").summary()
        tokens = m.counter("serving_tokens_total").value
        completed = int(m.counter("serving_requests_completed").value)
        if self._gauges_current:
            gauges = {
                "queue_depth": int(m.gauge("serving_queue_depth").value),
                "active_requests": int(
                    m.gauge("serving_active_requests").value),
                "page_occupancy": float(
                    m.gauge("serving_page_occupancy").value),
                "kv_fragmentation": float(
                    m.gauge("serving_kv_fragmentation").value),
                "scheduler_gauges": "ok",
            }
        else:
            # this engine has never stepped: null + an explicit marker
            # (the hbm_stats convention) instead of a fake-zero occupancy
            gauges = {"queue_depth": None, "active_requests": None,
                      "page_occupancy": None, "kv_fragmentation": None,
                      "scheduler_gauges": "unavailable"}
        snap = {
            "ts": time.time(),
            "scope": "serving",
            "schema_version": 2,
            "requests_admitted": int(
                m.counter("serving_requests_total").value
                - m.counter("serving_requests_refused").value),
            "requests_completed": completed,
            "requests_refused": int(
                m.counter("serving_requests_refused").value),
            "requests_preempted": int(
                m.counter("serving_requests_preempted").value),
            "deadline_sheds": int(
                m.counter("serving_deadline_sheds").value),
            "decode_path": ("paged_kernel" if self.paged_kernel_active
                            else "gather"),
            **gauges,
            "tokens_total": int(tokens),
            "tokens_per_sec": tokens / wall,
            "ttft_p50_s": ttft.get("p50"),
            "ttft_p99_s": ttft.get("p99"),
            "itl_p50_s": itl.get("p50"),
            "itl_p99_s": itl.get("p99"),
            # full windowed summaries: the router pools these
            # count-weighted into its fleet record
            "ttft": ttft,
            "itl": itl,
            "chips": int(self.n_chips),
            "requests_per_chip": completed / max(self.n_chips, 1),
        }
        if self.slo is not None:
            snap["slo_attainment"] = self.slo.observe(snap)["attainment"]
        return snap
