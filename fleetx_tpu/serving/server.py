"""One serving replica behind a JSON-lines TCP front, with graceful drain.

Wire protocol (one request per connection, newline-delimited JSON)::

    → {"id": "r1", "prompt": [5, 9, 23], "max_new_tokens": 8,
       "deadline_s": 2.5}                        # deadline optional
    ← {"id": "r1", "tokens": [41, 3, ...], "ttft_s": 0.01, "latency_s": 0.2}
    ← {"id": "r1", "error": "draining"}          # replica is being reclaimed
    ← {"id": "r1", "error": "overloaded: ...", "retry_after_s": 0.8}

Refusals are CLASSIFIED (docs/serving.md "Fault tolerance"): ``draining``
means the replica is being reclaimed (re-dispatch elsewhere),
``overloaded``/``unmeetable`` are deadline-admission verdicts carrying a
``retry_after_s`` hint, and ``deadline_shed``/``cancelled`` end requests
that were already in flight.

Four **verbs** ride the same protocol (docs/serving.md "Observability") —
the router polls the first two, operators ask the third, hedged dispatch
fires the fourth::

    → {"verb": "stats"}                    ← one serving_snapshot() record
    → {"verb": "ping"}                     ← {"ok": true, "draining": false}
                                             (answered on the HANDLER
                                             thread — cheap liveness for
                                             the router's health probes,
                                             never queued behind decode)
    → {"verb": "trace", "id": "r1"}        ← the request's lifecycle
                                             timeline + phase attribution
    → {"verb": "cancel", "id": "r1"}       ← {"id": "r1", "cancelled": true}
                                             (frees the request's slot at
                                             the next step boundary — the
                                             hedge loser's teardown)

The engine loop stays on the caller's (main) thread — connection handler
threads only enqueue submissions (and verb thunks, which the loop services
at every step boundary) and wait on completion events, so all device work
AND all engine-state reads are single-threaded and the PR 4/6
``PreemptionHandler`` can be installed normally. On a latched preemption the replica **drains**: new
requests are answered ``"draining"`` (the router re-dispatches them),
in-flight decodes run to completion, and ``run()`` returns so
``tools/serve.py`` can exit with the preemption code — the supervisor then
treats the reclaim as a clean stop instead of crash-restarting a machine
that is going away.
"""

from __future__ import annotations

import json
import os
import queue
import socket
import threading
import time
from typing import Optional

from fleetx_tpu.observability import flight, tsan
from fleetx_tpu.utils.log import logger

#: per-request completion wait bound (covers queue time under load)
REQUEST_TIMEOUT_S = 300.0


def read_json_line(conn: socket.socket, timeout: float) -> Optional[dict]:
    """Read one newline-terminated JSON object from ``conn`` (None on EOF
    or parse failure)."""
    conn.settimeout(timeout)
    buf = b""
    while not buf.endswith(b"\n"):
        chunk = conn.recv(4096)
        if not chunk:
            break
        buf += chunk
    if not buf.strip():
        return None
    try:
        return json.loads(buf.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return None


def send_json_line(conn: socket.socket, payload: dict) -> None:
    """Write one JSON object + newline."""
    conn.sendall((json.dumps(payload) + "\n").encode("utf-8"))


def request(addr: tuple, payload: dict, timeout: float = 60.0) -> dict:
    """One round trip against a replica/router: connect, send, await the
    response line. Raises ``OSError`` on transport failure — the caller
    (router, tests) decides whether to re-dispatch."""
    with socket.create_connection(addr, timeout=timeout) as conn:
        send_json_line(conn, payload)
        resp = read_json_line(conn, timeout)
    if resp is None:
        raise ConnectionError(f"no response from {addr}")
    return resp


class ReplicaServer:
    """Socket front + scheduler loop around one ``ServingEngine``."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0,
                 fault_plan=None):
        self.engine = engine
        self.host = host
        self.port = int(port)
        self.fault_plan = fault_plan
        self._submissions: queue.Queue = queue.Queue()
        self._control: queue.Queue = queue.Queue()
        self._listener: Optional[socket.socket] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------- listener
    def start(self) -> int:
        """Bind + start the accept thread; returns the bound port."""
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self._listener.listen(64)
        self.port = self._listener.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="serving-accept").start()
        logger.info("serving replica listening on %s:%d", self.host,
                    self.port)
        return self.port

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn: socket.socket) -> None:
        """One connection = one request: enqueue for the engine thread,
        wait for completion, answer."""
        try:
            msg = read_json_line(conn, REQUEST_TIMEOUT_S)
            if not isinstance(msg, dict):
                send_json_line(conn, {"error": "bad request"})
                return
            if self.fault_plan is not None and self.fault_plan.blackholed():
                # chaos knob ``blackhole_after``: accept, never answer —
                # the hung-process shape. Hold the connection open so the
                # client sees silence (a close would read as a crash and
                # trip the fast transport-retry path instead)
                self._stop.wait(REQUEST_TIMEOUT_S)
                return
            verb = msg.get("verb")
            if verb == "ping":
                # liveness answers on THIS thread, never queued behind
                # decode: a busy replica still pings, a hung one doesn't —
                # exactly the distinction the router's breakers probe for
                send_json_line(conn, {"ok": True,
                                      "draining":
                                          bool(self.engine.draining)})
                return
            if verb in ("stats", "trace", "cancel"):
                send_json_line(conn, self._control_call(verb, msg))
                return
            if "prompt" not in msg:
                send_json_line(conn, {"error": "bad request"})
                return
            if self.engine.draining:
                # explicit signal (vs. a dropped connection) so the router
                # marks this backend draining and re-dispatches immediately
                send_json_line(conn, {"id": msg.get("id"),
                                      "error": "draining"})
                return
            done = threading.Event()
            box: dict = {}

            def on_done(req) -> None:
                box["req"] = req
                done.set()

            self._submissions.put((msg, on_done))
            if not done.wait(REQUEST_TIMEOUT_S):
                send_json_line(conn, {"id": msg.get("id"),
                                      "error": "timeout"})
                return
            req = box["req"]
            if self.fault_plan is not None and \
                    self.fault_plan.take_crash_mid_write():
                # chaos knob ``crash_mid_write``: tear the response line
                # mid-JSON and die — the router must see a transport-level
                # parse failure, never hand the torn payload to a client
                try:
                    conn.sendall(b'{"id": "' + req.id.encode() + b'", "tok')
                finally:
                    os._exit(70)
            if req.error:
                resp = {"id": req.id, "error": req.error}
                if getattr(req, "retry_after_s", None) is not None:
                    resp["retry_after_s"] = req.retry_after_s
                send_json_line(conn, resp)
            else:
                send_json_line(conn, {
                    "id": req.id, "tokens": req.tokens,
                    "ttft_s": req.ttft_s,
                    "latency_s": req.finished_at - req.submitted_at})
            if self.fault_plan is not None:
                self.fault_plan.note_response()
        except OSError:
            pass  # client went away; the engine finishes the work regardless
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _control_call(self, verb: str, msg: dict,
                      timeout: float = 30.0) -> dict:
        """Run one read-only verb on the engine thread.

        The loop services the control queue at every step boundary (and
        through the drain grace window), so snapshots and timeline reads
        never race a scheduler step mutating histograms/slot state.
        """
        done = threading.Event()
        box: dict = {}

        def run() -> None:
            try:
                if verb == "stats":
                    box["resp"] = self.engine.serving_snapshot()
                elif verb == "cancel":
                    rid = str(msg.get("id"))
                    box["resp"] = {"id": rid,
                                   "cancelled": self.engine.cancel(rid)}
                else:
                    rid = str(msg.get("id"))
                    tr = self.engine.request_trace(rid)
                    box["resp"] = tr if tr is not None else \
                        {"id": rid, "error": "unknown request id"}
            except Exception as e:  # noqa: BLE001 — answer, don't kill the loop
                box["resp"] = {"error": f"{type(e).__name__}: {e}"}
            done.set()

        self._control.put(run)
        if not done.wait(timeout):
            return {"error": "control timeout"}
        return box["resp"]

    # ----------------------------------------------------------------- loop
    def _serve_control(self) -> None:
        while True:
            try:
                fn = self._control.get_nowait()
            except queue.Empty:
                return
            fn()

    def _drain_submissions(self) -> None:
        while True:
            try:
                msg, on_done = self._submissions.get_nowait()
            except queue.Empty:
                return
            deadline = msg.get("deadline_s")
            self.engine.submit(msg["prompt"],
                               int(msg.get("max_new_tokens") or 16),
                               request_id=msg.get("id"), callback=on_done,
                               deadline_s=(float(deadline)
                                           if deadline is not None
                                           else None))

    def run(self, preemption=None, idle_sleep: float = 0.002) -> None:
        """The scheduler loop; returns once a latched preemption has fully
        drained. ``preemption``: a ``PreemptionHandler`` (or anything with
        ``.triggered``) polled at every step boundary."""
        # this loop's thread owns the engine from here on: handler threads
        # must reach engine state only via the submission/control queues,
        # and FLEETX_TSAN=1 flags any direct touch
        tsan.register_object(self.engine, "serving-engine")
        # the allocator moves with its engine: the preemption path frees
        # and re-grants pages mid-decode, so the kill-one drill runs it
        # under the same thread-confinement sanitizer
        tsan.register_object(self.engine.allocator, "page-allocator")
        work_steps = 0
        while True:
            if preemption is not None and preemption.triggered and \
                    not self.engine.draining:
                self.engine.begin_drain()
            self._drain_submissions()
            self._serve_control()
            worked = self.engine.step()
            if worked:
                work_steps += 1
                if self.fault_plan is not None:
                    # the serving analogue of the train loop's
                    # sigterm-at-step drill (resilience/faults.py):
                    # SIGTERM ourselves after N engine work-steps
                    self.fault_plan.maybe_sigterm(work_steps)
                    # straggler knob ``slow_decode_ms_at``: stretch the
                    # step cadence so measured ITL genuinely inflates —
                    # the shape hedged dispatch exists to beat
                    delay = self.fault_plan.decode_delay_s(work_steps)
                    if delay:
                        time.sleep(delay)
            else:
                if self.engine.draining and self._submissions.empty():
                    break
                time.sleep(idle_sleep)
        # grace window: a handler that passed its drain check just before
        # the loop exited may still be enqueueing — keep refusing
        # (engine.submit answers "draining") for a bounded moment so those
        # clients get the explicit refusal. A connection that arrives
        # AFTER this window sees the socket close on process exit, which
        # the router treats like any transport failure (re-dispatch).
        grace_deadline = time.monotonic() + 0.5
        while time.monotonic() < grace_deadline:
            self._drain_submissions()
            self._serve_control()
            time.sleep(0.02)
        flight.note("serving", "drained", steps=work_steps)
        logger.warning("serving replica drained after %d work steps",
                       work_steps)

    def close(self) -> None:
        """Tear down the listener socket."""
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
