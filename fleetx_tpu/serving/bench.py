"""Poisson-load serving bench — the decode path's ``bench.py`` analogue.

Drives one in-process ``ServingEngine`` with a seeded Poisson request
stream (exponential inter-arrivals at ``--rate`` req/s, prompt lengths and
``max_new_tokens`` drawn from the same seed) and emits ONE JSON line in
the ``bench.py`` contract — ``{"metric": ..., "value": ...}`` with the
serving SLO block under ``"serving"`` — so decode regressions gate in CI
exactly like training ones::

    python tools/serve.py --bench -c cfg.yaml > fresh.json
    python tools/perf_gate.py fresh.json --baseline BENCH_SELF.json:serving

``tools/perf_gate.py``'s ``SERVING_METRICS`` bands cover
``serving.tokens_per_s`` (regresses down) and the TTFT / inter-token tail
quantiles (regress up); baselines without a serving entry skip, matching
the pre-PR-10 stance for decomposition metrics.

A warmup request runs (and ``reset_stats()`` clears it) before the clock
starts, so the one-off jit compile of the two serving programs never
pollutes the quantiles — same stance as ``InferenceEngine``'s separate
``request_compile_latency`` histogram.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Optional

import numpy as np

from fleetx_tpu.serving.engine import ServingEngine
from fleetx_tpu.utils.log import logger


#: fraction of requests drawing a LONG decode length — the bimodal mix
#: below models the chat-vs-completion split real traffic shows instead
#: of a flat uniform draw (a uniform mix never pressures the lazy
#: allocator: every request looks average, nobody grows far past its
#: admission grant, and the preemption path benches as dead code)
LONG_DECODE_FRACTION = 0.3


def poisson_plan(n_requests: int, rate_rps: float, vocab_size: int,
                 max_prompt: int, max_new: int, seed: int = 0) -> list:
    """The seeded request schedule: ``(arrival_s, prompt, max_new)`` rows.

    Deterministic per seed so a bench run is reproducible and two replicas
    under the same seed serve identical work (the acceptance drill's
    token-parity check relies on this). Decode lengths draw from a
    short/long mixture: most requests stop within ``max_new // 4``
    tokens, a ``LONG_DECODE_FRACTION`` tail runs toward ``max_new`` —
    the skew that makes lazy admission pay (short requests never claim
    their worst case) and that exercises page growth + preemption.
    """
    rng = np.random.RandomState(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / max(rate_rps, 1e-6),
                                         size=n_requests))
    short_hi = max(max_new // 4, 2)
    long_lo = max(max_new // 2, 1)
    plan = []
    for i in range(n_requests):
        plen = int(rng.randint(1, max(max_prompt, 2)))
        prompt = rng.randint(0, vocab_size, size=plen).astype(int).tolist()
        if rng.rand() < LONG_DECODE_FRACTION:
            new = int(rng.randint(long_lo, max(max_new, long_lo + 1)))
        else:
            new = int(rng.randint(1, short_hi))
        plan.append((float(arrivals[i]), prompt, new))
    return plan


def run_serving_bench(engine: ServingEngine, *, n_requests: int = 32,
                      rate_rps: float = 8.0, max_prompt: int = 24,
                      max_new: int = 16, seed: int = 0,
                      metric: str = "serving_poisson_tokens_per_s",
                      device_kind: Optional[str] = None) -> dict:
    """Run the Poisson stream to completion; returns the bench JSON dict."""
    vocab = engine.cfg.vocab_size - 2  # keep clear of eos/pad ids
    plan = poisson_plan(n_requests, rate_rps, vocab, max_prompt, max_new,
                        seed=seed)

    # warmup: compile both programs off the clock
    engine.submit(plan[0][1][:4] or [1], 2, request_id="warmup")
    engine.run_until_drained()
    engine.reset_stats()

    # the watcher's traced re-run (tools/tpu_watch.py _traced_sweep):
    # profile the measured window only — warmup compiles stay off the
    # trace, same stance as bench.py's armed window
    trace_dir = os.environ.get("FLEETX_BENCH_TRACE")
    if trace_dir:
        import jax

        jax.profiler.start_trace(trace_dir)

    t0 = time.monotonic()
    pending = list(plan)
    done: list = []
    occupancy_peak = 0.0
    # mean occupancy samples only WORKED steps: idle spins while waiting
    # for the next Poisson arrival would dilute the mean toward zero and
    # make the occupancy band hostage to host timing
    occupancy_sum, occupancy_samples = 0.0, 0
    while pending or engine.has_work():
        now = time.monotonic() - t0
        while pending and pending[0][0] <= now:
            _, prompt, new = pending.pop(0)
            done.append(engine.submit(prompt, new))
        worked = engine.step()
        occ = engine.allocator.occupancy()
        occupancy_peak = max(occupancy_peak, occ)
        if worked:
            occupancy_sum += occ
            occupancy_samples += 1
        if not worked and pending:
            time.sleep(min(pending[0][0] - now, 0.005))
    wall = time.monotonic() - t0
    if trace_dir:
        import jax

        jax.profiler.stop_trace()

    snap = engine.serving_snapshot()
    completed = [r for r in done if r.error is None]
    refused = [r for r in done if r.error is not None]
    result = {
        "metric": metric,
        "value": round(snap["tokens_total"] / max(wall, 1e-9), 3),
        "unit": "tokens/s",
        "requests": n_requests,
        "rate_rps": rate_rps,
        "wall_s": round(wall, 3),
        "device_kind": device_kind or _device_kind(),
        "serving": {
            "tokens_per_s": round(snap["tokens_total"] / max(wall, 1e-9), 3),
            "tokens_total": snap["tokens_total"],
            "completed": len(completed),
            "refused": len(refused),
            "ttft_p50_s": snap["ttft_p50_s"],
            "ttft_p99_s": snap["ttft_p99_s"],
            "itl_p50_s": snap["itl_p50_s"],
            "itl_p99_s": snap["itl_p99_s"],
            "page_occupancy_peak": round(occupancy_peak, 4),
            # gate-facing fleet-economics keys (tools/perf_gate.py
            # SERVING_METRICS): occupancy under the "higher is better"
            # band reuses the peak; completions per chip normalises
            # throughput across replica shapes
            "page_occupancy": round(occupancy_peak, 4),
            # lazy-lifecycle economics (tools/perf_gate.py bands): mean
            # occupancy over worked steps is the "how full did we run"
            # number lazy admission exists to raise; preemption_rate is
            # swap-outs per completion — nonzero is healthy under
            # pressure, a big jump means the watermark or pool shrank
            "page_occupancy_mean": round(
                occupancy_sum / max(occupancy_samples, 1), 4),
            "preemptions_total": int(snap.get("requests_preempted") or 0),
            "preemption_rate": round(
                int(snap.get("requests_preempted") or 0)
                / max(len(completed), 1), 4),
            "decode_path": snap.get("decode_path", "gather"),
            "requests_per_chip": round(
                len(completed) / max(engine.n_chips, 1), 3),
            # fault-tolerance rows (tools/perf_gate.py bands): deadline
            # sheds come straight off the engine snapshot; the in-process
            # bench has no router, so hedges/breaker opens are honest
            # zeros — the gate's abs band then catches any future bench
            # wiring that starts opening breakers under clean load
            "deadline_sheds": int(snap.get("deadline_sheds") or 0),
            "hedges_total": 0,
            "breaker_opens": 0,
        },
    }
    if snap.get("slo_attainment") is not None:
        result["serving"]["slo_attainment"] = snap["slo_attainment"]
    logger.info("serving bench: %.1f tokens/s over %d requests "
                "(ttft p99 %.4fs, itl p99 %.4fs, %d refused, "
                "%d preempted, mean occupancy %.2f)",
                result["value"], n_requests,
                snap["ttft_p99_s"] or 0.0, snap["itl_p99_s"] or 0.0,
                len(refused), int(snap.get("requests_preempted") or 0),
                result["serving"]["page_occupancy_mean"])
    return result


def _device_kind() -> str:
    """Best-effort accelerator name for the bench record."""
    import jax

    try:
        return jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 — cosmetic field only
        return "unknown"


def emit(result: dict, out: Optional[str] = None) -> None:
    """Print the one-line JSON (and optionally write it to ``out``)."""
    line = json.dumps(result)
    print(line, flush=True)
    if out:
        with open(out, "w") as f:
            f.write(line + "\n")
