"""Paged KV cache: a preallocated page pool + a host-side page allocator.

The training-era ``DecodeCache`` (``models/gpt/model.py``) is one dense
``[layers, batch, max_len, heads, head_dim]`` buffer per generate() call:
every row pays ``max_len`` slots whether its request is 4 tokens or 4000,
and the buffer's batch dim is welded to one call's lifetime. Serving needs
the vLLM-style shape instead: ONE pool of fixed-size pages allocated for
the process lifetime, per-request *block tables* mapping logical token
positions to pool pages, and a host-side allocator that admits or refuses
requests against real free capacity ("Compiler-First State Space Duality
and Portable O(1) Autoregressive Caching for Inference", PAPERS.md, is the
O(1)-append blueprint this follows).

Pool layout (K and V each)::

    [layers, num_pages, page_size, heads, head_dim]

Page 0 is the reserved **null page**: block-table filler slots and masked
(inactive) batch rows point at it, so the jitted steps can scatter/gather
with fully static shapes and no host-side branching — garbage written to
or read from page 0 is always masked out of the attention scores.

Sharding: ``pool_shardings`` places the page dim over ``fsdp`` and the
heads dim over ``tensor``, so cache capacity scales with the mesh the same
way the reference's dp-sharded serving scaled batch
(``inference_engine.py:128-163``); the engine keeps the pool constrained
through every jitted step.
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fleetx_tpu.observability import tsan

#: reserved scratch page — never allocated, always masked when read
NULL_PAGE = 0


class PageAllocatorError(ValueError):
    """A page-accounting violation: double-free, freeing a page that was
    never handed out, or an invalid (non-positive) allocation size.

    A real exception, NOT an ``assert`` — under ``python -O`` an assert
    vanishes and a double-free silently corrupts the free list (the same
    page handed to two requests ⇒ cross-request KV corruption). Exhaustion
    is NOT an error: ``alloc`` returns None for that, and the scheduler's
    preempt-and-swap path handles it.
    """


def init_pool(cfg: Any, num_pages: int, page_size: int,
              dtype: Any = None) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Allocate the (K, V) page pools for a GPT config.

    ``num_pages`` INCLUDES the reserved null page, so usable capacity is
    ``(num_pages - 1) * page_size`` token slots per layer.
    """
    dtype = dtype or cfg.dtype
    shape = (cfg.num_layers, int(num_pages), int(page_size),
             cfg.num_attention_heads, cfg.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def pool_shardings(mesh: Mesh) -> NamedSharding:
    """The pool's mesh placement: pages over ``fsdp``, heads over ``tensor``.

    Pool dims are ``(layers, pages, page_size, heads, head_dim)``; the
    spec is the registry's ``serving_kv`` family rule
    (``parallel/rules.py:kv_pool_spec``) — the page dim shards over the
    ZeRO axis (capacity scales with fsdp degree) and the heads dim over
    the Megatron axis, and shardcheck audits page/head divisibility for
    every serving config statically.
    """
    from fleetx_tpu.parallel.rules import kv_pool_spec

    return NamedSharding(mesh, kv_pool_spec())


class PageAllocator:
    """Host-side free-list allocator over the pool's page ids.

    The engine's default admission policy is **lazy** (vLLM-style): a
    request is admitted on its prompt pages plus a small headroom
    watermark, grows one page at a time as decode crosses page
    boundaries, and the scheduler preempts the youngest request when the
    pool runs dry (``ServingEngine._grow_or_preempt``). The allocator
    itself is policy-free — it hands out and reclaims page ids,
    all-or-nothing, and raises :class:`PageAllocatorError` on any
    accounting violation. ``internal_fragmentation`` reports
    reserved-but-unwritten slack so the occupancy gauge stays honest
    under either policy (reserve-up-front remains available via
    ``ServingConfig.lazy_alloc = False`` for A/B measurement).

    Thread-confinement: the allocator is owned by the engine's scheduler
    thread; ``FLEETX_TSAN=1`` (``observability/tsan.py``) flags any
    cross-thread alloc/free — the preemption path mutates free-list state
    mid-decode, so the kill-one drill runs it sanitized.
    """

    def __init__(self, num_pages: int, page_size: int):
        assert num_pages >= 2, "need at least the null page + one usable page"
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        # LIFO free list → recently-freed (cache-warm) pages are reused first
        self._free = list(range(self.num_pages - 1, NULL_PAGE, -1))
        self._allocated: set[int] = set()
        tsan.register_object(self, "page-allocator")

    # ------------------------------------------------------------- capacity
    @property
    def usable_pages(self) -> int:
        """Pages that can ever be handed out (pool minus the null page)."""
        return self.num_pages - 1

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def allocated_pages(self) -> int:
        return len(self._allocated)

    def pages_needed(self, tokens: int) -> int:
        """Pages required to hold ``tokens`` KV entries."""
        return max(-(-int(tokens) // self.page_size), 1)

    def can_allocate(self, n: int) -> bool:
        """Whether ``n`` pages are free right now."""
        return n <= len(self._free)

    def fits_ever(self, n: int) -> bool:
        """Whether ``n`` pages could EVER be satisfied — False is the
        permanent-refusal signal (the request is larger than the pool)."""
        return n <= self.usable_pages

    # ------------------------------------------------------------ alloc/free
    def alloc(self, n: int) -> Optional[list[int]]:
        """Allocate ``n`` pages, or None (leaving state untouched) when
        the free list cannot satisfy the request — never a partial grant.

        The two failure modes are distinct on purpose: exhaustion (the
        pool is merely full right now) returns None so schedulers can
        wait or preempt, while ``n <= 0`` raises
        :class:`PageAllocatorError` — a zero/negative ask is a caller
        bug, and conflating it with exhaustion used to make "admit on 0
        prompt pages" look like an OOM.
        """
        tsan.note_access(self, "alloc")
        if n <= 0:
            raise PageAllocatorError(f"invalid allocation size {n}")
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        self._allocated.update(pages)
        return pages

    def free(self, pages: list[int]) -> None:
        """Return ``pages`` to the free list.

        Raises :class:`PageAllocatorError` on a page that is not
        currently allocated (double-free / never-allocated / null page) —
        state up to the offending page is already returned, so this is a
        crash-the-replica signal, not a recoverable one.
        """
        tsan.note_access(self, "free")
        for p in pages:
            if p not in self._allocated:
                raise PageAllocatorError(
                    f"freeing unallocated page {p} (double-free or foreign "
                    f"id); {len(self._allocated)} pages currently out")
            self._allocated.discard(p)
            self._free.append(p)

    # ------------------------------------------------------------- metrics
    def occupancy(self) -> float:
        """Allocated fraction of usable pages (the page-occupancy gauge)."""
        return len(self._allocated) / max(self.usable_pages, 1)

    def internal_fragmentation(self, used_slots: int) -> float:
        """Reserved-but-unwritten fraction of the allocated slots.

        ``used_slots`` is the engine's count of token positions actually
        written across live requests; everything else inside allocated
        pages is reservation overhead of the admission policy.
        """
        allocated_slots = len(self._allocated) * self.page_size
        if allocated_slots <= 0:
            return 0.0
        return 1.0 - min(int(used_slots), allocated_slots) / allocated_slots
