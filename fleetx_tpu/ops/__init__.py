from fleetx_tpu.ops import flash_attention  # noqa: F401
