"""Pallas flash attention (FlashAttention-2) for TPU.

Replaces the reference's fused attention core — ``core_attn`` with
``incubate.softmax_mask_fuse_upper_triangle``
(``hybrid_model.py:268-298``) — with a blockwise online-softmax kernel that
never materialises the [S, S] score matrix in HBM:

- forward: one pass over K/V blocks per Q block, f32 accumulators in VMEM,
  causal blocks skipped entirely (2x FLOP saving);
- backward, fused (default where ``fused_backward_supported``): ONE kernel
  sweeps the (k-block, q-block) tile grid once, recomputes P once per tile,
  and emits dq, dk and dv together — dq accumulates in its full-sequence
  f32 output window (VMEM-resident per head, one HBM writeback), dk/dv in
  per-block scratch over the minor (q) dimension. The committed trace paid
  3 backward kernel passes per layer (dq + dkv each re-reading q/k/v/do and
  recomputing P); the fused sweep pays 1 (``flash_recompute`` + a share of
  the HBM re-reads in the BENCHMARKS.md decomposition).
- backward, split (fallback): FlashAttention-2 style — a dq kernel and a
  dk/dv kernel that recompute P from the saved logsumexp, so residual memory
  is O(S) not O(S^2). Selected when the fused predicate rejects the shape
  (wide heads, non-tiling or very long sequences) or via
  ``fused_bwd=False`` (``Model.flash_fused_bwd``).

Layout contract: q, k, v are [batch, seq, heads, head_dim] (the model's
``bsnd``); internally reshaped to [batch*heads, seq, head_dim].

Falls back automatically (``supported()``) when shapes don't tile; on CPU the
kernel runs in interpreter mode so the same code path is unit-testable without
hardware.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only importable on TPU-enabled builds; interpret mode needs it too
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    pltpu = None
    _VMEM = None

# Block sizes default to the largest of these that tiles the sequence:
# 512x512 measured 3.6x faster than 128x128 on v5e (fwd, seq 1024, d 64) —
# bigger blocks amortise the per-block epilogue and keep the MXU busy, and
# VMEM still fits comfortably (f32 scores block = 1MB). Callers can override
# with explicit block_q/block_k.
_BLOCK_CANDIDATES = (512, 256, 128)


def pick_block(seq: int, head_dim: int = 64) -> int:
    """Largest candidate block that tiles ``seq``; when none divides it the
    whole sequence becomes one block (grid 1 — always correct; absurdly long
    non-tiling sequences then fail loudly in Mosaic on VMEM rather than
    silently leaving output rows unwritten). Wide heads (256) cap at 256 to
    keep the backward kernels' live VMEM (q/k/v/do blocks + f32 scores +
    accumulators, double-buffered) well under the ~16MB budget."""
    cap = 256 if head_dim > 128 else _BLOCK_CANDIDATES[0]
    for b in _BLOCK_CANDIDATES:
        if b <= cap and seq >= b and seq % b == 0:
            return b
    return seq
_NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def dropout_supported() -> bool:
    """In-kernel dropout needs the TPU PRNG (``pltpu.prng_seed``), which has
    no interpret-mode lowering — so it's available exactly when we're NOT
    interpreting. CPU callers fall back to the naive-attention dropout path."""
    return pltpu is not None and not _interpret()


def supported(q: jax.Array, k: jax.Array | None = None,
              block_q: int | None = None,
              block_k: int | None = None, causal: bool = True) -> bool:
    """True when the pallas path applies: seq tiles into blocks and head_dim
    is MXU-friendly. When ``k`` is given, its seq length must also tile — and
    must equal q's under ``causal`` (see flash_attention), so gating on this
    predicate never selects a call that then raises. ``block_q``/``block_k``
    default to ``pick_block`` of the respective seq length, matching
    ``flash_attention``'s own defaulting."""
    if pltpu is None:
        return False
    if q.ndim != 4:
        return False
    seq, head_dim = q.shape[1], q.shape[3]
    block_q = pick_block(seq, head_dim) if block_q is None else block_q
    # q's seq only needs to tile into q blocks; k's seq into k blocks
    if seq % min(seq, block_q):
        return False
    if seq < 128 or seq % 128:
        return False
    if k is not None:
        if k.ndim != 4 or k.shape[3] != head_dim:
            return False
        sk = k.shape[1]
        if causal and sk != seq:
            return False
        block_k = pick_block(sk, head_dim) if block_k is None else block_k
        if sk < 128 or sk % 128 or sk % min(sk, block_k):
            return False
    elif block_k is not None and seq % min(seq, block_k):
        return False
    return head_dim in (64, 128, 256)


#: VMEM budget for the fused backward's full-sequence f32 dq accumulator
#: window (plus the two per-block dk/dv scratches). 4 MiB leaves the
#: q/k/v/do blocks, the f32 score tile and Mosaic's double buffering
#: comfortable headroom under the ~16 MB core budget: seq 16384 at
#: head_dim 64, 8192 at 128.
_FUSED_DQ_SCRATCH_BYTES = 4 * 1024 * 1024


def fused_backward_supported(q: jax.Array, k: jax.Array | None = None,
                             block_q: int | None = None,
                             block_k: int | None = None,
                             causal: bool = True) -> bool:
    """True when the single-pass fused backward kernel applies: the base
    ``supported`` contract, a non-wide head (>128 degrades to the split
    kernels — their per-block scratch stays bounded where the fused dq
    accumulator would not), and the full-sequence f32 dq window within
    ``_FUSED_DQ_SCRATCH_BYTES``. Shapes this rejects fall back to the
    split dq + dkv kernels — today's behavior, never silence."""
    if not supported(q, k, block_q=block_q, block_k=block_k, causal=causal):
        return False
    seq, head_dim = q.shape[1], q.shape[3]
    if head_dim > 128:
        return False
    sk = k.shape[1] if k is not None else seq
    bk = pick_block(sk, head_dim) if block_k is None else min(block_k, sk)
    scratch = (seq + 2 * bk) * head_dim * 4
    return scratch <= _FUSED_DQ_SCRATCH_BYTES


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _dropout_mask(seed_ref, h, qi, kj, nq_blocks, nk_blocks, shape, rate: float):
    """Regenerable per-block dropout mask (same seeding in fwd and bwd).

    Seeded by (step seed, flat block coordinates) so the backward kernels
    reproduce the identical mask when they recompute P from the logsumexp —
    this is what lets attention dropout run inside the flash kernel instead
    of materialising [S, S] probability/mask tensors (the reference applies
    dropout to full probs, ``single_model.py:214``).

    ``nq_blocks``/``nk_blocks`` are STATIC so the flat id is identical across
    the fwd/dq/dkv kernels, whose grid orders differ; Mosaic accepts at most
    two seed words.
    """
    flat = (h * nq_blocks * nk_blocks + qi * nk_blocks + kj).astype(jnp.int32)
    pltpu.prng_seed(seed_ref[0], flat)
    bits = pltpu.prng_random_bits(shape)
    threshold = min(int(rate * 2.0 ** 32), 2 ** 32 - 1)
    keep = bits.astype(jnp.uint32) >= jnp.uint32(threshold)
    return keep


def _fwd_kernel(q_ref, k_ref, v_ref, seed_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale: float, causal: bool,
                block_q: int, block_k: int, dropout_rate: float):
    h = pl.program_id(0)
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = qi * block_q
    k_start = kj * block_k
    run = True
    if causal:
        # skip blocks fully above the diagonal
        run = k_start <= q_start + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_prev = m_ref[:, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=1))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        # the softmax normaliser uses UNdropped p; the mask scales only the
        # weighted sum, so out = mask .* softmax(s) / keep_prob @ v
        l_ref[:, 0] = l_ref[:, 0] * alpha + p.sum(axis=1)
        m_ref[:, 0] = m_new
        if dropout_rate > 0.0:
            keep = _dropout_mask(seed_ref, h, qi, kj, pl.num_programs(1),
                                 pl.num_programs(2), p.shape, dropout_rate)
            p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
        v = v_ref[0].astype(jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32)

    @pl.when(kj == nk - 1)
    def _finish():
        l = l_ref[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe[:, None]).astype(o_ref.dtype)
        # lse laid out [bn, sq, 1]: Mosaic needs the last two block dims
        # (8k, 128m-or-full); a (block_q, 1) store satisfies that where a
        # 2D (1, block_q) block does not.
        lse_ref[0] = (m_ref[:, 0] + jnp.log(l_safe))[:, None]


def _fwd(q3, k3, v3, seed, *, scale, causal, block_q, block_k, dropout_rate):
    bn, sq, d = q3.shape
    sk = k3.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    grid = (bn, sq // block_q, sk // block_k)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          dropout_rate=dropout_rate),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda h, i, j: (h, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda h, i, j: (h, j, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda h, i, j: (h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bn, sq, d), q3.dtype),
            jax.ShapeDtypeStruct((bn, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            _VMEM((block_q, d), jnp.float32),
            _VMEM((block_q, 128), jnp.float32),
            _VMEM((block_q, 128), jnp.float32),
        ],
        interpret=_interpret(),
    )(q3, k3, v3, seed)
    return out, lse[..., 0]


# ---------------------------------------------------------------------------
# backward (FlashAttention-2: recompute P per block from saved logsumexp)
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, seed_ref,
                   dq_ref, acc_ref, *, scale, causal, block_q, block_k,
                   dropout_rate):
    h = pl.program_id(0)
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = kj * block_k
    run = True
    if causal:
        run = k_start <= q_start + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0])          # lse block [bq, 1] broadcasts
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            keep = _dropout_mask(seed_ref, h, qi, kj, pl.num_programs(1),
                                 pl.num_programs(2), p.shape, dropout_rate)
            dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
        ds = p * (dp - delta_ref[0]) * scale
        acc_ref[:] += jax.lax.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(kj == nk - 1)
    def _finish():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, seed_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    block_q, block_k, dropout_rate):
    h = pl.program_id(0)
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = qi * block_q
    k_start = kj * block_k
    run = True
    if causal:
        run = k_start <= q_start + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0])  # [bq, bk]; lse block [bq, 1] broadcasts
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            # identical (h, qi, kj) seeding as the forward mask; this kernel's
            # grid is (h, kj, qi) so the q/k block counts swap positions
            keep = _dropout_mask(seed_ref, h, qi, kj, pl.num_programs(2),
                                 pl.num_programs(1), p.shape, dropout_rate)
            inv = 1.0 / (1.0 - dropout_rate)
            dv_acc[:] += jax.lax.dot_general(
                jnp.where(keep, p * inv, 0.0), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jnp.where(keep, dp * inv, 0.0)
        else:
            dv_acc[:] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        dk_acc[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq(q3, k3, v3, do, lse3, delta3, seed, *, scale, causal,
            block_q, block_k, dropout_rate: float = 0.0):
    """dq kernel entry: lse3/delta3 as ``[bn, sq, 1]`` (any lse works — the
    ring backward feeds the GLOBAL logsumexp to get exact per-block grads)."""
    bn, sq, d = q3.shape
    sk = k3.shape[1]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    return pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, dropout_rate=dropout_rate),
        grid=(bn, sq // bq, sk // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, bk, d), lambda h, i, j: (h, j, 0)),
            pl.BlockSpec((1, bk, d), lambda h, i, j: (h, j, 0)),
            pl.BlockSpec((1, bq, d), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda h, i, j: (h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bn, sq, d), q3.dtype),
        scratch_shapes=[_VMEM((bq, d), jnp.float32)],
        interpret=_interpret(),
    )(q3, k3, v3, do, lse3, delta3, seed)


def _bwd_dkv(q3, k3, v3, do, lse3, delta3, seed, *, scale, causal,
             block_q, block_k, dropout_rate: float = 0.0):
    """dk/dv kernel entry (same lse3/delta3 contract as ``_bwd_dq``)."""
    bn, sq, d = q3.shape
    sk = k3.shape[1]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    return pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, dropout_rate=dropout_rate),
        grid=(bn, sk // bk, sq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda h, j, i: (h, i, 0)),
            pl.BlockSpec((1, bk, d), lambda h, j, i: (h, j, 0)),
            pl.BlockSpec((1, bk, d), lambda h, j, i: (h, j, 0)),
            pl.BlockSpec((1, bq, d), lambda h, j, i: (h, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda h, j, i: (h, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda h, j, i: (h, i, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda h, j, i: (h, j, 0)),
            pl.BlockSpec((1, bk, d), lambda h, j, i: (h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bn, sk, d), k3.dtype),
            jax.ShapeDtypeStruct((bn, sk, d), v3.dtype),
        ],
        scratch_shapes=[_VMEM((bk, d), jnp.float32), _VMEM((bk, d), jnp.float32)],
        interpret=_interpret(),
    )(q3, k3, v3, do, lse3, delta3, seed)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      seed_ref, dq_ref, dk_ref, dv_ref,
                      dk_acc, dv_acc, *, scale, causal,
                      block_q, block_k, dropout_rate):
    """Single-pass fused backward: grid (head, k-block, q-block).

    Each tile recomputes P exactly once and contributes to all three
    grads. dk/dv accumulate in per-block f32 scratch across the minor
    (q) dimension — the split dkv kernel's proven shape, one HBM
    writeback per k-block — and dq accumulates DIRECTLY in its
    full-sequence f32 output window, whose index map depends only on the
    head: Mosaic keeps the window VMEM-resident across the entire
    (k-block, q-block) sweep (the standard reduction idiom — out index
    invariant over the reduction dims) and flushes it to HBM exactly
    once, at the head transition. No per-step garbage flushes, no
    cross-step read-modify-write of HBM-backed blocks.
    """
    h = pl.program_id(0)
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    nk = pl.num_programs(1)
    nq = pl.num_programs(2)

    @pl.when((kj == 0) & (qi == 0))
    def _init_dq():  # fresh head: zero the resident full-seq dq window
        dq_ref[...] = jnp.zeros_like(dq_ref)

    @pl.when(qi == 0)
    def _init_dkv():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = qi * block_q
    k_start = kj * block_k
    run = True
    if causal:
        run = k_start <= q_start + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0])  # [bq, bk]; lse block [bq, 1] broadcasts
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            # identical (h, qi, kj) seeding as the forward mask; this
            # kernel's grid is (h, kj, qi) so the q/k block counts swap
            keep = _dropout_mask(seed_ref, h, qi, kj, nq, nk, p.shape,
                                 dropout_rate)
            inv = 1.0 / (1.0 - dropout_rate)
            dv_acc[:] += jax.lax.dot_general(
                jnp.where(keep, p * inv, 0.0), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jnp.where(keep, dp * inv, 0.0)
        else:
            dv_acc[:] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        dk_acc[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dq_ref[0, pl.ds(q_start, block_q), :] += jax.lax.dot(
            ds, k, preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _flush_dkv():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_fused(q3, k3, v3, do, lse3, delta3, seed, *, scale, causal,
               block_q, block_k, dropout_rate: float = 0.0):
    """Fused dq/dk/dv kernel entry (same lse3/delta3 contract as the split
    kernels: ``[bn, sq, 1]``). dq comes back f32 — it IS the in-kernel
    accumulator (see ``_bwd_fused_kernel``) — and is cast to the operand
    dtype outside the kernel."""
    bn, sq, d = q3.shape
    sk = k3.shape[1]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    dq32, dk, dv = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, dropout_rate=dropout_rate),
        grid=(bn, sk // bk, sq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda h, j, i: (h, i, 0)),
            pl.BlockSpec((1, bk, d), lambda h, j, i: (h, j, 0)),
            pl.BlockSpec((1, bk, d), lambda h, j, i: (h, j, 0)),
            pl.BlockSpec((1, bq, d), lambda h, j, i: (h, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda h, j, i: (h, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda h, j, i: (h, i, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            # dq: the whole head's [sq, d] as ONE window, index map
            # invariant over both sweep dims — resident in VMEM for the
            # head's entire tile sweep, flushed once at the head change
            pl.BlockSpec((1, sq, d), lambda h, j, i: (h, 0, 0)),
            pl.BlockSpec((1, bk, d), lambda h, j, i: (h, j, 0)),
            pl.BlockSpec((1, bk, d), lambda h, j, i: (h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bn, sq, d), jnp.float32),
            jax.ShapeDtypeStruct((bn, sk, d), k3.dtype),
            jax.ShapeDtypeStruct((bn, sk, d), v3.dtype),
        ],
        scratch_shapes=[
            _VMEM((bk, d), jnp.float32),
            _VMEM((bk, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q3, k3, v3, do, lse3, delta3, seed)
    return dq32.astype(q3.dtype), dk, dv


def _bwd(scale, causal, block_q, block_k, dropout_rate, fused_bwd,
         residuals, g):
    q3, k3, v3, seed, out, lse = residuals
    do = g
    delta = (out.astype(jnp.float32) * do.astype(jnp.float32)).sum(axis=-1)
    # lse/delta travel as [bn, sq, 1] so their blocks tile on TPU (see _fwd)
    lse3 = lse[..., None]
    delta3 = delta[..., None]
    kw = dict(scale=scale, causal=causal, block_q=block_q, block_k=block_k,
              dropout_rate=dropout_rate)
    if fused_bwd:
        dq, dk, dv = _bwd_fused(q3, k3, v3, do, lse3, delta3, seed, **kw)
    else:
        dq = _bwd_dq(q3, k3, v3, do, lse3, delta3, seed, **kw)
        dk, dv = _bwd_dkv(q3, k3, v3, do, lse3, delta3, seed, **kw)
    return dq, dk, dv, None


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash3(q3, k3, v3, seed, scale, causal, block_q, block_k, dropout_rate,
            fused_bwd):
    out, _ = _fwd(q3, k3, v3, seed, scale=scale, causal=causal,
                  block_q=block_q, block_k=block_k, dropout_rate=dropout_rate)
    return out


def _flash3_fwd(q3, k3, v3, seed, scale, causal, block_q, block_k,
                dropout_rate, fused_bwd):
    out, lse = _fwd(q3, k3, v3, seed, scale=scale, causal=causal,
                    block_q=block_q, block_k=block_k, dropout_rate=dropout_rate)
    return out, (q3, k3, v3, seed, out, lse)


_flash3.defvjp(_flash3_fwd, _bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, scale: float | None = None,
                    block_q: int | None = None,
                    block_k: int | None = None,
                    dropout_rate: float = 0.0,
                    dropout_seed: jax.Array | None = None,
                    fused_bwd: bool = True) -> jax.Array:
    """Blockwise causal attention. q/k/v: [batch, seq, heads, head_dim].

    ``dropout_rate`` > 0 applies attention-probability dropout INSIDE the
    kernel (regenerable per-block masks; see ``_dropout_mask``) so training
    configs with attention dropout keep the O(S) memory profile.
    ``dropout_seed``: int32 scalar/[1] array; vary per step.
    ``fused_bwd`` selects the single-pass fused backward kernel where
    ``fused_backward_supported`` admits the shape (``Model.flash_fused_bwd``
    upstream); other shapes — and ``fused_bwd=False`` — take the split
    dq + dkv kernels.
    """
    b, sq, n, d = q.shape
    sk = k.shape[1]
    if block_q is None:
        block_q = pick_block(sq, d)
    if block_k is None:
        block_k = pick_block(sk, d)
    # a non-dividing explicit block would floor away whole grid rows and
    # return unwritten output — refuse loudly (defaults always divide)
    if sq % min(sq, block_q) or sk % min(sk, block_k):
        raise ValueError(
            f"block sizes must tile the sequence: seq {sq}/{sk} vs "
            f"block_q={block_q}, block_k={block_k}")
    if causal and sq != sk:
        # The kernel's causal mask compares absolute row/col positions with no
        # offset, which is only meaningful for self-attention (sq == sk).
        raise ValueError(
            f"flash_attention(causal=True) requires q and k to share a seq "
            f"length; got sq={sq}, sk={sk}")
    scale = scale if scale is not None else d ** -0.5
    if dropout_seed is None:
        seed = jnp.zeros((1,), jnp.int32)
    else:
        seed = jnp.asarray(dropout_seed, jnp.int32).reshape((1,))

    def to3(x, s):
        return x.transpose(0, 2, 1, 3).reshape(b * n, s, d)

    use_fused = bool(fused_bwd) and fused_backward_supported(
        q, k, block_q=block_q, block_k=block_k, causal=causal)
    out3 = _flash3(to3(q, sq), to3(k, sk), to3(v, sk), seed, scale, causal,
                   block_q, block_k, float(dropout_rate), use_fused)
    return out3.reshape(b, n, sq, d).transpose(0, 2, 1, 3)


def reference_attention(q, k, v, *, causal: bool = True,
                        scale: float | None = None) -> jax.Array:
    """Naive O(S^2)-memory attention, used for numerics tests."""
    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    s = jnp.einsum("bqnd,bknd->bnqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bnqk,bknd->bqnd", p.astype(q.dtype), v)


def sharded_supported(q: jax.Array, mesh) -> bool:
    """True when the per-device shards still satisfy the kernel contract:
    batch divides the data axes, heads divide the tensor axis, and the seq
    axis is not context-sharded (ring attention owns that case)."""
    if mesh is None or q.ndim != 4:
        return False
    shape = dict(mesh.shape)
    dp = shape.get("data", 1) * shape.get("fsdp", 1)
    tp = shape.get("tensor", 1)
    if shape.get("seq", 1) != 1:
        return False
    b, _, n, _ = q.shape
    return b % dp == 0 and n % tp == 0


def flash_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array, *,
                            mesh=None, causal: bool = True,
                            **kwargs) -> jax.Array:
    """Mesh-aware flash attention: the kernel is a Mosaic custom call GSPMD
    cannot partition, so under a multi-device mesh the operands would be
    all-gathered and the kernel run replicated. This wrapper runs it
    per-device instead — batch sharded over ``(data, fsdp)``, heads over
    ``tensor`` — via a partial-manual ``shard_map`` (attention is
    embarrassingly parallel over both dims; remaining axes stay automatic).

    The in-kernel dropout seed is folded with the device's linear index so
    shards draw independent masks.
    """
    from functools import partial as _partial

    from jax.sharding import PartitionSpec as _P

    if mesh is None:
        from fleetx_tpu.parallel.mesh import current_mesh

        mesh = current_mesh()
    if mesh is None or not sharded_supported(q, mesh):
        return flash_attention(q, k, v, causal=causal, **kwargs)

    manual = tuple(a for a in ("data", "fsdp", "tensor")
                   if mesh.shape.get(a, 1) > 1)
    # Under pipeline parallelism this wrapper is reached through the stage
    # nn.vmap (``spmd_axis_name="pipe"``, parallel/pipeline.py): declaring
    # ``pipe`` manual here lets the vmap batching rule shard the stage dim
    # over ``pipe`` — without it, sdy refuses the composition and GSPMD
    # would all-gather the Mosaic call's operands across stages. Outside
    # that vmap the extra manual axis just asserts pipe-replication, which
    # holds for every non-pipelined caller (decode, single-stack training).
    if mesh.shape.get("pipe", 1) > 1:
        manual = manual + ("pipe",)
    if not manual:
        return flash_attention(q, k, v, causal=causal, **kwargs)
    batch_axes = tuple(a for a in ("data", "fsdp") if a in manual)
    head_axis = "tensor" if "tensor" in manual else None
    spec = _P(batch_axes or None, None, head_axis, None)

    def body(q, k, v):
        kw = dict(kwargs)
        if kw.get("dropout_seed") is not None:
            ix = jnp.int32(0)
            for a in manual:
                ix = ix * mesh.shape[a] + jax.lax.axis_index(a)
            kw["dropout_seed"] = kw["dropout_seed"] + ix
        return flash_attention(q, k, v, causal=causal, **kw)

    fn = jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, axis_names=frozenset(manual),
                       check_vma=False)
    return fn(q, k, v)
