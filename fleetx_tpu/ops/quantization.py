"""Quantization-aware training — int8 fake-quant with straight-through grads.

Reference: ``ppfleetx/models/language_model/language_module.py:142-144`` wraps
the model with ``paddleslim.dygraph.quant.QAT`` (simulated int8 on linear
weights + activations, ``pretrain_gpt_345M_mp8_qat.yaml``). The functional
equivalent: symmetric fake-quantisation applied to each matmul's kernel
(per-output-channel scales) and input activations (per-tensor scale), with
the straight-through estimator so gradients flow as if quantisation were
identity. XLA folds the quant/dequant pair into the surrounding fusion.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["fake_quant", "quantize_weight", "quantize_act"]


def fake_quant(x: jax.Array, bits: int = 8, axis=None) -> jax.Array:
    """Simulated symmetric quantisation with straight-through gradients."""
    qmax = float(2 ** (bits - 1) - 1)
    scale = jnp.max(jnp.abs(x), axis=axis, keepdims=axis is not None)
    scale = jnp.maximum(scale / qmax, 1e-8).astype(x.dtype)
    q = jnp.clip(jnp.round(x / scale), -qmax, qmax) * scale
    return x + jax.lax.stop_gradient(q - x)


def quantize_weight(w: jax.Array, bits: int = 8,
                    out_axis: int = -1) -> jax.Array:
    """Per-output-channel weight fake-quant (paddleslim 'channel_wise_abs_max')."""
    axes = tuple(i for i in range(w.ndim) if i != (out_axis % w.ndim))
    return fake_quant(w, bits=bits, axis=axes)


def quantize_act(x: jax.Array, bits: int = 8) -> jax.Array:
    """Per-tensor activation fake-quant (paddleslim 'moving_average_abs_max'
    collapses to abs-max under jit: the scale is recomputed per step)."""
    return fake_quant(x, bits=bits, axis=None)
