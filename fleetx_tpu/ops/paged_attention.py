"""Pallas paged-attention decode kernel: block tables walked in-kernel.

The serving decode step previously materialised the dense page view
``pool[block_tables] → [B, pages_per_req·page_size, heads, head_dim]``
per layer per token — ``B·pages_per_req·page_size·heads·head_dim`` bytes
of HBM gather traffic for keys that are mostly masked tail. This kernel
removes the materialisation: per-request page ids arrive as **scalar
prefetch** operands (``pltpu.PrefetchScalarGridSpec``), the BlockSpec
index maps read them to DMA each page of the pool directly, and an
online-softmax accumulator in f32 VMEM scratch (the
``ops/flash_attention.py`` m/l/acc discipline) folds every page into the
output without ever holding more than one ``[page_size, head_block,
head_dim]`` tile of K/V live.

Grid: ``(batch, head-block, page-block)`` with the page walk innermost so
the accumulator output block (index-map invariant over the page dim)
stays VMEM-resident across the whole walk and is flushed once. Null
pages (``NULL_PAGE``), pages past a request's allocation (lazy lifecycle:
block-table tails), and key positions beyond the query's ``lens`` are
all masked in-kernel — callers hand the raw block tables over and the
wrapper rewrites invalid entries to ``-1`` (the kernel's skip sentinel).

Contract mirrors ``ops/flash_attention.py`` exactly:

- ``paged_attention_supported(...)`` gates the path; rejected shapes keep
  today's gather — degrade, never break (``serving/decode.py`` makes the
  choice ONCE at ``make_step_fns`` time so the jit cache still holds one
  entry).
- CPU runs the kernel in interpret mode (``_interpret()``), which is how
  the serving parity suite pins token-identity without a TPU.
- Under a multi-device mesh the kernel is a Mosaic custom call GSPMD
  cannot partition, so ``paged_attention_sharded`` runs it per-device via
  ``shard_map``: pool pages sharded over ``fsdp``, heads over ``tensor``
  (the ``parallel/rules.py`` ``serving_kv`` family stays the one spec
  source), with a cross-shard flash-decoding combine (global running max
  + rescaled numerator/denominator psum) over the page axis.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only importable on TPU-enabled builds; interpret mode needs it
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover - exercised on minimal builds
    pltpu = None
    _VMEM = None

_NEG_INF = -1e30

#: the reserved filler page — must match ``serving.paged_cache.NULL_PAGE``
#: (pinned by a test; importing it here would cycle ops ← serving ← ops).
NULL_PAGE = 0

#: per-grid-step live VMEM budget for the kernel's K/V page tiles plus the
#: f32 accumulator/m/l scratch, double-buffered. Decode tiles are tiny
#: (one page × one head block), so this bound only rejects pathological
#: page_size × head_dim configs rather than anything a serving YAML ships.
_PAGED_VMEM_BUDGET_BYTES = 2 * 1024 * 1024

#: head-block candidates: largest divisor of the (per-shard) head count,
#: capped small — decode attention is DMA-bound, wider head blocks only
#: grow the K/V tile without feeding the MXU any better.
_HEAD_BLOCK_CANDIDATES = (8, 4, 2, 1)


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def pick_head_block(num_heads: int) -> int:
    """Largest head-block candidate dividing ``num_heads`` (≥ 1 always)."""
    for hb in _HEAD_BLOCK_CANDIDATES:
        if num_heads % hb == 0:
            return hb
    return 1


def _shard_map_fn():
    """Feature-detect a usable ``shard_map`` (None when this jax has
    neither the stable nor the experimental API)."""
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn
    try:
        from jax.experimental.shard_map import shard_map as _sm

        return _sm
    except ImportError:  # pragma: no cover - every pinned jax has one
        return None


def paged_attention_supported(*, num_heads: int, head_dim: int,
                              page_size: int, pages_per_req: int,
                              dtype: Any = jnp.float32) -> bool:
    """True when the in-kernel page walk applies to this engine geometry.

    Consulted ONCE per engine (``serving/decode.py:make_step_fns``) —
    shapes it rejects take the dense gather path, today's behavior, never
    silence. Bounds are alignment (f32 sublane-friendly ``head_dim``) and
    the VMEM tile budget; Mosaic pads small tiles, so the gate is about
    staying a sensible kernel rather than about lowering at all.
    """
    if pltpu is None:
        return False
    if num_heads < 1 or pages_per_req < 1 or page_size < 1:
        return False
    if head_dim < 8 or head_dim % 8 or head_dim > 256:
        return False
    if jnp.dtype(dtype) not in (jnp.dtype(jnp.float32),
                                jnp.dtype(jnp.bfloat16)):
        return False
    hb = pick_head_block(num_heads)
    esize = jnp.dtype(dtype).itemsize
    # double-buffered K+V page tiles + f32 acc/m/l scratch
    tile = 2 * 2 * page_size * hb * head_dim * esize
    scratch = hb * head_dim * 4 + 2 * hb * 128 * 4
    return tile + scratch <= _PAGED_VMEM_BUDGET_BYTES


def paged_sharded_supported(mesh: Any, *, num_heads: int,
                            num_pages: int) -> bool:
    """True when the per-device ``shard_map`` wrapping applies: a
    ``shard_map`` API exists, the pool's page dim splits evenly over
    ``fsdp`` and its head dim over ``tensor`` (the ``serving_kv``
    placement), and decode is not running under sequence parallelism."""
    if mesh is None or _shard_map_fn() is None:
        return False
    shape = dict(mesh.shape)
    if shape.get("seq", 1) != 1 or shape.get("pipe", 1) != 1:
        return False
    return num_pages % shape.get("fsdp", 1) == 0 and \
        num_heads % shape.get("tensor", 1) == 0


def _decode_kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref,
                   acc_ref, m_out_ref, l_out_ref, m_ref, l_ref, *,
                   page_size: int, scale: float):
    """One (request, head-block, page) step of the online-softmax walk.

    ``tables_ref``/``lens_ref`` are the scalar-prefetch operands (SMEM);
    a table entry < 0 marks an invalid page — null, beyond the request's
    lazy allocation, or owned by another shard — and skips the step
    entirely (the page's DMA still lands, on local page 0, but its
    contribution is never folded in). ``acc_ref`` is the f32 output block
    itself: its index map is invariant over the page dim, so it stays
    VMEM-resident across the walk and accumulates in place.
    """
    b = pl.program_id(0)
    p = pl.program_id(2)
    np_ = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        acc_ref[0] = jnp.zeros_like(acc_ref[0])
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    page = tables_ref[b, p]
    q_pos = lens_ref[b]
    base = p * page_size
    run = (page >= 0) & (q_pos >= 0) & (base <= q_pos)

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)                  # [hb, hd]
        k = k_ref[0].astype(jnp.float32)                  # [ps, hb, hd]
        s = jax.lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * scale   # [hb, ps]
        pos = base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos <= q_pos, s, _NEG_INF)
        m_prev = m_ref[:, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=1))
        alpha = jnp.exp(m_prev - m_new)
        pexp = jnp.exp(s - m_new[:, None])
        l_ref[:, 0] = l_ref[:, 0] * alpha + pexp.sum(axis=1)
        m_ref[:, 0] = m_new
        v = v_ref[0].astype(jnp.float32)                  # [ps, hb, hd]
        pv = jax.lax.dot_general(
            pexp, v, (((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)           # [hb, hd]
        acc_ref[0] = acc_ref[0] * alpha[:, None] + pv

    @pl.when(p == np_ - 1)
    def _finish():
        # m/l laid out [B, nh, 1]: a (hb, 1) store satisfies Mosaic's
        # last-two-dims tiling where a 2D (1, hb) block does not — the
        # flash kernel's lse idiom.
        m_out_ref[0] = m_ref[:, 0][:, None]
        l_out_ref[0] = l_ref[:, 0][:, None]


def _paged_call(q: jax.Array, pool_k: jax.Array, pool_v: jax.Array,
                tables: jax.Array, lens: jax.Array):
    """Raw kernel invocation on one device's shard.

    ``q`` ``[B, nh, hd]``, pools ``[pages, page_size, nh, hd]``,
    ``tables`` ``[B, pages_per_req]`` int32 with ``-1`` marking invalid
    entries, ``lens`` ``[B]`` int32 absolute query positions (< 0 =
    inactive row). Returns the UNnormalized ``(acc [B,nh,hd] f32,
    m [B,nh], l [B,nh])`` triple so sharded callers can run the
    cross-shard softmax combine before dividing.
    """
    B, nh, hd = q.shape
    ps = pool_k.shape[1]
    pages_per_req = tables.shape[1]
    hb = pick_head_block(nh)
    scale = 1.0 / math.sqrt(hd)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, nh // hb, pages_per_req),
        in_specs=[
            pl.BlockSpec((1, hb, hd), lambda b, h, p, t, l: (b, h, 0)),
            pl.BlockSpec(
                (1, ps, hb, hd),
                lambda b, h, p, t, l: (jnp.maximum(t[b, p], 0), 0, h, 0)),
            pl.BlockSpec(
                (1, ps, hb, hd),
                lambda b, h, p, t, l: (jnp.maximum(t[b, p], 0), 0, h, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, hb, hd), lambda b, h, p, t, l: (b, h, 0)),
            pl.BlockSpec((1, hb, 1), lambda b, h, p, t, l: (b, h, 0)),
            pl.BlockSpec((1, hb, 1), lambda b, h, p, t, l: (b, h, 0)),
        ],
        scratch_shapes=[
            _VMEM((hb, 128), jnp.float32),
            _VMEM((hb, 128), jnp.float32),
        ],
    )
    acc, m, l = pl.pallas_call(
        functools.partial(_decode_kernel, page_size=ps, scale=scale),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, nh, hd), jnp.float32),
            jax.ShapeDtypeStruct((B, nh, 1), jnp.float32),
            jax.ShapeDtypeStruct((B, nh, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(tables, lens, q, pool_k, pool_v)
    return acc, m[..., 0], l[..., 0]


def _localize_tables(tables: jax.Array, page_lo, local_pages: int):
    """Rewrite global page ids to shard-local ones; null pages and pages
    owned by another shard become the kernel's ``-1`` skip sentinel."""
    local = tables - page_lo
    ok = (tables != NULL_PAGE) & (local >= 0) & (local < local_pages)
    return jnp.where(ok, local, -1).astype(jnp.int32)


def _normalize(acc: jax.Array, l: jax.Array, dtype) -> jax.Array:
    """Final softmax division; fully-masked rows (inactive slots: every
    page skipped, ``l == 0``) come out exactly zero instead of NaN."""
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return (acc / l_safe[..., None]).astype(dtype)


def paged_attention(q: jax.Array, pool_k: jax.Array, pool_v: jax.Array,
                    block_tables: jax.Array, lens: jax.Array) -> jax.Array:
    """Single-shard paged decode attention.

    Semantics match ``serving/decode.py``'s gather path for active rows:
    softmax over key positions ``≤ lens`` with ``1/sqrt(head_dim)``
    scaling, f32 accumulation, output cast back to ``q.dtype``. Inactive
    rows (``lens < 0``) return exact zeros (the gather path returns
    finite null-page garbage there; both are discarded by the host).
    """
    tables = _localize_tables(block_tables, 0, pool_k.shape[0])
    acc, _, l = _paged_call(q, pool_k, pool_v, tables, lens)
    return _normalize(acc, l, q.dtype)


def paged_attention_sharded(q: jax.Array, pool_k: jax.Array,
                            pool_v: jax.Array, block_tables: jax.Array,
                            lens: jax.Array, *,
                            mesh: Optional[Any] = None) -> jax.Array:
    """Mesh-aware paged attention: pool pages stay sharded over ``fsdp``
    and heads over ``tensor`` (the ``serving_kv`` placement from
    ``parallel/rules.py``) while each device walks only its own page
    slice; partial (acc, m, l) triples are merged with the standard
    flash-decoding combine (global running max over ``fsdp``, rescaled
    numerator/denominator psum). Callers must have gated on
    :func:`paged_sharded_supported`; with no mesh (or a trivial one) this
    is the single-shard call.
    """
    from jax.sharding import PartitionSpec as _P

    from fleetx_tpu.parallel.rules import kv_pool_spec

    manual = ()
    if mesh is not None:
        manual = tuple(a for a in ("fsdp", "tensor")
                       if dict(mesh.shape).get(a, 1) > 1)
    if not manual:
        return paged_attention(q, pool_k, pool_v, block_tables, lens)

    # per-layer pool spec = the registry's 5D serving_kv spec minus the
    # scanned layer dim — rules.py stays the one source of placement
    # (PartitionSpec drops trailing Nones, hence the re-pad to 4 dims)
    entries = (tuple(kv_pool_spec())[1:] + (None, None, None, None))[:4]
    pages_ax, _, heads_ax, _ = entries
    pages_ax = pages_ax if pages_ax in manual else None
    heads_ax = heads_ax if heads_ax in manual else None
    pool_spec = _P(pages_ax, None, heads_ax, None)
    q_spec = _P(None, heads_ax, None)
    fsdp = dict(mesh.shape).get(pages_ax, 1) if pages_ax else 1
    local_pages = pool_k.shape[0] // fsdp

    def body(q, pk, pv, tabs, lens):
        lo = jax.lax.axis_index(pages_ax) * local_pages if pages_ax else 0
        tabs = _localize_tables(tabs, lo, local_pages)
        acc, m, l = _paged_call(q, pk, pv, tabs, lens)
        if pages_ax is None:
            return _normalize(acc, l, q.dtype)
        # flash-decoding combine across the page shards: rescale every
        # shard's numerator/denominator to the global running max, sum
        m_g = jax.lax.pmax(m, pages_ax)
        w = jnp.exp(m - m_g)
        num = jax.lax.psum(acc * w[..., None], pages_ax)
        den = jax.lax.psum(l * w, pages_ax)
        return _normalize(num, den, q.dtype)

    # FULL-manual mapping (every mesh axis): ``axis_index`` — the page-slice
    # localizer — lowers to a PartitionId XLA cannot place under the
    # partial-manual mode, and decode has no other tensor the remaining
    # axes could stay automatic for. The stable ``jax.shard_map`` and the
    # experimental API spell the replication-check kwarg differently.
    sm = _shard_map_fn()
    in_specs = (q_spec, pool_spec, pool_spec, _P(None, None), _P(None))
    try:
        fn = sm(body, mesh=mesh, in_specs=in_specs, out_specs=q_spec,
                check_vma=False)
    except TypeError:
        fn = sm(body, mesh=mesh, in_specs=in_specs, out_specs=q_spec,
                check_rep=False)
    return fn(q, pool_k, pool_v, block_tables, lens)
