"""Fused residual-add + f32 LayerNorm + output-cast Pallas kernel.

The committed trace decomposition bills **elementwise 32 ms/step**
(BENCHMARKS.md, `observability/perf.py`) largely to the op chain XLA
materialises around every pre-norm `LayerNorm` call in
`models/gpt/model.py`: the block residual add, the f32 upcast, the
mean/variance reductions, the normalise/affine elementwise line, and the
cast back to the compute dtype — each a separate HBM round-trip when XLA
declines to fuse across the reduction. This kernel runs the whole chain
in one VMEM-resident pass per row block:

- forward: ``s = residual + x`` (optional), f32 mean/var over the hidden
  dim, normalise + affine, cast to ``out_dtype`` — one read of ``x`` (and
  ``residual``), one write each of ``out``/``s``/the two stat rows.
- backward (``custom_vjp``): recomputes ``rsqrt``/centred rows from the
  **saved f32 stats** ``(mean, var)`` plus the saved compute-dtype ``s``
  instead of re-running the forward reductions, and emits ``dx``;
  ``dscale``/``dbias`` reduce outside the kernel from the same saved
  stats so XLA sees the identical elementwise-then-reduce subgraph the
  unfused backward has (bitwise, and no extra f32 row buffer to spill).

Numerics contract: the kernel body transcribes the *exact* op sequence
JAX autodiff derives for the unfused `LayerNorm` (operand order, the
per-branch ``dmean`` accumulation, the ``-0.5 * rstd / u`` residual) so
f32 loss AND grads are bitwise identical fused vs unfused under jit —
pinned by `tests/test_zz_fusednorm.py`. bf16 compute stays drift-bounded
by the same cast points the unfused path has.

Fallback contract (the PR 13 playbook): `fused_norm_supported` gates on
lane-aligned hidden dims, sublane-aligned row counts and the VMEM budget;
rejected shapes — and ``Model.fused_residual_norm: False`` — keep today's
unfused jnp path, never silence. On CPU the kernel runs in interpreter
mode, so every path is unit-testable without a TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only importable on TPU-enabled builds; interpret mode needs it too
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

#: VMEM budget for one row-block's live buffers (x/residual/s/out blocks,
#: the f32 upcast + centred-row temps, stats, double buffering). 4 MiB
#: leaves the ~16 MB core budget comfortable headroom; with the f32 worst
#: case (~28 bytes/element live) an 8-row block admits hidden dims up to
#: ~18k — wider hidden sizes fall back to the unfused path.
_FUSED_NORM_VMEM_BYTES = 4 * 1024 * 1024

#: Live bytes per block element, worst case (f32 in/out): x + residual +
#: s + out blocks plus three f32 temporaries (upcast, centred, product).
_BYTES_PER_ELEMENT = 28

_ROW_BLOCK_CANDIDATES = (512, 256, 128, 64, 32, 16, 8)


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _pick_rows_block(rows: int, hidden: int) -> int:
    """Largest sublane-aligned candidate that tiles ``rows`` and keeps the
    block's live VMEM under `_FUSED_NORM_VMEM_BYTES`."""
    for b in _ROW_BLOCK_CANDIDATES:
        if rows % b == 0 and b * hidden * _BYTES_PER_ELEMENT <= \
                _FUSED_NORM_VMEM_BYTES:
            return b
    return 0


def fused_norm_supported(x: jax.Array, residual: jax.Array | None = None
                         ) -> bool:
    """True when the fused kernel applies to this activation shape: hidden
    dim lane-aligned (multiple of 128), the second-minor (seq) dim tiling
    into a sublane-aligned block that fits the VMEM budget, and a float
    compute dtype. Shapes this rejects keep the unfused jnp path —
    today's behavior, never silence.

    The kernel blocks the *native-rank* array over its ``-2`` axis
    (leading dims become grid dims) rather than flattening to
    ``[rows, hidden]``: a rank change perturbs XLA's reduce codegen by an
    ulp, which would break the bitwise-f32 contract with the fallback.
    """
    if pltpu is None:
        return False
    if x.ndim < 2:
        return False
    if residual is not None and (residual.shape != x.shape
                                 or residual.dtype != x.dtype):
        return False
    if x.dtype not in (jnp.float32, jnp.bfloat16, jnp.float16):
        return False
    hidden = x.shape[-1]
    if hidden < 128 or hidden % 128:
        return False
    total_rows = 1
    for d in x.shape[:-1]:
        total_rows *= d
    if total_rows * hidden * _BYTES_PER_ELEMENT <= _FUSED_NORM_VMEM_BYTES:
        return True  # whole array in one block (also the bitwise-pin path)
    return _pick_rows_block(x.shape[-2], hidden) > 0


def _fwd_kernel(*refs, eps: float, have_residual: bool):
    """One row block: (optional) residual add, f32 LayerNorm, affine, cast.

    Op-for-op the unfused `models/gpt/model.py:LayerNorm` body, so the
    forward is bitwise identical to the fallback in f32.
    """
    if have_residual:
        (x_ref, r_ref, scale_ref, bias_ref,
         out_ref, s_ref, mean_ref, var_ref) = refs
        s = r_ref[...] + x_ref[...]
        s_ref[...] = s
    else:
        x_ref, scale_ref, bias_ref, out_ref, mean_ref, var_ref = refs
        s = x_ref[...]
    x32 = s.astype(jnp.float32)
    mean = x32.mean(-1, keepdims=True)
    var = ((x32 - mean) ** 2).mean(-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    out_ref[...] = (y * scale_ref[...] + bias_ref[...]).astype(out_ref.dtype)
    mean_ref[...] = mean
    var_ref[...] = var


def _bwd_kernel(*refs, eps: float, hidden: int, have_dsin: bool):
    """One row block of the LayerNorm backward from saved ``(mean, var)``.

    Transcribes the exact op sequence JAX autodiff derives for the unfused
    forward (see module docstring): ``rstd``/``u`` recomputed from the
    saved stats reproduce the forward values bitwise, the two ``dxc``
    branches accumulate var-branch-first, the downstream residual-stream
    cotangent ``ds_in`` (when present) joins the accumulation FIRST —
    ``((ds_in + dxc_b) + dxc_a) + dmean_term``, autodiff's ``add_any``
    chain at the residual-sum node — and ``dmean`` sums each branch
    separately before combining. These orderings make f32 grads bitwise
    equal to the fallback. ``dscale``/``dbias`` are *not* computed here:
    the caller re-derives ``y`` from the saved stats with plain jnp ops
    so their reduce sees the same fusion context the unfused graph has.
    """
    if have_dsin:
        (s_ref, scale_ref, mean_ref, var_ref, do_ref, dsin_ref,
         dx_ref) = refs
    else:
        s_ref, scale_ref, mean_ref, var_ref, do_ref, dx_ref = refs
    s32 = s_ref[...].astype(jnp.float32)
    mean = mean_ref[...]
    var = var_ref[...]
    u = var + eps
    rstd = jax.lax.rsqrt(u)
    xc = s32 - mean
    dout = do_ref[...].astype(jnp.float32)
    dy = dout * scale_ref[...].astype(jnp.float32)
    dxc_a = dy * rstd
    drstd = (xc * dy).sum(-1, keepdims=True)
    e_res = -0.5 * (rstd / u)
    f_res = 2.0 * xc
    dxc_b = ((drstd * e_res) / hidden) * f_res
    if have_dsin:
        acc = (dsin_ref[...].astype(jnp.float32) + dxc_b) + dxc_a
    else:
        acc = dxc_b + dxc_a
    dmean = (jnp.negative(dxc_b).sum(-1, keepdims=True)
             + jnp.negative(dxc_a).sum(-1, keepdims=True))
    dx_ref[...] = (acc + dmean / hidden).astype(dx_ref.dtype)


def _specs(shape, hidden):
    """Native-rank BlockSpecs. Keeping the operands at their original
    rank keeps the interpret-mode lowering's op shapes identical to the
    unfused graph's — a flatten-to-``[rows, hidden]`` reshape perturbs
    XLA's reduce codegen by an ulp and breaks the bitwise-f32 contract.

    When the whole array fits the VMEM budget, a single whole-array
    block (grid of one) is used: the kernel body then runs at exactly
    the unfused graph's shapes, which pins every internal reduce's
    codegen too. Larger arrays block the ``-2`` (seq) axis into
    sublane-aligned rows with the leading dims as grid dims."""
    nd = len(shape)
    total_rows = 1
    for d in shape[:-1]:
        total_rows *= d
    if total_rows * hidden * _BYTES_PER_ELEMENT <= _FUSED_NORM_VMEM_BYTES:
        grid = (1,)
        row_spec = pl.BlockSpec(shape, lambda i: (0,) * nd)
        stat_spec = pl.BlockSpec(shape[:-1] + (1,), lambda i: (0,) * nd)
        vec_spec = pl.BlockSpec((1,) * (nd - 1) + (hidden,),
                                lambda i: (0,) * nd)
        return grid, row_spec, stat_spec, vec_spec
    br = _pick_rows_block(shape[-2], hidden)
    lead = shape[:-2]
    ones = (1,) * len(lead)
    grid = lead + (shape[-2] // br,)
    row_spec = pl.BlockSpec(ones + (br, hidden), lambda *i: (*i, 0))
    stat_spec = pl.BlockSpec(ones + (br, 1), lambda *i: (*i, 0))
    vec_spec = pl.BlockSpec(ones + (1, hidden), lambda *i: (0,) * nd)
    return grid, row_spec, stat_spec, vec_spec


def _fwd_call(x, r, scale_v, bias_v, eps, out_dtype):
    """Dispatch the forward kernel on native-rank operands."""
    shape = x.shape
    hidden = shape[-1]
    stat_shape = shape[:-1] + (1,)
    vec_shape = (1,) * (len(shape) - 1) + (hidden,)
    grid, row_spec, stat_spec, vec_spec = _specs(shape, hidden)
    scale_v = scale_v.astype(jnp.float32).reshape(vec_shape)
    bias_v = bias_v.astype(jnp.float32).reshape(vec_shape)
    have_residual = r is not None
    in_specs = [row_spec] + ([row_spec] if have_residual else []) + \
        [vec_spec, vec_spec]
    out_specs = [row_spec] + ([row_spec] if have_residual else []) + \
        [stat_spec, stat_spec]
    out_shape = [jax.ShapeDtypeStruct(shape, out_dtype)] + \
        ([jax.ShapeDtypeStruct(shape, x.dtype)] if have_residual else []) + \
        [jax.ShapeDtypeStruct(stat_shape, jnp.float32),
         jax.ShapeDtypeStruct(stat_shape, jnp.float32)]
    operands = (x, r, scale_v, bias_v) if have_residual else \
        (x, scale_v, bias_v)
    outs = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps, have_residual=have_residual),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interpret(),
        name="fused_norm_fwd",
    )(*operands)
    if have_residual:
        out, s, mean, var = outs
    else:
        out, mean, var = outs
        s = x
    return out, s, mean, var


def _bwd_call(s, scale_v, mean, var, do, eps, ds_in=None):
    """Dispatch the backward kernel on native-rank operands."""
    shape = s.shape
    hidden = shape[-1]
    vec_shape = (1,) * (len(shape) - 1) + (hidden,)
    grid, row_spec, stat_spec, vec_spec = _specs(shape, hidden)
    scale_v = scale_v.astype(jnp.float32).reshape(vec_shape)
    have_dsin = ds_in is not None
    in_specs = [row_spec, vec_spec, stat_spec, stat_spec, row_spec] + \
        ([row_spec] if have_dsin else [])
    operands = (s, scale_v, mean, var, do) + \
        ((ds_in,) if have_dsin else ())
    dx = pl.pallas_call(
        functools.partial(_bwd_kernel, eps=eps, hidden=hidden,
                          have_dsin=have_dsin),
        grid=grid,
        in_specs=in_specs,
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct(shape, s.dtype),
        interpret=_interpret(),
        name="fused_norm_bwd",
    )(*operands)
    return dx


def _param_grads(s, mean, var, dout, eps, scale_dtype):
    """``dscale``/``dbias`` via the unfused backward's exact subgraph.

    Re-derives ``y`` from the saved ``(s, mean, var)`` with plain jnp ops
    at the cotangent's original shape, so the elementwise-then-reduce
    chain compiles identically to the unfused backward's and stays
    bitwise in f32 (a pallas-emitted ``y`` lands in a different fusion
    context and drifts by an ulp). It is also cheaper: no extra f32 row
    buffer round-trips HBM — the recompute fuses into the reduce.
    """
    lead = tuple(range(dout.ndim - 1))
    y = (s.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + eps)
    dout32 = dout.astype(jnp.float32)
    dscale = (y * dout32).sum(axis=lead).astype(scale_dtype)
    dbias = dout32.sum(axis=lead).astype(scale_dtype)
    return dscale, dbias


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _fused_add_norm(x, residual, scale, bias, eps, out_dtype):
    """Primal: ``s = residual + x``; return ``(LN(s).astype(out_dtype), s)``."""
    primal, _ = _fused_add_norm_fwd(x, residual, scale, bias, eps, out_dtype)
    return primal


def _fused_add_norm_fwd(x, residual, scale, bias, eps, out_dtype):
    out, s, mean, var = _fwd_call(x, residual, scale, bias, eps, out_dtype)
    return (out, s), (s, scale, mean, var)


def _fused_add_norm_bwd(eps, out_dtype, res, cts):
    s, scale, mean, var = res
    dout, ds_in = cts
    ds = _bwd_call(s, scale, mean, var, dout, eps, ds_in=ds_in)
    dscale, dbias = _param_grads(s, mean, var, dout, eps, scale.dtype)
    return ds, ds, dscale, dbias


_fused_add_norm.defvjp(_fused_add_norm_fwd, _fused_add_norm_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused_norm(x, scale, bias, eps, out_dtype):
    """Primal: ``LN(x).astype(out_dtype)`` with no residual add."""
    return _fused_norm_fwd(x, scale, bias, eps, out_dtype)[0]


def _fused_norm_fwd(x, scale, bias, eps, out_dtype):
    out, s, mean, var = _fwd_call(x, None, scale, bias, eps, out_dtype)
    return out, (s, scale, mean, var)


def _fused_norm_bwd(eps, out_dtype, res, cts):
    s, scale, mean, var = res
    dx = _bwd_call(s, scale, mean, var, cts, eps)
    dscale, dbias = _param_grads(s, mean, var, cts, eps, scale.dtype)
    return dx, dscale, dbias


_fused_norm.defvjp(_fused_norm_fwd, _fused_norm_bwd)


def fused_residual_norm(x: jax.Array, scale: jax.Array, bias: jax.Array,
                        residual: jax.Array | None = None, *,
                        eps: float = 1e-5,
                        out_dtype=jnp.float32):
    """Fused (residual-add +) f32 LayerNorm + cast; the public entry point.

    Returns ``(out, s)`` where ``s = residual + x`` (or ``x`` when
    ``residual`` is None — the norm-only sites ``ln1``/``ln_f``) and
    ``out = LayerNorm_f32(s).astype(out_dtype)``. Callers must gate on
    `fused_norm_supported` first; this function assumes the shape was
    admitted.
    """
    if residual is None:
        return _fused_norm(x, scale, bias, float(eps), out_dtype), x
    return _fused_add_norm(x, residual, scale, bias, float(eps), out_dtype)
