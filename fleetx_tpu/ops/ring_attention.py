"""Ring attention over the ``seq`` mesh axis — long-context parallelism.

The reference tops out at Megatron-SP over the TP group (activations
scattered 1/mp along sequence between blocks,
``ppfleetx/models/language_model/gpt/dygraph/sequence_parallel_utils.py:150-326``)
and trains seq_len 1024; it has NO ring/context/blockwise attention anywhere
(SURVEY.md §5). This module is the idiomatic TPU superset: sequence-sharded
attention where K/V blocks rotate around the ``seq`` ring via
``lax.ppermute`` (one ICI hop per step) while each device folds the incoming
block into an online-softmax accumulator — flash attention's streaming
update, distributed.

Written as a *partial-manual* ``jax.shard_map``: only ``seq`` is manual, so
GSPMD still handles dp/fsdp/tensor sharding of the same operands inside the
body. Causality with contiguous block sharding means block ``j`` contributes
to queries of block ``i`` only when ``j <= i``; later blocks are masked (the
compute is uniform across ring steps — the standard ring-attention bubble).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

__all__ = ["ring_attention", "ring_attention_local"]

_NEG_INF = -1e30


def ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array, *,
                         axis_name: str = "seq", causal: bool = True,
                         kv_chunk: int | None = None) -> jax.Array:
    """Per-device body; call inside ``shard_map`` with ``axis_name`` manual.

    q/k/v: [batch, s_local, heads, head_dim] — the local sequence block.
    Returns the exact softmax(QK^T)V rows for the local queries.

    ``kv_chunk`` streams each incoming K/V block through the online-softmax
    accumulator in chunks, bounding the live score tensor to
    ``[b, n, s_local, kv_chunk]`` instead of ``[b, n, s_local, s_local]`` —
    at 8k tokens over seq4 that is the difference between ~270MB and ~2.1GB
    of f32 scores per ring step. Exact (online softmax), differentiable
    (plain ``lax.scan``); must divide the local block length.
    """
    ring = lax.static_axis_size(axis_name) if hasattr(lax, "static_axis_size") \
        else lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, s_loc, n, d = q.shape
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    chunk = int(kv_chunk) if kv_chunk else s_loc
    if s_loc % chunk:
        raise ValueError(f"kv_chunk {chunk} must divide the local block "
                         f"length {s_loc}")
    n_chunks = s_loc // chunk

    q32 = q.astype(jnp.float32)
    qpos = me * s_loc + jnp.arange(s_loc)

    def fold(acc, xs):
        """One K/V chunk through the streaming softmax update."""
        m, l, o = acc
        k_c, v_c, kpos_c = xs
        s = jnp.einsum("bqnd,bknd->bnqk", q32, k_c.astype(jnp.float32)) * scale
        if causal:
            mask = kpos_c[None, :] <= qpos[:, None]  # [q, k]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * corr + p.sum(axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bnqk,bknd->bnqd", p, v_c.astype(jnp.float32))
        return (m_new, l_new, o_new), None

    def step(carry, t):
        k_cur, v_cur, m, l, o = carry
        j = (me - t) % ring  # whose block we hold at step t
        kpos = j * s_loc + jnp.arange(s_loc)
        k_ch = jnp.moveaxis(k_cur.reshape(b, n_chunks, chunk, n, d), 1, 0)
        v_ch = jnp.moveaxis(v_cur.reshape(b, n_chunks, chunk, n, d), 1, 0)
        # remat the fold: without it lax.scan stacks each chunk's p
        # residuals across iterations and backward peaks at the full
        # [s_loc, s_loc] score tensor anyway — recompute per chunk instead
        (m, l, o), _ = lax.scan(jax.checkpoint(fold), (m, l, o),
                                (k_ch, v_ch, kpos.reshape(n_chunks, chunk)))
        perm = [(r, (r + 1) % ring) for r in range(ring)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m, l, o), None

    m0 = jnp.full((b, n, s_loc), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, n, s_loc), jnp.float32)
    o0 = jnp.zeros((b, n, s_loc, d), jnp.float32)
    (_, _, _, l, o), _ = lax.scan(step, (k, v, m0, l0, o0),
                                  jnp.arange(ring))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   causal: bool = True, axis_name: str = "seq",
                   kv_chunk: int | None = None, mesh=None) -> jax.Array:
    """Sequence-parallel attention: q/k/v ``[b, s, n, d]`` with ``s`` sharded
    over ``axis_name``. Must run inside jit under the mesh context (the
    engine's ``_ctx``); all other axes stay GSPMD-automatic. ``kv_chunk``
    bounds per-ring-step score memory (see ``ring_attention_local``)."""
    if mesh is None:
        from fleetx_tpu.parallel.mesh import current_mesh

        mesh = current_mesh()
    assert mesh is not None, "ring_attention needs an ambient or explicit mesh"
    spec = P(None, axis_name)
    fn = jax.shard_map(
        partial(ring_attention_local, axis_name=axis_name, causal=causal,
                kv_chunk=kv_chunk),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axis_names=frozenset({axis_name}), check_vma=False)
    return fn(q, k, v)
