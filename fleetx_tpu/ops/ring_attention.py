"""Ring attention over the ``seq`` mesh axis — long-context parallelism.

The reference tops out at Megatron-SP over the TP group (activations
scattered 1/mp along sequence between blocks,
``ppfleetx/models/language_model/gpt/dygraph/sequence_parallel_utils.py:150-326``)
and trains seq_len 1024; it has NO ring/context/blockwise attention anywhere
(SURVEY.md §5). This module is the idiomatic TPU superset: sequence-sharded
attention where K/V blocks rotate around the ``seq`` ring via
``lax.ppermute`` (one ICI hop per step) while each device folds the incoming
block into an online-softmax accumulator — flash attention's streaming
update, distributed.

Written as a *partial-manual* ``jax.shard_map``: only ``seq`` is manual, so
GSPMD still handles dp/fsdp/tensor sharding of the same operands inside the
body. Causality with contiguous block sharding means block ``j`` contributes
to queries of block ``i`` only when ``j <= i``; later blocks are masked (the
compute is uniform across ring steps — the standard ring-attention bubble).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

__all__ = ["ring_attention", "ring_attention_local", "ring_flash_local",
           "flash_ring_supported"]

_NEG_INF = -1e30


def ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array, *,
                         axis_name: str = "seq", causal: bool = True,
                         kv_chunk: int | None = None) -> jax.Array:
    """Per-device body; call inside ``shard_map`` with ``axis_name`` manual.

    q/k/v: [batch, s_local, heads, head_dim] — the local sequence block.
    Returns the exact softmax(QK^T)V rows for the local queries.

    ``kv_chunk`` streams each incoming K/V block through the online-softmax
    accumulator in chunks, bounding the live score tensor to
    ``[b, n, s_local, kv_chunk]`` instead of ``[b, n, s_local, s_local]`` —
    at 8k tokens over seq4 that is the difference between ~270MB and ~2.1GB
    of f32 scores per ring step. Exact (online softmax), differentiable
    (plain ``lax.scan``); must divide the local block length.
    """
    ring = lax.static_axis_size(axis_name) if hasattr(lax, "static_axis_size") \
        else lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, s_loc, n, d = q.shape
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    chunk = int(kv_chunk) if kv_chunk else s_loc
    if s_loc % chunk:
        raise ValueError(f"kv_chunk {chunk} must divide the local block "
                         f"length {s_loc}")
    n_chunks = s_loc // chunk

    q32 = q.astype(jnp.float32)
    qpos = me * s_loc + jnp.arange(s_loc)

    def fold(acc, xs):
        """One K/V chunk through the streaming softmax update."""
        m, l, o = acc
        k_c, v_c, kpos_c = xs
        s = jnp.einsum("bqnd,bknd->bnqk", q32, k_c.astype(jnp.float32)) * scale
        if causal:
            mask = kpos_c[None, :] <= qpos[:, None]  # [q, k]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * corr + p.sum(axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bnqk,bknd->bnqd", p, v_c.astype(jnp.float32))
        return (m_new, l_new, o_new), None

    def step(carry, t):
        k_cur, v_cur, m, l, o = carry
        j = (me - t) % ring  # whose block we hold at step t
        kpos = j * s_loc + jnp.arange(s_loc)
        k_ch = jnp.moveaxis(k_cur.reshape(b, n_chunks, chunk, n, d), 1, 0)
        v_ch = jnp.moveaxis(v_cur.reshape(b, n_chunks, chunk, n, d), 1, 0)
        # remat the fold: without it lax.scan stacks each chunk's p
        # residuals across iterations and backward peaks at the full
        # [s_loc, s_loc] score tensor anyway — recompute per chunk instead
        (m, l, o), _ = lax.scan(jax.checkpoint(fold), (m, l, o),
                                (k_ch, v_ch, kpos.reshape(n_chunks, chunk)))
        perm = [(r, (r + 1) % ring) for r in range(ring)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m, l, o), None

    m0 = jnp.full((b, n, s_loc), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, n, s_loc), jnp.float32)
    o0 = jnp.zeros((b, n, s_loc, d), jnp.float32)
    (_, _, _, l, o), _ = lax.scan(step, (k, v, m0, l0, o0),
                                  jnp.arange(ring))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


# ---------------------------------------------------------------------------
# flash-composed ring (VERDICT r3 #9): the per-ring-step block attention runs
# on the Pallas MXU kernels instead of einsums-in-HBM
# ---------------------------------------------------------------------------


def flash_ring_supported(q: jax.Array, ring: int) -> bool:
    """True when each device's local block (global seq / ``ring``) satisfies
    the Pallas kernel contract."""
    from fleetx_tpu.ops import flash_attention as fa

    if fa.pltpu is None or q.ndim != 4 or q.shape[1] % max(ring, 1):
        return False
    s_loc, d = q.shape[1] // ring, q.shape[3]
    return s_loc >= 128 and s_loc % 128 == 0 and d in (64, 128, 256)


def _to3(x):
    b, s, n, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * n, s, d)


def _ring_perm(axis_name):
    ring = lax.axis_size(axis_name)
    return [(r, (r + 1) % ring) for r in range(ring)]


def _ring_flash_fwd_pass(q3, k3, v3, axis_name, block):
    """Ring forward on the Pallas kernel: per-step (out, lse) folded through
    the online-logsumexp merge. Block structure per device ``me`` at step
    ``t`` (holding block ``j = (me - t) % ring``): ``t == 0`` → causal
    self-block; ``t <= me`` → fully-visible earlier block; else skipped."""
    from fleetx_tpu.ops import flash_attention as fa

    ring = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    bn, s, d = q3.shape
    scale = d ** -0.5
    seed = jnp.zeros((1,), jnp.int32)

    def block_fwd(k_b, v_b, causal):
        return fa._fwd(q3, k_b, v_b, seed, scale=scale, causal=causal,
                       block_q=block, block_k=block, dropout_rate=0.0)

    out, lse = block_fwd(k3, v3, True)  # t = 0: the causal diagonal
    out = out.astype(jnp.float32)
    k_cur, v_cur = k3, v3
    for t in range(1, ring):
        k_cur = lax.ppermute(k_cur, axis_name, _ring_perm(axis_name))
        v_cur = lax.ppermute(v_cur, axis_name, _ring_perm(axis_name))

        def visible(args):
            o_acc, l_acc, k_b, v_b = args
            o_t, l_t = block_fwd(k_b, v_b, False)
            l_new = jnp.logaddexp(l_acc, l_t)
            o_new = (o_acc * jnp.exp(l_acc - l_new)[..., None]
                     + o_t.astype(jnp.float32)
                     * jnp.exp(l_t - l_new)[..., None])
            return o_new, l_new

        out, lse = lax.cond(t <= me, visible,
                            lambda args: (args[0], args[1]),
                            (out, lse, k_cur, v_cur))
    return out.astype(q3.dtype), lse


def ring_flash_local(q: jax.Array, k: jax.Array, v: jax.Array, *,
                     axis_name: str = "seq") -> jax.Array:
    """Causal ring attention whose per-block math runs on the Pallas flash
    kernels (``ops/flash_attention.py``) — forward merges per-block
    (out, lse) pairs; backward re-rotates K/V and runs the dq/dkv kernels
    against the GLOBAL logsumexp. Exact, differentiable, O(s_local) memory.

    Same contract as ``ring_attention_local`` (call inside ``shard_map``
    with ``axis_name`` manual; q/k/v ``[b, s_local, n, d]``), restricted to
    causal self-attention without dropout.
    """
    from fleetx_tpu.ops import flash_attention as fa

    b, s_loc, n, d = q.shape
    block = fa.pick_block(s_loc, d)
    q3, k3, v3 = _to3(q), _to3(k), _to3(v)
    out3 = _ring_flash3(q3, k3, v3, axis_name, block)
    return out3.reshape(b, n, s_loc, d).transpose(0, 2, 1, 3)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ring_flash3(q3, k3, v3, axis_name, block):
    out, _ = _ring_flash_fwd_pass(q3, k3, v3, axis_name, block)
    return out


def _ring_flash3_fwd(q3, k3, v3, axis_name, block):
    out, lse = _ring_flash_fwd_pass(q3, k3, v3, axis_name, block)
    return out, (q3, k3, v3, out, lse)


def _ring_flash3_bwd(axis_name, block, residuals, g):
    from fleetx_tpu.ops import flash_attention as fa

    q3, k3, v3, out, lse = residuals
    do = g
    ring = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    bn, s, d = q3.shape
    scale = d ** -0.5
    seed = jnp.zeros((1,), jnp.int32)
    # p = exp(s - GLOBAL lse) makes the per-block backward exact
    delta = (out.astype(jnp.float32) * do.astype(jnp.float32)).sum(axis=-1)
    lse3, delta3 = lse[..., None], delta[..., None]

    def block_bwd(k_b, v_b, causal):
        dq_b = fa._bwd_dq(q3, k_b, v_b, do, lse3, delta3, seed, scale=scale,
                          causal=causal, block_q=block, block_k=block)
        dk_b, dv_b = fa._bwd_dkv(q3, k_b, v_b, do, lse3, delta3, seed,
                                 scale=scale, causal=causal, block_q=block,
                                 block_k=block)
        return dq_b, dk_b, dv_b

    dq_d, dk_d, dv_d = block_bwd(k3, v3, True)  # diagonal
    dq = dq_d.astype(jnp.float32)
    k_cur, v_cur = k3, v3
    dk_cur = dk_d.astype(jnp.float32)
    dv_cur = dv_d.astype(jnp.float32)
    for t in range(1, ring):
        # dk/dv accumulators travel WITH their k/v block around the ring
        k_cur = lax.ppermute(k_cur, axis_name, _ring_perm(axis_name))
        v_cur = lax.ppermute(v_cur, axis_name, _ring_perm(axis_name))
        dk_cur = lax.ppermute(dk_cur, axis_name, _ring_perm(axis_name))
        dv_cur = lax.ppermute(dv_cur, axis_name, _ring_perm(axis_name))

        def visible(args):
            dq_acc, dk_acc, dv_acc, k_b, v_b = args
            dq_b, dk_b, dv_b = block_bwd(k_b, v_b, False)
            return (dq_acc + dq_b.astype(jnp.float32),
                    dk_acc + dk_b.astype(jnp.float32),
                    dv_acc + dv_b.astype(jnp.float32))

        dq, dk_cur, dv_cur = lax.cond(
            t <= me, visible, lambda args: (args[0], args[1], args[2]),
            (dq, dk_cur, dv_cur, k_cur, v_cur))
    # after ring-1 hops the accumulators sit one hop short of home
    dk_cur = lax.ppermute(dk_cur, axis_name, _ring_perm(axis_name))
    dv_cur = lax.ppermute(dv_cur, axis_name, _ring_perm(axis_name))
    return (dq.astype(q3.dtype), dk_cur.astype(k3.dtype),
            dv_cur.astype(v3.dtype))


_ring_flash3.defvjp(_ring_flash3_fwd, _ring_flash3_bwd)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   causal: bool = True, axis_name: str = "seq",
                   kv_chunk: int | None = None, mesh=None,
                   use_flash: bool | None = None) -> jax.Array:
    """Sequence-parallel attention: q/k/v ``[b, s, n, d]`` with ``s`` sharded
    over ``axis_name``. Must run inside jit under the mesh context (the
    engine's ``_ctx``); all other axes stay GSPMD-automatic. ``kv_chunk``
    bounds per-ring-step score memory on the einsum path
    (see ``ring_attention_local``).

    ``use_flash`` None (auto) routes causal calls whose local block fits the
    Pallas contract through ``ring_flash_local`` — per-block attention on
    the MXU kernels, the einsum path kept as fallback/reference.
    """
    if mesh is None:
        from fleetx_tpu.parallel.mesh import current_mesh

        mesh = current_mesh()
    assert mesh is not None, "ring_attention needs an ambient or explicit mesh"
    ring = mesh.shape.get(axis_name, 1)
    if use_flash is None:
        use_flash = causal and flash_ring_supported(q, ring)
    body = (partial(ring_flash_local, axis_name=axis_name) if use_flash
            else partial(ring_attention_local, axis_name=axis_name,
                         causal=causal, kv_chunk=kv_chunk))
    spec = P(None, axis_name)
    fn = jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axis_names=frozenset({axis_name}), check_vma=False)
    return fn(q, k, v)
