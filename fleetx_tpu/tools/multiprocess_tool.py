"""Parallel shell-command runner for data preparation
(reference ``ppfleetx/tools/multiprocess_tool.py:49-87``).

Runs a list of shell commands with bounded parallelism and reports
failures — the reference uses it for sharded corpus download/convert jobs;
same contract here.
"""

from __future__ import annotations

import subprocess
from concurrent.futures import ThreadPoolExecutor, as_completed

from fleetx_tpu.utils.log import logger


def run_commands(commands: list[str], num_workers: int = 4,
                 stop_on_error: bool = False) -> list[int]:
    """Execute shell commands in parallel; returns per-command exit codes."""
    results = [None] * len(commands)

    def run(i: int) -> int:
        proc = subprocess.run(commands[i], shell=True,
                              capture_output=True, text=True)
        if proc.returncode != 0:
            logger.error("command failed (%d): %s\n%s", proc.returncode,
                         commands[i], proc.stderr[-500:])
        return proc.returncode

    with ThreadPoolExecutor(max_workers=num_workers) as pool:
        futures = {pool.submit(run, i): i for i in range(len(commands))}
        for fut in as_completed(futures):
            i = futures[fut]
            results[i] = fut.result()
            if stop_on_error and results[i] != 0:
                for other in futures:
                    other.cancel()
                break
    done = sum(1 for r in results if r == 0)
    logger.info("ran %d commands: %d ok, %d failed", len(commands), done,
                sum(1 for r in results if r not in (0, None)))
    return [r if r is not None else -1 for r in results]
