"""Parallel shell-command runner for data preparation
(reference ``ppfleetx/tools/multiprocess_tool.py:49-87``).

Runs a list of shell commands with bounded parallelism and reports
failures — the reference uses it for sharded corpus download/convert jobs;
same contract here. The returned exit codes distinguish every terminal
state a sharded prep job can reach: the command's own code, ``RC_TIMEOUT``
for a per-command deadline kill, and ``RC_CANCELLED`` for commands
``stop_on_error`` cancelled before they started — a cancelled shard needs
a re-run, a timed-out one needs a bigger deadline or a smaller shard, and
conflating them (the old single ``-1``) hid which.
"""

from __future__ import annotations

import os
import signal
import subprocess
from concurrent.futures import ThreadPoolExecutor, as_completed
from typing import Optional

from fleetx_tpu.utils.log import logger

#: command never started: stop_on_error cancelled it while still queued
RC_CANCELLED = -1
#: command killed by its per-command ``timeout`` deadline
RC_TIMEOUT = -2


def run_commands(commands: list[str], num_workers: int = 4,
                 stop_on_error: bool = False,
                 timeout: Optional[float] = None) -> list[int]:
    """Execute shell commands in parallel; returns per-command exit codes.

    ``timeout`` (seconds, per command) kills an overrunning command and
    records ``RC_TIMEOUT`` for it. With ``stop_on_error``, the first
    non-zero exit cancels all not-yet-started commands (``RC_CANCELLED``);
    commands already running are allowed to finish and report their REAL
    code — the old behaviour lumped them in with the failures as ``-1``.
    """
    results: list = [None] * len(commands)

    def run(i: int) -> int:
        # own session so a timeout kill reaches the WHOLE pipeline: with
        # shell=True a plain timeout kills only the shell, and the
        # `wget | tar` grandchildren keep writing the shard after
        # RC_TIMEOUT was reported — the re-run then races the orphan
        proc = subprocess.Popen(commands[i], shell=True,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True,
                                start_new_session=True)
        try:
            _, stderr = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            proc.communicate()
            logger.error("command timed out after %.0fs: %s", timeout,
                         commands[i])
            return RC_TIMEOUT
        rc = proc.returncode
        if rc < 0:
            # shell killed by signal N: report the 128+N shell convention —
            # a raw negative collides with the RC_* sentinels (SIGINT
            # -> -2 reads as a timeout, SIGHUP -> -1 as a cancellation)
            rc = 128 - rc
        if rc != 0:
            logger.error("command failed (%d): %s\n%s", rc, commands[i],
                         stderr[-500:])
        return rc

    with ThreadPoolExecutor(max_workers=num_workers) as pool:
        futures = {pool.submit(run, i): i for i in range(len(commands))}
        for fut in as_completed(futures):
            results[futures[fut]] = fut.result()
            if stop_on_error and results[futures[fut]] != 0:
                for other in futures:
                    other.cancel()
                break
        # drain: in-flight commands run to completion (pool shutdown joins
        # them) and report their genuine code; only never-started ones are
        # recorded as cancelled
        for fut, i in futures.items():
            if results[i] is None:
                results[i] = RC_CANCELLED if fut.cancelled() else fut.result()
    ok = sum(1 for r in results if r == 0)
    timed_out = sum(1 for r in results if r == RC_TIMEOUT)
    cancelled = sum(1 for r in results if r == RC_CANCELLED)
    failed = len(results) - ok - timed_out - cancelled
    logger.info("ran %d commands: %d ok, %d failed, %d timed out, "
                "%d cancelled", len(commands), ok, failed, timed_out,
                cancelled)
    return results
