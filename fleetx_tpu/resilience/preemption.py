"""Graceful preemption: SIGTERM/SIGINT → checkpoint at the next step boundary.

TPU pools reclaim preemptible slices with a SIGTERM and a short grace
window; an unhandled signal kills the process mid-step and forfeits every
step since the last periodic save. ``PreemptionHandler`` converts the
signal into a flag the train loop polls at step boundaries: the engine
saves an emergency checkpoint (finalizing any outstanding async save so
the meta completion marker is durable), flushes telemetry, and exits with
a configurable code — rc 0 by default so supervisors treat a preemption
as a clean stop rather than a crash loop.

Installation is main-thread-only (CPython restriction); from any other
thread the handler degrades to a warning and the run keeps the default
signal behaviour.
"""

from __future__ import annotations

import contextlib
import os
import signal
import threading
from typing import Iterable, Optional

from fleetx_tpu.utils.log import logger

__all__ = ["PreemptionHandler"]

_DEFAULT_SIGNALS = ("SIGTERM", "SIGINT")


class PreemptionHandler:
    """Latching signal-to-flag bridge for graceful shutdown requests.

    ``installed()`` is a context manager scoped to one ``fit()``: previous
    handlers are restored on exit so nested engines (eval inside train,
    tests running many engines) never leak handler state.
    """

    def __init__(self, signals: Optional[Iterable[str]] = None):
        names = list(signals) if signals else list(_DEFAULT_SIGNALS)
        self._signums = [getattr(signal, n) for n in names
                         if hasattr(signal, n)]
        self._flag = threading.Event()
        self._previous: dict = {}

    # ------------------------------------------------------------- lifecycle
    def install(self) -> bool:
        """Register the handlers; False when not on the main thread."""
        try:
            for signum in self._signums:
                self._previous[signum] = signal.signal(signum, self._on_signal)
        except ValueError:  # signal only works in main thread
            self._previous.clear()
            logger.warning("preemption handler not installed (fit running "
                           "off the main thread); signals keep default "
                           "behaviour")
            return False
        return True

    def uninstall(self) -> None:
        """Restore whatever handlers were active before ``install()``."""
        for signum, prev in self._previous.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, OSError):  # interpreter shutdown / odd thread
                pass
        self._previous.clear()

    @contextlib.contextmanager
    def installed(self):
        """``with handler.installed():`` — install now, restore on exit."""
        self.install()
        try:
            yield self
        finally:
            self.uninstall()

    # -------------------------------------------------------------- signal
    def _on_signal(self, signum, frame) -> None:
        # latch only: everything heavy (checkpoint I/O, device syncs) is
        # forbidden in a signal handler; the train loop does the real work
        if self._flag.is_set():
            # second signal: the step boundary never came (hung step) or
            # the operator is insisting — restore the default handlers and
            # re-deliver so Ctrl-C/SIGTERM regain their normal teeth
            logger.error("second signal %d before the graceful exit "
                         "completed — restoring default handlers", signum)
            self.uninstall()
            os.kill(os.getpid(), signum)
            return
        self._flag.set()
        logger.warning("received signal %d — requesting graceful "
                       "checkpoint-and-exit at the next step boundary "
                       "(signal again to force the default behaviour)",
                       signum)

    def latch(self, reason: str = "gang agreement") -> None:
        """Latch without a local signal — the gang propagation path.

        When the preemption vote (``coordination.any_flag``) reports that
        ANOTHER rank received SIGTERM, every rank latches locally so the
        whole gang takes the same checkpoint-and-exit at the same step
        boundary; the local latch also keeps the second-signal escalation
        semantics intact if this rank later receives its own signal.
        """
        if not self._flag.is_set():
            self._flag.set()
            # flight evidence from the VOTE path only — never from the
            # signal handler itself (the ring's lock is not signal-safe)
            from fleetx_tpu.observability import flight

            flight.note("preemption", "latched", via=str(reason))
            logger.warning("preemption latched via %s — checkpoint-and-exit "
                           "at the next step boundary", reason)

    @property
    def triggered(self) -> bool:
        """True once any registered signal has been received."""
        return self._flag.is_set()

    def reset(self) -> None:
        """Clear the latch (tests / multi-fit engines)."""
        self._flag.clear()
