"""Fault-tolerant training runtime (docs/resilience.md).

FleetX's value proposition is keeping thousand-chip runs alive; the
reference delegates all fault handling to the Paddle substrate. This
package owns it natively, one module per failure mode:

- ``policy``       — retry/backoff-with-jitter + transient-vs-fatal
  classification (checkpoint I/O, downloads);
- ``preemption``   — SIGTERM/SIGINT → graceful checkpoint-and-exit at the
  next step boundary;
- ``guard``        — non-finite-streak / loss-spike policy with
  ``skip | rollback | abort`` actions;
- ``watchdog``     — hung-step heartbeat with stack dumps, plus the gang
  barrier mode that names straggler ranks;
- ``faults``       — deterministic fault injection driving the tests;
- ``coordination`` — cross-process agreement primitives (timed barrier,
  rank-0 broadcast, any-rank OR, majority vote) that turn each of the
  above into a gang-wide decision on multi-host pods;
- ``integrity``    — state-integrity layer: checkpoint digest manifests
  with verified restore + fall-back, the SDC sentinel's replay compare
  and cross-replica param fingerprint, and the supervisor preflight
  self-test.

``Resilience`` is the engine-facing facade built from the ``Resilience:``
YAML block (``utils/config.py``): with the block absent or disabled every
hook is a no-op and the train loop is byte-identical to the pre-resilience
engine. All recovery events surface as counters in the shared
observability registry (``nonfinite_skips``, ``rollbacks_total``,
``ckpt_retries_total``, ``preemption_exits``, ``watchdog_stalls``,
``ckpt_gc_total``).
"""

from __future__ import annotations

from typing import Optional

from fleetx_tpu.observability.metrics import get_registry
from fleetx_tpu.resilience import coordination
from fleetx_tpu.resilience import faults as faults_mod
from fleetx_tpu.resilience.coordination import (  # noqa: F401
    CoordinationTimeout, get_coordinator, most_severe)
from fleetx_tpu.resilience.faults import FaultPlan, InjectedFault  # noqa: F401
from fleetx_tpu.resilience.guard import (  # noqa: F401
    TrainingAborted, TrainingGuard)
from fleetx_tpu.resilience.integrity import (  # noqa: F401
    CheckpointIntegrityError, WriteVerifyError)
from fleetx_tpu.resilience.policy import (  # noqa: F401
    RetryPolicy, call_with_retry, is_transient, set_default_policy)
from fleetx_tpu.resilience.preemption import PreemptionHandler  # noqa: F401
from fleetx_tpu.resilience.watchdog import GangWatchdog, StepWatchdog  # noqa: F401

__all__ = [
    "Resilience", "RetryPolicy", "TrainingGuard", "TrainingAborted",
    "PreemptionHandler", "StepWatchdog", "GangWatchdog", "FaultPlan",
    "InjectedFault", "CoordinationTimeout", "CheckpointIntegrityError",
    "WriteVerifyError", "call_with_retry", "is_transient",
    "set_default_policy", "get_coordinator", "most_severe",
]

#: SDC sentinel actions, in the order the Integrity docs list them
SENTINEL_ACTIONS = ("log", "quarantine", "abort")


def _on(value, default: bool = True) -> bool:
    """A config value as a bool, with ``None``/absent meaning ``default``
    — the YAML zoo leaves opt-out knobs empty rather than writing
    ``false``. Takes the looked-up VALUE (callers keep the literal
    ``cfg.get("key")``) so fleetx-lint's dead-config-key rule still sees
    every key consumed at its call site."""
    return default if value is None else bool(value)


class Resilience:
    """Engine-facing facade over retry policy, guard, watchdog, preemption
    and fault injection.

    Built once per engine from the ``Resilience:`` config block. When the
    block is absent or ``enable`` is false, every attribute is inert — no
    signal handlers, no threads, no step-fn changes — and the process-wide
    fault plan / retry policy are reset to defaults so nothing leaks in
    from a previously-built engine.
    """

    def __init__(self, cfg: Optional[dict] = None):
        cfg = dict(cfg or {})
        self.enabled = bool(cfg.get("enable"))
        self.registry = get_registry()
        self.auto_resume = self.enabled and _on(cfg.get("auto_resume"))
        self.retry_policy = RetryPolicy.from_cfg(cfg.get("retry"))
        self.guard: Optional[TrainingGuard] = None
        self.guard_skip = False
        self.preemption: Optional[PreemptionHandler] = None
        self.preemption_save = True
        self.preemption_exit_code = 0
        self.watchdog_enabled = False
        self._watchdog_cfg: dict = {}
        self.preemption_sync_every = 1
        self.faults = FaultPlan()
        # state-integrity layer (docs/resilience.md "Integrity"): manifest
        # verification defaults ON even with the runtime disabled —
        # persisted state is never trusted blindly — while the sentinel is
        # strictly opt-in (cadence 0 keeps the train loop byte-identical)
        integ_cfg = dict(cfg.get("integrity") or {})
        self.integrity_verify = _on(integ_cfg.get("verify_checkpoints"))
        self.sentinel_every = 0
        self.sentinel_action = "log"
        if self.enabled:
            self.sentinel_every = max(
                int(integ_cfg.get("sentinel_every") or 0), 0)
            self.sentinel_action = str(
                integ_cfg.get("sentinel_action") or "log")
            if self.sentinel_action not in SENTINEL_ACTIONS:
                raise ValueError(
                    f"Resilience.integrity.sentinel_action must be one of "
                    f"{SENTINEL_ACTIONS}, got {self.sentinel_action!r}")
        if not self.enabled:
            # inert AND isolating: a disabled engine must not inherit a
            # previous engine's armed fault plan, tuned retry policy or
            # agreement deadlines (the globals are engine-scoped; the
            # newest engine wins)
            faults_mod.install_plan(None)
            set_default_policy(None)
            coordination.configure(None, None)
            return
        # gang agreement deadlines (docs/resilience.md multi-host section):
        # one knob pair shared by every collective the runtime issues
        coord_cfg = dict(cfg.get("coordination") or {})
        coordination.configure(coord_cfg.get("timeout_s"),
                               coord_cfg.get("poll_s"))
        # the process-wide default policy: checkpoint.py / download.py
        # retry under the engine's Resilience.retry settings
        set_default_policy(self.retry_policy)
        guard_cfg = dict(cfg.get("guard") or {})
        if _on(guard_cfg.get("enable")):
            # extend the fp16-only in-step isfinite skip to every dtype:
            # a non-finite update is dropped on-device, params survive
            self.guard_skip = _on(guard_cfg.get("skip_nonfinite_update"))
            self.guard = TrainingGuard.from_cfg(guard_cfg,
                                                skip_active=self.guard_skip,
                                                registry=self.registry)
        pre_cfg = dict(cfg.get("preemption") or {})
        if _on(pre_cfg.get("enable")):
            self.preemption = PreemptionHandler(pre_cfg.get("signals"))
        self.preemption_save = _on(pre_cfg.get("save_on_exit"))
        self.preemption_exit_code = int(pre_cfg.get("exit_code") or 0)
        # steps between gang preemption votes (multi-process only): 1 means
        # every step boundary is a legal gang-wide exit point
        self.preemption_sync_every = max(int(pre_cfg.get("sync_every") or 1),
                                         1)
        wd_cfg = dict(cfg.get("watchdog") or {})
        self.watchdog_enabled = bool(wd_cfg.get("enable"))
        self._watchdog_cfg = wd_cfg
        self.faults = FaultPlan.from_cfg(cfg.get("faults"))
        # module-level install so core/checkpoint.py's injection point
        # fires without config plumbing (cleared when this plan is unarmed)
        faults_mod.install_plan(self.faults)

    @property
    def preempted(self) -> bool:
        """True once a graceful-shutdown signal has been latched."""
        return self.preemption is not None and self.preemption.triggered

    def make_watchdog(self, on_stall=None) -> Optional[StepWatchdog]:
        """A fresh (un-started) watchdog per fit, or None when disabled."""
        if not (self.enabled and self.watchdog_enabled):
            return None
        return StepWatchdog.from_cfg(self._watchdog_cfg, on_stall=on_stall,
                                     registry=self.registry)

    def make_gang_watchdog(self, coord) -> Optional[GangWatchdog]:
        """The distributed watchdog mode (timed gang barrier every K steps),
        or None when the watchdog/gang mode is off or the gang has one
        member. Independent of the heartbeat thread: a pod can run both."""
        if not (self.enabled and self.watchdog_enabled):
            return None
        return GangWatchdog.from_cfg(self._watchdog_cfg, coord,
                                     registry=self.registry)
