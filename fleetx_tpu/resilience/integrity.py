"""State-integrity layer: digests, manifests, SDC fingerprints, preflight.

PRs 4 and 6 made the runtime survive *loud* failures; every byte the
system persists or computes was still trusted blindly. At fleet scale the
dominant UNDETECTED failure mode is silent data corruption — a bit-flipped
checkpoint shard, a truncated ``state.npz`` leaf, a defective core
corrupting one replica's params — so this module gives every piece of
state a verifiable identity:

- **content digests** (stdlib ``zlib.crc32`` — crc32c/xxhash-class speed,
  no new dependency): per-leaf digests of a state pytree and per-file
  digests of a checkpoint directory's payload;
- **integrity manifests** (``fleetx_integrity.json``): written next to
  the meta marker at save for BOTH codecs (Orbax and the per-rank npz
  path), re-verified on restore and by the offline auditor
  (``tools/verify_ckpt.py``);
- **params fingerprint**: a cheap on-device bit-content reduction of the
  param pytree, compared across dp-replicated ranks by the engine's SDC
  sentinel (``docs/resilience.md`` "Integrity");
- **preflight selftest** (``python -m fleetx_tpu.resilience.integrity
  --selftest``): a short compute+digest self-test ``tools/supervise.py
  --preflight`` runs per gang member before forming the gang.

Module-level imports stay stdlib+numpy so the selftest entry point and
the offline auditor run without dragging in jax; jax is imported lazily
where device arrays actually appear.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Any, Iterable, Optional

import numpy as np

from fleetx_tpu.utils.log import logger

__all__ = [
    "MANIFEST_NAME", "CheckpointIntegrityError", "WriteVerifyError",
    "atomic_write", "digest_bytes", "digest_array", "tree_digests",
    "file_digests", "write_manifest", "read_manifest", "verify_files",
    "verify_leaves", "verify_npz_leaves", "verify_checkpoint_dir",
    "params_fingerprint", "selftest",
]

#: manifest file name inside a ``step_<N>`` checkpoint directory
MANIFEST_NAME = "fleetx_integrity.json"

#: files that are checkpoint *metadata*, never digested as payload
_NON_PAYLOAD = {"fleetx_meta.json", MANIFEST_NAME}

#: streaming chunk for file digests (bounded memory on multi-GB shards)
_CHUNK = 1 << 20


class CheckpointIntegrityError(RuntimeError):
    """A checkpoint failed digest verification at restore.

    Deliberately NOT an ``OSError``: re-reading corrupt bytes does not
    un-corrupt them, so the retry policy must never absorb this — the
    caller's contract is a loud refusal plus fall-back to the newest
    checkpoint that *does* verify (``EagerEngine.load``).
    """


class WriteVerifyError(OSError):
    """A just-written checkpoint failed its read-back verification.

    An ``OSError`` on purpose: a torn write is transient-shaped — the
    retry policy re-dispatches the whole write — while a STICKY failure
    (a dying disk, an injected drill) exhausts the retries and surfaces
    as this error, which ``save_checkpoint`` turns into a failed
    ``ckpt_commit`` vote on gangs.
    """


def atomic_write(target: str, write, mode: str = "w") -> None:
    """Publish a file all-or-nothing: temp file + fsync + ``os.replace``,
    with the temp removed on any failure so a crashed writer never leaves
    a torn payload (or a truncated marker) behind the final name."""
    tmp = f"{target}.tmp.{os.getpid()}"
    try:
        with open(tmp, mode) as f:
            write(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, target)
    except BaseException:
        if os.path.exists(tmp):
            os.remove(tmp)
        raise


# ---------------------------------------------------------------------------
# digests
# ---------------------------------------------------------------------------

def digest_bytes(data: bytes, seed: int = 0) -> int:
    """crc32 of ``data`` (unsigned 32-bit int, stdlib-only)."""
    return zlib.crc32(data, seed) & 0xFFFFFFFF


def digest_array(arr: Any) -> dict:
    """Content digest of one array leaf: crc32 of its C-contiguous bytes
    plus the shape/dtype/nbytes needed to compare across codecs (the crc
    is byte-content only, so it survives leading-dim reshapes and the
    npy format's extension-dtype flattening to raw void)."""
    host = np.ascontiguousarray(np.asarray(arr))
    return {"crc32": digest_bytes(host.tobytes()),
            "dtype": str(host.dtype), "shape": list(host.shape),
            "nbytes": int(host.nbytes)}


def tree_digests(state: Any) -> list:
    """Per-leaf digests of a state pytree in flatten order — the order
    both checkpoint codecs store leaves in, so index ``i`` here is
    ``leaf_i`` on disk."""
    import jax

    return [digest_array(leaf)
            for leaf in jax.tree.leaves(jax.device_get(state))]


def _payload_files(path: str) -> Iterable[str]:
    """Relative paths of every payload file under ``path``, sorted for a
    deterministic manifest (metadata markers and temp litter excluded)."""
    out = []
    for root, _, names in os.walk(path):
        for name in names:
            if name in _NON_PAYLOAD or ".tmp." in name:
                continue
            out.append(os.path.relpath(os.path.join(root, name), path))
    return sorted(out)


def _digest_file(target: str) -> dict:
    """Streaming crc32 + size of one file."""
    crc = 0
    size = 0
    with open(target, "rb") as f:
        while True:
            chunk = f.read(_CHUNK)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            size += len(chunk)
    return {"crc32": crc & 0xFFFFFFFF, "size": size}


def file_digests(path: str) -> dict:
    """Relative path → ``{crc32, size}`` for every payload file under a
    checkpoint step directory (recursive — Orbax nests its shard files
    under ``state/``)."""
    return {rel: _digest_file(os.path.join(path, rel))
            for rel in _payload_files(path)}


# ---------------------------------------------------------------------------
# manifests
# ---------------------------------------------------------------------------

def write_manifest(path: str, leaves: Optional[list] = None) -> dict:
    """Digest the payload files under ``path`` (which must be durable by
    now — after the commit barrier on gangs) and atomically publish the
    integrity manifest; ``leaves`` carries the per-leaf digests computed
    from the in-memory state at save time. Returns the manifest dict."""
    manifest = {"version": 1, "files": file_digests(path)}
    if leaves is not None:
        manifest["leaves"] = leaves
    atomic_write(os.path.join(path, MANIFEST_NAME),
                 lambda f: json.dump(manifest, f))
    return manifest


def read_manifest(path: str) -> Optional[dict]:
    """The step dir's integrity manifest, or None when absent/corrupt
    (corrupt manifests log a warning — the checkpoint is then treated as
    unverifiable, exactly like a pre-integrity checkpoint)."""
    target = os.path.join(path, MANIFEST_NAME)
    if not os.path.exists(target):
        return None
    try:
        with open(target) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError, ValueError) as e:
        logger.warning("corrupt integrity manifest %s (%s) — treating %s "
                       "as unverifiable", target, e, path)
        return None
    if not isinstance(manifest, dict) or "files" not in manifest:
        logger.warning("malformed integrity manifest %s — treating %s as "
                       "unverifiable", target, path)
        return None
    return manifest


# ---------------------------------------------------------------------------
# verification
# ---------------------------------------------------------------------------

def verify_files(path: str, manifest: dict) -> list:
    """Re-digest the manifest's files on disk; returns the relative paths
    that are missing or whose crc32/size changed (empty = verified)."""
    bad = []
    for rel, want in sorted(manifest.get("files", {}).items()):
        target = os.path.join(path, rel)
        if not os.path.exists(target):
            bad.append(rel)
            continue
        got = _digest_file(target)
        if got["crc32"] != int(want["crc32"]) or \
                got["size"] != int(want["size"]):
            bad.append(rel)
    return bad


def verify_leaves(arrays: Iterable[Any], manifest_leaves: list) -> list:
    """Compare loaded leaf arrays against their manifest digests; returns
    the mismatching leaf indices.

    Only byte content is compared (crc32 + nbytes): leading-dim reshapes
    and the npy format's void-view of extension dtypes keep the bytes
    identical. A leaf whose byte COUNT differs from the manifest was
    restored under a changed precision config (a legitimate recast), so
    it is skipped — content verification across a dtype cast is
    impossible by construction.
    """
    bad = []
    for i, arr in enumerate(arrays):
        if i >= len(manifest_leaves):
            break
        want = manifest_leaves[i]
        host = np.ascontiguousarray(np.asarray(arr))
        if int(host.nbytes) != int(want["nbytes"]):
            continue  # recast on restore — not comparable
        if digest_bytes(host.tobytes()) != int(want["crc32"]):
            bad.append(i)
    return bad


def verify_npz_leaves(path: str, manifest_leaves: list,
                      npz_name: str = "state.npz") -> list:
    """Read-back verification of a just-written (or about-to-be-restored)
    npz snapshot: reload every leaf from disk and compare its bytes
    against the in-memory digests; returns mismatching leaf indices. An
    archive too corrupt to decode at all (the zip layer's own CRC check
    fires first) reports EVERY leaf as mismatched rather than leaking the
    decoder's exception."""
    bad = []
    try:
        with np.load(os.path.join(path, npz_name)) as data:
            for i, want in enumerate(manifest_leaves):
                key = f"leaf_{i}"
                if key not in data:
                    bad.append(i)
                    continue
                host = np.ascontiguousarray(data[key])
                if int(host.nbytes) != int(want["nbytes"]) or \
                        digest_bytes(host.tobytes()) != int(want["crc32"]):
                    bad.append(i)
    except Exception as e:  # noqa: BLE001 — undecodable == all corrupt
        logger.warning("npz snapshot %s unreadable during verification "
                       "(%s: %s)", os.path.join(path, npz_name),
                       type(e).__name__, e)
        return list(range(len(manifest_leaves)))
    return bad


def verify_checkpoint_dir(path: str, files_only: bool = False) -> dict:
    """Offline verification of one ``step_<N>`` directory.

    Returns ``{"status": "ok" | "corrupt" | "unverified",
    "files_checked": N, "leaves_checked": N, "mismatched_files": [...],
    "mismatched_leaves": [...]}``. ``unverified`` means no (readable)
    manifest — a pre-integrity checkpoint, usable but unprovable.
    ``files_only`` skips the npz leaf decode (the file digest already
    covers every byte of the archive) — the cheap form resume targeting
    uses, since the restore itself re-verifies leaves anyway.
    """
    manifest = read_manifest(path)
    if manifest is None:
        return {"status": "unverified", "files_checked": 0,
                "leaves_checked": 0, "mismatched_files": [],
                "mismatched_leaves": []}
    bad_files = verify_files(path, manifest)
    bad_leaves: list = []
    leaves = manifest.get("leaves")
    leaves_checked = 0
    npz = os.path.join(path, "state.npz")
    if not files_only and leaves and os.path.exists(npz):
        leaves_checked = len(leaves)
        try:
            bad_leaves = verify_npz_leaves(path, leaves)
        except Exception as e:  # noqa: BLE001 — unreadable == corrupt
            logger.warning("npz leaf verification failed to read %s (%s)",
                           npz, e)
            bad_leaves = list(range(len(leaves)))
    status = "corrupt" if (bad_files or bad_leaves) else "ok"
    return {"status": status,
            "files_checked": len(manifest.get("files", {})),
            "leaves_checked": leaves_checked,
            "mismatched_files": bad_files,
            "mismatched_leaves": bad_leaves}


# ---------------------------------------------------------------------------
# on-device params fingerprint (the SDC sentinel's cross-replica probe)
# ---------------------------------------------------------------------------

def params_fingerprint(params: Any):
    """A cheap on-device bit-content reduction of a param pytree.

    Every leaf is bitcast to unsigned integers and summed with uint32
    wraparound; leaf sums are mixed positionally so swapped leaves don't
    cancel. dp-replicated ranks hold bit-identical replicas and run the
    identical reduction, so their fingerprints match EXACTLY — any
    divergence (a flipped bit in one replica's HBM) changes the value.
    Designed to be jitted by the engine and compared across ranks via the
    coordination layer's ``all_gather``.
    """
    import jax
    import jax.numpy as jnp

    total = jnp.uint32(0)
    for leaf in jax.tree.leaves(params):
        x = leaf
        if x.dtype == jnp.bool_:
            bits = x.astype(jnp.uint32)
        elif jnp.issubdtype(x.dtype, jnp.floating):
            width = x.dtype.itemsize * 8
            target = {8: jnp.uint8, 16: jnp.uint16, 32: jnp.uint32}.get(width)
            if target is None:  # f64 and exotics: deterministic downcast
                x = x.astype(jnp.float32)
                target = jnp.uint32
            bits = jax.lax.bitcast_convert_type(x, target).astype(jnp.uint32)
        elif jnp.issubdtype(x.dtype, jnp.signedinteger) and \
                x.dtype.itemsize == 4:
            bits = jax.lax.bitcast_convert_type(x, jnp.uint32)
        else:
            bits = x.astype(jnp.uint32)
        total = total * jnp.uint32(1000003) + jnp.sum(
            bits, dtype=jnp.uint32)
    return total


# ---------------------------------------------------------------------------
# preflight selftest (tools/supervise.py --preflight)
# ---------------------------------------------------------------------------

#: crc32 of the deterministic selftest input block, as a HARD-CODED
#: literal — pinning the digest machinery itself only works if the
#: expected value was computed somewhere else: a host whose zlib/crc
#: tables are deterministically corrupt would reproduce its own wrong
#: value if this were evaluated at import time on the same host
_SELFTEST_INPUT_CRC = 0x2F5700C1


def selftest(size: int = 192, repeats: int = 3) -> dict:
    """A short compute+digest self-test for one host.

    Runs a seeded float32 matmul ``repeats`` times and digests each
    result: on healthy hardware every repeat is bit-identical, so any
    digest divergence means the host computes or remembers wrong — the
    exact class of silent fault a gang must refuse to include. The digest
    machinery itself is pinned against a known crc. The
    ``FLEETX_SELFTEST_FORCE_FAIL`` env knob (empty/``*`` or this member's
    ``FLEETX_PREFLIGHT_MEMBER`` index) fails the test on purpose — the
    drill hook the preflight tests use.
    """
    import time

    member = os.environ.get("FLEETX_PREFLIGHT_MEMBER", "0")
    t0 = time.perf_counter()
    rng = np.random.RandomState(20260803)
    a = rng.rand(size, size).astype(np.float32)
    b = rng.rand(size, size).astype(np.float32)
    digests = [digest_bytes(np.ascontiguousarray(a @ b).tobytes())
               for _ in range(max(int(repeats), 2))]
    crc_ok = digest_bytes(
        np.arange(4096, dtype=np.uint32).tobytes()) == _SELFTEST_INPUT_CRC
    compute_ok = len(set(digests)) == 1
    forced = os.environ.get("FLEETX_SELFTEST_FORCE_FAIL")
    forced_fail = forced is not None and forced in ("", "*", member)
    ok = compute_ok and crc_ok and not forced_fail
    return {"ok": ok, "member": member, "compute_ok": compute_ok,
            "crc_ok": crc_ok, "forced_fail": forced_fail,
            "digests": digests,
            "elapsed_s": round(time.perf_counter() - t0, 4)}


def main(argv: Optional[list] = None) -> int:
    """``python -m fleetx_tpu.resilience.integrity --selftest`` entry
    point: JSON report on stdout, exit 0 on a healthy host, 1 otherwise."""
    import argparse

    parser = argparse.ArgumentParser(
        description="fleetx integrity selftest (preflight)")
    parser.add_argument("--selftest", action="store_true",
                        help="run the compute+digest self-test")
    args = parser.parse_args(argv)
    if not args.selftest:
        parser.error("nothing to do (pass --selftest)")
    report = selftest()
    print(json.dumps(report))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
