"""Retry/backoff policy with transient-vs-fatal exception classification.

Large-run practice (Megatron-LM / OPT-175B logbooks, PAPERS.md) shows the
dominant recoverable failures are transient I/O: a checkpoint write hitting
a briefly-full or flaky filesystem, a download racing a network blip. The
reference delegates all of this to the Paddle substrate; here ONE policy
object owns the decision "retry or die" so checkpoint save/restore
(``core/checkpoint.py``) and artifact fetching (``utils/download.py``)
behave identically under pressure.

Classification is by exception type: ``OSError`` and friends (which
already cover ``ConnectionError``, ``TimeoutError`` and
``urllib.error.URLError``) are transient; everything else — a shape
mismatch, an assertion, a keyboard interrupt — is fatal and re-raises
immediately, because retrying a deterministic bug only delays the
traceback. Backoff is exponential with decorrelating jitter so a fleet of
hosts retrying a shared filesystem does not thundering-herd it.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple, Type

from fleetx_tpu.utils.log import logger

__all__ = ["RetryPolicy", "DEFAULT_POLICY", "is_transient", "call_with_retry",
           "retrying", "set_default_policy", "get_default_policy"]

#: exception classes worth a second attempt — I/O and environment, never
#: logic errors. TimeoutError/ConnectionError/URLError are OSError
#: subclasses already; listed types are matched with isinstance.
TRANSIENT_TYPES: Tuple[Type[BaseException], ...] = (OSError,)


@dataclass(frozen=True)
class RetryPolicy:
    """How many times to retry a transient failure, and how to back off.

    ``max_attempts`` counts TOTAL attempts (1 = no retries). Sleep before
    attempt ``n`` (n >= 2) is ``backoff_s * 2**(n-2)`` capped at
    ``max_backoff_s``, scaled by a uniform jitter in
    ``[1 - jitter, 1 + jitter]``.
    """

    max_attempts: int = 3
    backoff_s: float = 0.5
    max_backoff_s: float = 30.0
    jitter: float = 0.25
    transient_types: Tuple[Type[BaseException], ...] = \
        field(default=TRANSIENT_TYPES)

    def sleep_for(self, attempt: int, rng: Optional[random.Random] = None
                  ) -> float:
        """Backoff seconds before retry number ``attempt`` (1-based)."""
        base = min(self.backoff_s * (2.0 ** max(attempt - 1, 0)),
                   self.max_backoff_s)
        if self.jitter <= 0:
            return base
        r = rng if rng is not None else random
        return base * r.uniform(1.0 - self.jitter, 1.0 + self.jitter)

    @classmethod
    def from_cfg(cls, cfg: Optional[dict]) -> "RetryPolicy":
        """Build from a ``Resilience.retry`` config block (missing keys keep
        the dataclass defaults)."""
        cfg = dict(cfg or {})
        kwargs = {}
        for key in ("max_attempts", "backoff_s", "max_backoff_s", "jitter"):
            if cfg.get(key) is not None:
                cast = int if key == "max_attempts" else float
                kwargs[key] = cast(cfg[key])
        return cls(**kwargs)


DEFAULT_POLICY = RetryPolicy()

#: process-wide default used by checkpoint.py / download.py when no policy
#: is passed explicitly; the engine overrides it from the Resilience block
_active_policy: RetryPolicy = DEFAULT_POLICY


def set_default_policy(policy: Optional[RetryPolicy]) -> None:
    """Install the process-wide retry policy (None restores the default)."""
    global _active_policy
    _active_policy = policy if policy is not None else DEFAULT_POLICY


def get_default_policy() -> RetryPolicy:
    """The process-wide retry policy currently in effect."""
    return _active_policy


def is_transient(exc: BaseException,
                 policy: Optional[RetryPolicy] = None) -> bool:
    """True when ``exc`` is worth retrying under ``policy``.

    A :class:`~fleetx_tpu.resilience.coordination.CoordinationTimeout` is
    categorically fatal — even under a custom policy with widened
    ``transient_types`` — because an expired agreement deadline means the
    GANG diverged: retrying one rank's call would advance it a generation
    past its peers and convert a detectable straggler into a silent hang.
    """
    from fleetx_tpu.resilience.coordination import CoordinationTimeout

    if isinstance(exc, CoordinationTimeout):
        return False
    types = (policy or _active_policy).transient_types
    return isinstance(exc, types)


def call_with_retry(fn: Callable, *, policy: Optional[RetryPolicy] = None,
                    desc: str = "operation",
                    counter=None, sleep: Callable[[float], None] = time.sleep):
    """Run ``fn()`` retrying transient failures per ``policy``.

    ``counter`` (an observability ``Counter`` or None) is bumped once per
    retry, so ``ckpt_retries_total``-style telemetry reflects every
    absorbed failure. Fatal exceptions and exhausted policies re-raise the
    LAST error unchanged — callers keep their existing except clauses.
    """
    policy = policy or _active_policy
    attempts = max(int(policy.max_attempts), 1)
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001 — classified below
            if not is_transient(e, policy) or attempt >= attempts:
                raise
            if counter is not None:
                counter.inc()
            delay = policy.sleep_for(attempt)
            logger.warning("%s failed (%s: %s) — retry %d/%d in %.2fs",
                           desc, type(e).__name__, e, attempt,
                           attempts - 1, delay)
            if delay > 0:
                sleep(delay)


def retrying(desc: str = "operation", policy: Optional[RetryPolicy] = None,
             counter=None) -> Callable:
    """Decorator form of ``call_with_retry`` for free functions."""
    def wrap(fn: Callable) -> Callable:
        def inner(*args, **kwargs):
            return call_with_retry(lambda: fn(*args, **kwargs),
                                   policy=policy, desc=desc, counter=counter)
        inner.__name__ = getattr(fn, "__name__", "retrying")
        inner.__doc__ = fn.__doc__
        return inner
    return wrap
