"""TrainingGuard: loss-health policy — non-finite streaks and loss spikes.

The engine's in-step ``isfinite`` skip (historically fp16-scaler-only,
``eager_engine.py``) protects ONE step: a non-finite update is dropped and
the parameters survive. What it cannot do is decide when a run has gone
bad — a NaN *streak* means the data or the optimizer state is poisoned and
skipping forever just burns chips, and a sudden loss blow-up (OPT-175B
logbook's dominant "restart from an earlier checkpoint" trigger) often
precedes the NaNs. ``TrainingGuard`` owns that policy host-side:

- a consecutive non-finite counter with a configurable action once the
  streak reaches ``nonfinite_streak``: ``skip`` (tolerate and count),
  ``rollback`` (restore the last good checkpoint and rewind the data
  position), or ``abort``;
- an EWMA loss-spike detector (``loss > spike_factor × ewma`` after a
  warmup) with the same action set;
- a ``max_rollbacks`` budget so a deterministically-poisoned run escalates
  to ``abort`` instead of rollback-looping forever.

The guard only *decides*; the engine executes rollbacks and aborts. All
decisions surface as registry counters (``nonfinite_skips``,
``loss_spikes_total``, ``rollbacks_total`` from the engine side).
"""

from __future__ import annotations

import math
from typing import Optional

from fleetx_tpu.observability import flight
from fleetx_tpu.observability.metrics import get_registry
from fleetx_tpu.utils.log import logger

__all__ = ["TrainingGuard", "TrainingAborted", "ACTIONS"]

ACTIONS = ("skip", "rollback", "abort")


class TrainingAborted(RuntimeError):
    """Raised by the engine when the guard (or a failed rollback) decides
    the run cannot continue — distinct from arbitrary crashes so
    supervisors can treat it as non-retryable."""


class TrainingGuard:
    """Streak/spike policy over the host-observed loss sequence.

    ``observe()`` is called once per logging window with the synced loss
    (and the step fn's device-computed ``finite`` flag when available) and
    returns ``None`` (healthy / tolerated), ``"rollback"`` or ``"abort"``.
    Granularity is therefore the logging window — with ``logging_freq: 1``
    every step is inspected.
    """

    def __init__(self, nonfinite_action: str = "skip",
                 nonfinite_streak: int = 3,
                 spike_action: str = "skip",
                 spike_factor: Optional[float] = None,
                 spike_ewma_alpha: float = 0.1,
                 spike_min_steps: int = 20,
                 max_rollbacks: int = 3,
                 skip_active: bool = True,
                 registry=None):
        assert nonfinite_action in ACTIONS, nonfinite_action
        assert spike_action in ACTIONS, spike_action
        self.nonfinite_action = nonfinite_action
        self.nonfinite_streak = max(int(nonfinite_streak), 1)
        self.spike_action = spike_action
        self.spike_factor = float(spike_factor) if spike_factor else None
        self.spike_ewma_alpha = float(spike_ewma_alpha)
        self.spike_min_steps = max(int(spike_min_steps), 1)
        self.max_rollbacks = max(int(max_rollbacks), 0)
        # honest counter naming: a window only counts as a SKIP when the
        # in-step update-skip is actually active; otherwise the update
        # landed and the event is recorded as nonfinite_windows
        self.skip_active = bool(skip_active)
        self.registry = registry or get_registry()
        self._streak = 0
        self._ewma: Optional[float] = None
        self._observed = 0
        self._rollbacks = 0

    @classmethod
    def from_cfg(cls, cfg: Optional[dict], skip_active: bool = True,
                 registry=None) -> "TrainingGuard":
        """Build from a ``Resilience.guard`` config block."""
        cfg = dict(cfg or {})
        return cls(
            nonfinite_action=str(cfg.get("nonfinite_action") or "skip"),
            nonfinite_streak=int(cfg.get("nonfinite_streak") or 3),
            spike_action=str(cfg.get("spike_action") or "skip"),
            spike_factor=cfg.get("spike_factor"),
            spike_ewma_alpha=float(cfg.get("spike_ewma_alpha") or 0.1),
            spike_min_steps=int(cfg.get("spike_min_steps") or 20),
            max_rollbacks=int(3 if cfg.get("max_rollbacks") is None
                              else cfg.get("max_rollbacks")),
            skip_active=skip_active, registry=registry)

    # --------------------------------------------------------------- policy
    def observe(self, step: int, loss: float,
                finite: Optional[bool] = None) -> Optional[str]:
        """Feed one window's loss; returns the action the engine must take.

        ``finite`` is the device-side flag from the step fn when present
        (it also covers grad norms); otherwise finiteness of ``loss``
        decides.
        """
        self._observed += 1
        ok = bool(finite) if finite is not None else math.isfinite(loss)
        if not ok:
            self._streak += 1
            # granularity is the observation window (one per logging_freq
            # steps): with the in-step skip active the window's update was
            # dropped on-device; without it the update landed and only the
            # observation is recorded
            self.registry.counter("nonfinite_skips" if self.skip_active
                                  else "nonfinite_windows").inc()
            # the flight ring wants the streak's BUILD-UP, not just the
            # final decision — a crash dump should show the run going bad
            flight.note("guard", "nonfinite", step=int(step),
                        streak=self._streak)
            logger.warning("non-finite loss at step %d (streak %d/%d, "
                           "action=%s)", step, self._streak,
                           self.nonfinite_streak, self.nonfinite_action)
            if self._streak >= self.nonfinite_streak:
                return self._escalate(self.nonfinite_action,
                                      f"non-finite streak of {self._streak}")
            return None
        self._streak = 0
        if self.spike_factor and self._ewma is not None and \
                self._observed > self.spike_min_steps and \
                loss > self.spike_factor * self._ewma:
            self.registry.counter("loss_spikes_total").inc()
            flight.note("guard", "loss_spike", step=int(step),
                        loss=float(loss), ewma=float(self._ewma))
            logger.warning("loss spike at step %d: %.4g > %.1fx ewma %.4g "
                           "(action=%s)", step, loss, self.spike_factor,
                           self._ewma, self.spike_action)
            decision = self._escalate(self.spike_action,
                                      f"loss spike {loss:.4g}")
            # a tolerated spike must not drag the EWMA up toward the spike
            # (that would mask a slow divergence); skip the update
            return decision
        a = self.spike_ewma_alpha
        self._ewma = (loss if self._ewma is None
                      else a * loss + (1.0 - a) * self._ewma)
        return None

    def _escalate(self, action: str, why: str) -> Optional[str]:
        """Map a tripped detector to the engine-facing decision."""
        if action == "skip":
            return None  # tolerate: the in-step skip already protected params
        if action == "rollback":
            if self._rollbacks >= self.max_rollbacks:
                logger.error("%s: rollback budget exhausted (%d) — aborting",
                             why, self.max_rollbacks)
                return "abort"
            return "rollback"
        return "abort"

    # ------------------------------------------------------------ lifecycle
    def note_rollback(self) -> None:
        """Engine notifies a completed rollback: reset streak/EWMA state and
        spend one unit of the rollback budget."""
        self._rollbacks += 1
        self._streak = 0
        self._ewma = None
        self._observed = 0

    @property
    def rollbacks(self) -> int:
        """Rollbacks performed so far (budget accounting)."""
        return self._rollbacks
