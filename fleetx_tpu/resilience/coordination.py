"""Cross-process agreement primitives for multi-host gangs.

PR 4's resilience runtime is process-local: the preemption latch reacts to
the signal one rank happened to receive, the guard decides rollback from
its own loss window, and resume scans a per-host directory. On a
multi-process pod any of these lets ranks diverge and then hang inside the
next collective — the failure mode the MPMD-pipeline scaling work
(PAPERS.md) names as the blocker for DCN-linked multi-slice runs. This
module gives every recovery decision a gang-wide form:

- ``barrier(name)``        — timed rendezvous; a timeout reports *which
  ranks arrived* (the straggler set a hung-collective post-mortem needs);
- ``broadcast(name, v)``   — rank 0's JSON-serializable value to everyone
  (resume-step agreement);
- ``any_flag(name, f)``    — OR across ranks (one rank's SIGTERM latches
  preemption everywhere);
- ``all_gather(name, v)``  — every rank's value (guard decisions);
- ``majority(name, v)``    — most common value, deterministic tie-break.

Everything runs over the JAX distributed KV store
(``jax._src.distributed.global_state.client``), NOT over device
collectives: the KV store works wherever ``jax.distributed.initialize``
does — including multi-process CPU meshes, where XLA has no cross-process
computations and ``jax.experimental.multihost_utils`` therefore cannot run
— and, unlike a device psum, it can time out and report who is missing.

Calls are generation-counted per name: every rank must invoke the same
primitives in the same order (they are collectives). A process-lifetime
singleton (``get_coordinator``) keeps the generation counters monotonic
across engine rebuilds so a fresh engine can never re-read a previous
fit's stale keys.
"""

from __future__ import annotations

import json
import time
from collections import Counter, defaultdict
from typing import Any, Dict, Iterable, Optional

from fleetx_tpu.observability import gang as obs_gang
from fleetx_tpu.utils.log import logger

__all__ = ["CoordinationTimeout", "LocalCoordinator", "DistributedCoordinator",
           "get_coordinator", "reset_coordinator", "configure",
           "most_severe", "DEFAULT_TIMEOUT_S"]

#: default agreement deadline — generous enough to ride out a checkpoint
#: restore on the slowest rank, small enough that a wedged gang surfaces
#: within one scheduler health-check interval
DEFAULT_TIMEOUT_S = 600.0
_DEFAULT_POLL_S = 0.05

_timeout_s = DEFAULT_TIMEOUT_S
_poll_s = _DEFAULT_POLL_S


def configure(timeout_s: Optional[float] = None,
              poll_s: Optional[float] = None) -> None:
    """Set module-wide agreement defaults from ``Resilience.coordination``
    (None resets a knob to its built-in default)."""
    global _timeout_s, _poll_s
    _timeout_s = DEFAULT_TIMEOUT_S if timeout_s is None else float(timeout_s)
    _poll_s = _DEFAULT_POLL_S if poll_s is None else float(poll_s)


class CoordinationTimeout(RuntimeError):
    """An agreement deadline expired — carries the arrival census.

    ``arrived``/``missing`` are the rank sets observed at expiry: the
    missing set IS the straggler/crash suspect list, which is exactly what
    a hung-gang post-mortem needs and what a plain deadlocked device
    collective can never produce.
    """

    def __init__(self, name: str, arrived: Iterable[int],
                 missing: Iterable[int], timeout_s: float):
        self.name = name
        self.arrived = sorted(arrived)
        self.missing = sorted(missing)
        self.timeout_s = timeout_s
        super().__init__(
            f"coordination '{name}' timed out after {timeout_s:.1f}s: "
            f"arrived ranks {self.arrived}, missing ranks {self.missing}")


def most_severe(decisions: Iterable[Optional[str]]) -> Optional[str]:
    """Combine per-rank guard decisions into the gang's decision.

    Severity: ``None`` (healthy/tolerated) < ``"rollback"`` < ``"abort"``
    — any rank's rollback rolls everyone back, any abort aborts everyone,
    so no rank ever takes a recovery action the others don't mirror.
    """
    rank = {None: 0, "rollback": 1, "abort": 2}
    worst = None
    for d in decisions:
        if rank.get(d, 0) > rank.get(worst, 0):
            worst = d
    return worst


class LocalCoordinator:
    """Single-process no-op implementation of the coordinator protocol.

    Keeps every call site unconditional: a single-host run (the common dev
    case, and every existing test) pays nothing and behaves byte-identically
    to the pre-coordination engine.
    """

    rank = 0
    world = 1

    def barrier(self, name: str, timeout_s: Optional[float] = None) -> None:
        """Trivially satisfied with one process."""

    def broadcast(self, name: str, value: Any = None,
                  timeout_s: Optional[float] = None) -> Any:
        """Rank 0 is the only rank: its value is the agreement."""
        return value

    def any_flag(self, name: str, flag: bool,
                 timeout_s: Optional[float] = None) -> bool:
        """OR over one rank."""
        return bool(flag)

    def all_gather(self, name: str, value: Any = None,
                   timeout_s: Optional[float] = None) -> Dict[int, Any]:
        """One-entry census."""
        return {0: value}

    def majority(self, name: str, value: Any = None,
                 timeout_s: Optional[float] = None) -> Any:
        """A one-vote election."""
        return value


class DistributedCoordinator:
    """KV-store implementation over the JAX distributed client.

    ``all_gather`` is the base primitive: every rank publishes
    ``<ns>/<name>/<generation>/<rank>`` and blocks on each peer's key
    (server-side blocking gets — a rendezvous costs the actual rank skew,
    not a poll quantum) until all ``world`` ranks appear or the deadline
    expires — expiry raises :class:`CoordinationTimeout` with the arrival
    census.
    Barrier/any_flag/majority derive from it. ``broadcast`` is the one
    asymmetric call: rank 0 publishes, everyone else does a blocking get.

    A rank deletes its *previous* generation's key when a new generation
    of the same name completes: observing all ranks in generation ``g``
    proves every rank finished ``g-1``, so the old keys are dead and the
    KV store stays bounded over million-step runs.
    """

    def __init__(self, client, rank: int, world: int,
                 namespace: str = "fleetx/coord",
                 poll_s: Optional[float] = None):
        assert world >= 1 and 0 <= rank < world, (rank, world)
        self._client = client
        self.rank = int(rank)
        self.world = int(world)
        self._ns = namespace.rstrip("/")
        self._poll_s = poll_s
        self._gen: Dict[str, int] = defaultdict(int)

    # ------------------------------------------------------------- internals
    def _prefix(self, name: str, gen: int) -> str:
        return f"{self._ns}/{name}/{gen}"

    def _deadline(self, timeout_s: Optional[float]) -> float:
        return time.monotonic() + (_timeout_s if timeout_s is None
                                   else float(timeout_s))

    def _poll_interval(self) -> float:
        return _poll_s if self._poll_s is None else self._poll_s

    def _await_key(self, key: str, remaining_s: float) -> Optional[str]:
        """Block until ``key`` exists (returning its payload) or
        ``remaining_s`` elapses (returning ``None``).

        Prefers the KV store's server-side blocking get — the wake-up is
        push-driven, so a rendezvous costs the actual rank skew, not a
        poll quantum (the preemption vote sits on the hot step path).
        Falls back to polling at ``poll_s`` for clients without it.
        """
        blocking = getattr(self._client, "blocking_key_value_get", None)
        if blocking is not None:
            t0 = time.monotonic()
            try:
                return blocking(key, max(int(remaining_s * 1000), 1))
            except Exception:  # noqa: BLE001 — DEADLINE_EXCEEDED variants
                if time.monotonic() - t0 < remaining_s * 0.9:
                    # returned well before the deadline: a local
                    # client/RPC failure, not an expiry — re-raise rather
                    # than reporting healthy peers as a straggler census
                    raise
                return None
        deadline = time.monotonic() + remaining_s
        prefix, _, rank = key.rpartition("/")
        while time.monotonic() < deadline:
            payload = self._arrived(prefix).get(int(rank))
            if payload is not None:
                return payload
            time.sleep(self._poll_interval())
        return None

    def _arrived(self, prefix: str) -> Dict[int, str]:
        """Ranks that have published under ``prefix`` → their payloads."""
        try:
            entries = self._client.key_value_dir_get(prefix)
        except Exception:  # noqa: BLE001 — directory not created yet
            return {}
        out: Dict[int, str] = {}
        for key, payload in entries:
            tail = str(key).rsplit("/", 1)[-1]
            if tail.isdigit():
                out[int(tail)] = payload
        return out

    def _gc_previous(self, name: str, gen: int) -> None:
        """Drop our own key from the completed previous generation."""
        if gen <= 0:
            return
        try:
            self._client.key_value_delete(
                f"{self._prefix(name, gen - 1)}/{self.rank}")
        except Exception:  # noqa: BLE001 — GC is best-effort
            pass

    # ------------------------------------------------------------ primitives
    def all_gather(self, name: str, value: Any = None,
                   timeout_s: Optional[float] = None) -> Dict[int, Any]:
        """Every rank's ``value`` for this generation of ``name``.

        Deterministic across ranks: each rank publishes exactly once per
        generation, so all ranks decode the identical census.

        Every payload rides in a ``{"__v": value, "__t": publish-time}``
        envelope: the timestamps are the collective-wait evidence
        (docs/observability.md "Multi-host") — the entry-to-completion
        wait lands in the ``barrier_wait_ms`` histogram and the per-rank
        arrival census feeds the rolling straggler-skew estimate, so a
        slow rank is *named* while the run is healthy instead of
        surfacing as a post-mortem ``CoordinationTimeout`` census.
        """
        gen = self._gen[name]
        self._gen[name] += 1
        prefix = self._prefix(name, gen)
        t_entry = time.monotonic()
        own = json.dumps({"__v": value, "__t": time.time()})
        self._client.key_value_set(f"{prefix}/{self.rank}", own)
        timeout = _timeout_s if timeout_s is None else float(timeout_s)
        deadline = time.monotonic() + timeout
        # the per-peer blocking gets already return every payload (own
        # value is known locally) — a success needs no extra directory
        # read, which matters on the once-per-step loop_flags vote
        payloads = {self.rank: own}
        for peer in range(self.world):
            if peer == self.rank:
                continue
            remaining = deadline - time.monotonic()
            payload = (self._await_key(f"{prefix}/{peer}", remaining)
                       if remaining > 0 else None)
            if payload is None:
                arrived = self._arrived(prefix)
                missing = set(range(self.world)) - set(arrived)
                obs_gang.note_timeout(f"{name}#{gen}", arrived, missing)
                raise CoordinationTimeout(f"{name}#{gen}", arrived, missing,
                                          timeout)
            payloads[peer] = payload
        self._gc_previous(name, gen)
        values: Dict[int, Any] = {}
        arrivals: Dict[int, float] = {}
        for rank, payload in payloads.items():
            decoded = json.loads(payload)
            values[rank] = decoded["__v"]
            arrivals[rank] = float(decoded["__t"])
        obs_gang.note_agreement(name, time.monotonic() - t_entry,
                                arrivals=arrivals, rank=self.rank,
                                world=self.world)
        return values

    def barrier(self, name: str, timeout_s: Optional[float] = None) -> None:
        """Timed rendezvous; :class:`CoordinationTimeout` names stragglers."""
        self.all_gather(name, None, timeout_s=timeout_s)

    def broadcast(self, name: str, value: Any = None,
                  timeout_s: Optional[float] = None) -> Any:
        """Rank 0's JSON-serializable ``value``, delivered to every rank."""
        gen = self._gen[name]
        self._gen[name] += 1
        key = f"{self._prefix(name, gen)}/0"
        if self.rank == 0:
            self._client.key_value_set(key, json.dumps(value))
            return value
        t_entry = time.monotonic()
        timeout = _timeout_s if timeout_s is None else float(timeout_s)
        payload = self._await_key(key, timeout)
        if payload is None:
            # the census is the set of PUBLISHED keys; a broadcast waiter
            # never writes one, so it must not report itself as arrived
            obs_gang.note_timeout(f"{name}#{gen}", [], [0])
            raise CoordinationTimeout(f"{name}#{gen}", [], [0], timeout)
        # wait histogram only — the one-publisher shape has no arrival
        # census to feed the skew estimate
        obs_gang.note_agreement(name, time.monotonic() - t_entry,
                                rank=self.rank, world=self.world)
        return json.loads(payload)

    def any_flag(self, name: str, flag: bool,
                 timeout_s: Optional[float] = None) -> bool:
        """True once ANY rank raised ``flag`` this generation."""
        votes = self.all_gather(name, bool(flag), timeout_s=timeout_s)
        return any(votes.values())

    def majority(self, name: str, value: Any = None,
                 timeout_s: Optional[float] = None) -> Any:
        """The most common value; ties break toward the lowest-rank holder
        so every rank resolves the same winner."""
        votes = self.all_gather(name, value, timeout_s=timeout_s)
        counts = Counter(json.dumps(v, sort_keys=True)
                         for v in votes.values())
        best = max(counts.items(),
                   key=lambda kv: (kv[1], -self._first_holder(votes, kv[0])))
        return json.loads(best[0])

    @staticmethod
    def _first_holder(votes: Dict[int, Any], encoded: str) -> int:
        """Lowest rank holding ``encoded`` (tie-break anchor)."""
        for rank in sorted(votes):
            if json.dumps(votes[rank], sort_keys=True) == encoded:
                return rank
        return 0


# ---------------------------------------------------------------------------
# Process-lifetime singleton
# ---------------------------------------------------------------------------

_coordinator = None


def get_coordinator():
    """The process-wide coordinator (built on first use).

    Distributed iff ``jax.distributed`` is initialized with more than one
    process at first call; otherwise the no-op local implementation. The
    instance persists for the process lifetime so generation counters stay
    monotonic across engine rebuilds — a fresh coordinator would restart
    at generation 0 and re-read a previous fit's stale keys.
    """
    global _coordinator
    if _coordinator is not None:
        return _coordinator
    client = None
    world = 1
    rank = 0
    try:
        import jax
        from jax._src import distributed

        client = distributed.global_state.client
        if client is not None:
            world = jax.process_count()
            rank = jax.process_index()
    except Exception:  # noqa: BLE001 — no jax / no distributed runtime
        client = None
    if client is not None and world > 1:
        _coordinator = DistributedCoordinator(client, rank, world)
        logger.info("gang coordinator: rank %d of %d (KV-store agreement)",
                    rank, world)
    else:
        _coordinator = LocalCoordinator()
    return _coordinator


def reset_coordinator() -> None:
    """Drop the singleton (tests only — a real process never outlives its
    distributed runtime, and a fresh coordinator restarts generation
    counters, which is unsafe while peers hold the old ones)."""
    global _coordinator
    _coordinator = None
