"""Deterministic fault injection: rehearse failures before production does.

A fault-tolerance layer that has never seen a fault is untested code on
the critical path. ``FaultPlan`` injects the three dominant large-run
failure modes at exact, reproducible points so the resilience tests drive
the REAL recovery machinery end-to-end:

- ``data_raise_at: K``       — raise from the data path at batch index K
  (a flaky storage read / corrupt shard);
- ``nan_loss_at: [K, ...]``  — poison the batch's ``loss_mask`` with NaN
  at those indices, producing a genuinely non-finite device loss (a loss
  blow-up, exercised through the full jitted step);
- ``sigterm_at: K``          — SIGTERM our own process before step K (a
  TPU-pool preemption);
- ``ckpt_write_fail_times: N`` — the first N checkpoint writes raise a
  transient ``InjectedFault(OSError)`` (an I/O blip the retry policy must
  absorb).

The state-integrity layer (``docs/resilience.md`` "Integrity") adds three
corruption drills so every detector is rehearsed the way the matrix above
rehearses crashes:

- ``bitflip_param_at: K``    — flip one bit in a param leaf after step K
  (a silent HBM/compute fault; the SDC sentinel's cross-replica
  fingerprint must trip);
- ``corrupt_ckpt_at: K``     — flip a byte in step K's just-written
  checkpoint payload, STICKY across write retries (the save-side
  read-back verification must fail the ``ckpt_commit`` vote);
- ``corrupt_restore_at: K``  — flip a byte in step K's payload just
  before a restore reads it (restore must refuse and fall back to the
  newest checkpoint that verifies).

Plans come from the ``Resilience.faults`` config block or the
``FLEETX_FAULTS`` env var (``"sigterm_at=5,ckpt_write_fail_times=1,
nan_loss_at=4:5"``), env winning — so a restart harness can inject into an
unmodified recipe. A module-level active plan lets deep layers
(``core/checkpoint.py``) consult injection points without config plumbing.

The serving chaos drills (docs/serving.md "Fault tolerance") add three
replica-front failure shapes, consumed by ``serving/server.py``:

- ``slow_decode_ms_at: [K, MS]`` — from work-step K onward every decode
  step takes MS extra milliseconds (a straggler replica; the router's
  hedged dispatch must absorb the tail);
- ``blackhole_after: K``     — after K responses the replica still
  ACCEPTS connections but never answers anything again, verbs included
  (a hung process; only an observing health probe, not a timer, can
  tell it from a busy one);
- ``crash_mid_write: K``     — the K-th data response is torn mid-JSON
  and the process hard-exits (a crash that leaves a half-written line
  on the wire; the router must classify it as transport failure and
  re-dispatch).

Multi-host gangs add ``only_rank: R``: the plan arms on process R alone
and every other rank gets an empty plan from the same config — the drill a
collective recovery needs is "ONE rank fails, the whole gang reacts"
(one rank's SIGTERM, one rank's poisoned batch), which a uniformly-armed
plan cannot stage.
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Any, Optional

import numpy as np

from fleetx_tpu.utils.log import logger

__all__ = ["FaultPlan", "InjectedFault", "install_plan", "active_plan",
           "fire"]

ENV_VAR = "FLEETX_FAULTS"


class InjectedFault(OSError):
    """Injected transient failure — an ``OSError`` so the retry policy
    classifies it exactly like the real I/O error it stands in for."""


def _this_rank(override: Optional[int] = None) -> int:
    """This process's gang rank (0 when jax / the distributed runtime is
    absent, so single-process drills behave like rank 0)."""
    if override is not None:
        return int(override)
    try:
        import jax

        return int(jax.process_index())
    except Exception:  # noqa: BLE001 — faults must import without jax
        return 0


def _parse_env(spec: str) -> dict:
    """``k=v,k=v`` with ``:``-separated int lists → a faults config dict."""
    out: dict = {}
    for part in spec.split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        key, value = part.split("=", 1)
        if ":" in value:
            out[key.strip()] = [int(v) for v in value.split(":") if v]
        else:
            out[key.strip()] = int(value)
    return out


class FaultPlan:
    """One run's worth of armed faults; all methods are cheap no-ops when
    the corresponding fault is not armed."""

    def __init__(self, data_raise_at: Optional[int] = None,
                 nan_loss_at: Optional[list] = None,
                 sigterm_at: Optional[int] = None,
                 ckpt_write_fail_times: int = 0,
                 bitflip_param_at: Optional[int] = None,
                 corrupt_ckpt_at: Optional[int] = None,
                 corrupt_restore_at: Optional[int] = None,
                 slow_decode_ms_at: Optional[list] = None,
                 blackhole_after: Optional[int] = None,
                 crash_mid_write: Optional[int] = None):
        self.data_raise_at = data_raise_at
        self.nan_loss_at = set(int(s) for s in (nan_loss_at or ()))
        self.sigterm_at = sigterm_at
        self.ckpt_write_fail_times = int(ckpt_write_fail_times or 0)
        self.bitflip_param_at = bitflip_param_at
        self.corrupt_ckpt_at = corrupt_ckpt_at
        self.corrupt_restore_at = corrupt_restore_at
        if slow_decode_ms_at is not None:
            pair = [int(v) for v in slow_decode_ms_at]
            assert len(pair) == 2, \
                "slow_decode_ms_at wants [work_step, extra_ms]"
            slow_decode_ms_at = pair
        self.slow_decode_ms_at = slow_decode_ms_at
        self.blackhole_after = blackhole_after
        self.crash_mid_write = crash_mid_write
        # serving-front counters are bumped by concurrent connection
        # handler threads (unlike the train-loop triggers above, which
        # are engine-thread-only), so they share one lock
        self._io_lock = threading.Lock()
        self._responses = 0

    @classmethod
    def from_cfg(cls, cfg: Optional[dict],
                 env: Optional[str] = None,
                 rank: Optional[int] = None) -> "FaultPlan":
        """Merge the config block and the env spec (env wins per key).

        ``only_rank`` (config or env) arms the plan on that process index
        alone: every other rank receives an empty plan, so ONE config can
        stage a single-rank failure for a whole gang. ``rank`` overrides
        the process-index lookup (tests).
        """
        merged = dict(cfg or {})
        env = os.environ.get(ENV_VAR) if env is None else env
        if env:
            merged.update(_parse_env(env))
        only = merged.get("only_rank")
        if only is not None and int(only) != _this_rank(rank):
            logger.info("fault plan targets rank %d only — disarmed on "
                        "rank %d", int(only), _this_rank(rank))
            return cls()
        nan_at = merged.get("nan_loss_at")
        if isinstance(nan_at, int):
            nan_at = [nan_at]
        def opt_int(key: str) -> Optional[int]:
            return None if merged.get(key) is None else int(merged[key])

        slow = merged.get("slow_decode_ms_at")
        if isinstance(slow, int):
            slow = [slow]
        return cls(
            data_raise_at=opt_int("data_raise_at"),
            nan_loss_at=nan_at,
            sigterm_at=opt_int("sigterm_at"),
            ckpt_write_fail_times=int(merged.get("ckpt_write_fail_times")
                                      or 0),
            bitflip_param_at=opt_int("bitflip_param_at"),
            corrupt_ckpt_at=opt_int("corrupt_ckpt_at"),
            corrupt_restore_at=opt_int("corrupt_restore_at"),
            slow_decode_ms_at=slow,
            blackhole_after=opt_int("blackhole_after"),
            crash_mid_write=opt_int("crash_mid_write"))

    @property
    def armed(self) -> bool:
        """True when any fault is configured."""
        return bool(self.data_raise_at is not None or self.nan_loss_at
                    or self.sigterm_at is not None
                    or self.ckpt_write_fail_times
                    or self.bitflip_param_at is not None
                    or self.corrupt_ckpt_at is not None
                    or self.corrupt_restore_at is not None
                    or self.slow_decode_ms_at is not None
                    or self.blackhole_after is not None
                    or self.crash_mid_write is not None)

    # ------------------------------------------------------------- triggers
    def on_batch(self, index: int, batch: Any) -> Any:
        """Data-path hook: raise or poison at batch ``index`` (the engine's
        global step numbering), else pass ``batch`` through untouched."""
        if self.data_raise_at is not None and index == self.data_raise_at:
            self.data_raise_at = None  # once
            raise InjectedFault(
                f"injected data-path failure at batch {index}")
        if index in self.nan_loss_at and isinstance(batch, dict) and \
                "loss_mask" in batch:
            logger.warning("fault injection: NaN loss_mask at batch %d",
                           index)
            mask = np.asarray(batch["loss_mask"], dtype=np.float32).copy()
            mask[...] = np.nan
            batch = dict(batch, loss_mask=mask)
        return batch

    def maybe_sigterm(self, step: int, start_step: int = 0) -> None:
        """Send SIGTERM to our own process before step ``step`` (once).

        Fires on FRESH runs only (``start_step == 0``, same gate as the
        legacy ``FLEETX_FAULT_STEP`` hook): a resumed process must sail
        past the injection point, otherwise a supervisor re-running the
        same command re-kills the run at its own resume step forever.
        """
        if start_step:
            return
        if self.sigterm_at is not None and step >= self.sigterm_at:
            self.sigterm_at = None
            logger.warning("fault injection: SIGTERM self at step %d", step)
            os.kill(os.getpid(), signal.SIGTERM)

    def take_bitflip(self, step: int) -> bool:
        """True (once) when the param bit-flip is due at ``step`` — the
        engine then flips one bit in its live state, staging the silent
        HBM-corruption event the SDC sentinel exists to catch."""
        if self.bitflip_param_at is not None and \
                step >= self.bitflip_param_at:
            self.bitflip_param_at = None
            return True
        return False

    # ----------------------------------------------------- serving triggers
    def decode_delay_s(self, work_step: int) -> float:
        """Extra seconds the replica loop must sleep after ``work_step``
        (0.0 while the straggler fault is unarmed or not yet due)."""
        if self.slow_decode_ms_at is None:
            return 0.0
        at, ms = self.slow_decode_ms_at
        return ms / 1000.0 if work_step >= at else 0.0

    def blackholed(self) -> bool:
        """True once the replica has answered its ``blackhole_after``-th
        response: from then on every connection — data or verb — is
        accepted and never answered (the hung-process shape)."""
        if self.blackhole_after is None:
            return False
        with self._io_lock:
            return self._responses >= self.blackhole_after

    def note_response(self) -> None:
        """Count one answered data response (drives ``blackhole_after``
        and ``crash_mid_write``)."""
        with self._io_lock:
            self._responses += 1

    def take_crash_mid_write(self) -> bool:
        """True when the NEXT data response is the ``crash_mid_write``-th:
        the caller writes a torn line and hard-exits."""
        if self.crash_mid_write is None:
            return False
        with self._io_lock:
            return self._responses + 1 >= self.crash_mid_write

    def fire(self, point: str) -> None:
        """Named-point hook for deep layers (``"ckpt_write"``)."""
        if point == "ckpt_write" and self.ckpt_write_fail_times > 0:
            self.ckpt_write_fail_times -= 1
            raise InjectedFault("injected checkpoint-write failure")

    def fire_path(self, point: str, path: str, step: int) -> None:
        """Corruption hooks keyed on a checkpoint step directory:
        ``"ckpt_written"`` fires after step ``corrupt_ckpt_at``'s state
        write (STICKY — every retry's rewrite is re-corrupted, so the
        save-side verification genuinely exhausts the policy), and
        ``"ckpt_restore"`` fires before step ``corrupt_restore_at`` is
        read back (idempotent — re-corrupting corrupt bytes is fine)."""
        due = {"ckpt_written": self.corrupt_ckpt_at,
               "ckpt_restore": self.corrupt_restore_at}.get(point)
        if due is not None and int(step) == int(due):
            _corrupt_payload(path, point)


# ---------------------------------------------------------------------------
# Module-level active plan (checkpoint.py consults it without plumbing)
# ---------------------------------------------------------------------------

_active: Optional[FaultPlan] = None


def install_plan(plan: Optional[FaultPlan]) -> None:
    """Install (or clear, with None) the process-wide fault plan."""
    global _active
    _active = plan if plan is not None and plan.armed else None
    if _active is not None:
        logger.warning("fault-injection plan armed: %s", vars(plan))


def active_plan() -> Optional[FaultPlan]:
    """The armed process-wide plan, if any."""
    return _active


def fire(point: str) -> None:
    """Trigger the named injection point on the active plan (no-op when
    nothing is armed) — the one-liner deep layers call."""
    if _active is not None:
        _active.fire(point)


def fire_path(point: str, path: str, step: int) -> None:
    """Trigger a path-keyed corruption point on the active plan (no-op
    when nothing is armed) — ``core/checkpoint.py``'s one-liner."""
    if _active is not None:
        _active.fire_path(point, path, step)


def _corrupt_payload(path: str, point: str) -> None:
    """Flip one byte in the middle of the first payload file under
    ``path`` (deterministic: sorted walk, metadata markers skipped) — the
    exact bit-rot shape storage hands back in the wild."""
    from fleetx_tpu.resilience import integrity

    for rel in integrity._payload_files(path):
        target = os.path.join(path, rel)
        size = os.path.getsize(target)
        if size == 0:
            continue
        offset = size // 2
        with open(target, "r+b") as f:
            f.seek(offset)
            byte = f.read(1)
            f.seek(offset)
            f.write(bytes([byte[0] ^ 0xFF]))
        logger.warning("fault injection: corrupted byte %d of %s (%s)",
                       offset, target, point)
        return
    logger.warning("fault injection: no payload file to corrupt under %s",
                   path)
