"""Step watchdog: detect a hung step, dump stacks, flush telemetry.

A deadlocked collective, a wedged host-to-device transfer or a stuck data
producer leaves the train loop silent forever — the run *looks* alive to
the scheduler while burning its reservation. ``StepWatchdog`` runs a
daemon heartbeat thread: the train loop calls ``beat()`` once per step,
and once armed by the FIRST beat (so the first step's XLA compile, however
long, can never false-positive), a silence of ``stall_factor ×`` the
median step time — floored at ``min_timeout_s`` to ride out restores and
mid-run re-compiles — makes the watchdog

1. logs every Python thread's stack (the post-mortem a hung run normally
   never produces),
2. flushes the observability sinks so the last telemetry window is
   durable,
3. bumps ``watchdog_stalls``, and
4. optionally aborts the process (``action: abort``, exit code 43) so a
   supervisor restarts from the last checkpoint.

The median step time comes from the telemetry registry's ``step_time``
histogram when populated (the engine records it every logging window) and
falls back to the watchdog's own observed beat intervals before the first
window closes.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Callable, Optional

from fleetx_tpu.observability.metrics import get_registry
from fleetx_tpu.utils.log import logger

__all__ = ["StepWatchdog", "GangWatchdog", "ABORT_EXIT_CODE"]

#: distinct from fault-injection's 17 so supervisors can tell them apart
ABORT_EXIT_CODE = 43


def _format_all_stacks() -> str:
    """Every thread's current Python stack, hung-run post-mortem style."""
    names = {t.ident: t.name for t in threading.enumerate()}
    chunks = []
    for ident, frame in sys._current_frames().items():
        name = names.get(ident, "?")
        stack = "".join(traceback.format_stack(frame))
        chunks.append(f"--- thread {name} ({ident}) ---\n{stack}")
    return "\n".join(chunks)


class StepWatchdog:
    """Heartbeat monitor for the train loop (daemon thread).

    One instance per ``fit()``: ``start()`` arms it, ``beat(step)`` feeds
    it, ``stop()`` joins it. Re-arming after a fired stall requires a new
    beat, so a genuinely hung run logs once instead of every poll.
    """

    def __init__(self, stall_factor: float = 10.0,
                 min_timeout_s: float = 60.0,
                 poll_s: float = 1.0,
                 action: str = "log",
                 on_stall: Optional[Callable[[], None]] = None,
                 registry=None):
        assert action in ("log", "abort"), action
        self.stall_factor = float(stall_factor)
        self.min_timeout_s = float(min_timeout_s)
        self.poll_s = float(poll_s)
        self.action = action
        self.on_stall = on_stall
        self.registry = registry or get_registry()
        self._beats: deque = deque(maxlen=64)  # own fallback intervals
        self._last_beat: Optional[float] = None
        self._last_step = -1
        self._fired_for: Optional[float] = None
        self._suspended = 0  # depth-counted: nested suspended() blocks
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def from_cfg(cls, cfg: Optional[dict],
                 on_stall: Optional[Callable[[], None]] = None,
                 registry=None) -> "StepWatchdog":
        """Build from a ``Resilience.watchdog`` config block."""
        cfg = dict(cfg or {})
        return cls(
            stall_factor=float(cfg.get("stall_factor") or 10.0),
            min_timeout_s=float(60.0 if cfg.get("min_timeout_s") is None
                                else cfg.get("min_timeout_s")),
            poll_s=float(cfg.get("poll_s") or 1.0),
            action=str(cfg.get("action") or "log"),
            on_stall=on_stall, registry=registry)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "StepWatchdog":
        """Start the heartbeat thread (idempotent).

        The detector stays UNARMED until the first ``beat()``: the first
        train step includes XLA compilation (often minutes for a large
        model), and a clock running from ``start()`` would fire a false
        stall — and under ``action: abort`` kill a healthy run — before
        the loop ever had a chance to beat.
        """
        if self._thread is not None:
            return self
        self._stop.clear()
        self._last_beat = None  # armed by the first beat
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleetx-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        """Disarm and join the heartbeat thread (idempotent)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def beat(self, step: int) -> None:
        """Train loop progress signal — call once per completed step."""
        now = time.monotonic()
        if self._last_beat is not None:
            self._beats.append(now - self._last_beat)
        self._last_beat = now  # fleetx: noqa[FX014] -- deliberate lock-free protocol: monitor-thread reads tolerate one stale beat (next poll sees it); a beat()-side lock would put lock traffic on every train step
        self._last_step = step  # fleetx: noqa[FX014] -- same lock-free beat protocol: _run only formats _last_step into the stall report, staleness is cosmetic
        self._fired_for = None  # re-arm after any progress  # fleetx: noqa[FX014] -- same lock-free beat protocol: worst case is one duplicate or suppressed stall report, never a missed wedge (the beat gap keeps growing)

    @contextlib.contextmanager
    def suspended(self):
        """Disarm around a known-long host phase (eval, checkpoint write,
        rollback restore): the phase is legitimate progress-free time a
        post-phase beat can't retroactively excuse — the detector would
        already have fired (and under ``action: abort``, killed the run)
        mid-phase. The clock restarts when the phase ends."""
        self._suspended += 1  # fleetx: noqa[FX014] -- suspended() only runs on the train-loop thread (re-entrant phases nest, hence a counter not a flag); the monitor thread only reads, and a stale read just delays the disarm by one poll
        try:
            yield self
        finally:
            # restart the silence clock BEFORE re-arming: the poll thread
            # must never observe an unsuspended watchdog that still
            # carries the stale pre-phase beat (that ordering race is a
            # false stall). The phase is deliberately NOT recorded as a
            # step interval — it would inflate the median.
            self._last_beat = time.monotonic()
            self._fired_for = None
            self._suspended -= 1

    # ------------------------------------------------------------ internals
    def _median_step_s(self) -> Optional[float]:
        hist = self.registry.histogram("step_time")
        p50 = hist.quantile(0.5)
        if p50:
            return p50
        if self._beats:
            xs = sorted(self._beats)
            return xs[len(xs) // 2]
        return None

    def timeout_s(self) -> float:
        """Current stall threshold in seconds."""
        median = self._median_step_s()
        if median is None:
            return self.min_timeout_s
        return max(self.stall_factor * median, self.min_timeout_s)

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            # order matters: check suspension BEFORE sampling the beat.
            # suspended() refreshes the beat and only then decrements, so
            # a poll that observes _suspended == 0 is guaranteed to read
            # the post-phase beat — the reverse order could pair a stale
            # pre-phase beat with an already-lifted suspension and fire a
            # false stall
            if self._suspended:
                continue
            last = self._last_beat
            if last is None or self._fired_for == last:
                continue
            silent = time.monotonic() - last
            limit = self.timeout_s()
            if silent <= limit:
                continue
            self._fired_for = last  # once per stall episode
            self.registry.counter("watchdog_stalls").inc()
            logger.error(
                "watchdog: no step progress for %.1fs (limit %.1fs, last "
                "step %d) — dumping stacks\n%s", silent, limit,
                self._last_step, _format_all_stacks())
            if self.on_stall is not None:
                try:
                    self.on_stall()
                except Exception as e:  # noqa: BLE001 — flush must not kill us
                    logger.warning("watchdog on_stall callback failed: %s", e)
            if self.action == "abort":
                logger.error("watchdog: aborting process (exit %d)",
                             ABORT_EXIT_CODE)
                os._exit(ABORT_EXIT_CODE)


class GangWatchdog:
    """Distributed hang detector: a timed gang barrier every K steps.

    The per-process :class:`StepWatchdog` sees a silent train loop but
    cannot say WHO wedged the collective — on a pod, every healthy rank's
    watchdog fires identically while the one hung rank says nothing. This
    runs ``coordination.barrier`` on the train-loop thread every
    ``sync_steps`` steps: when it times out, the raised
    ``CoordinationTimeout`` carries the arrival census, so the log names
    the exact straggler set (the missing ranks) next to this rank's own
    stack dump. ``action: abort`` then exits with the watchdog code (43)
    so a gang supervisor tears the survivors down and restarts from the
    last checkpoint — a JAX gang cannot shrink around a lost member.

    ``check()`` is a collective: every rank must call it once per step
    (the internal call counter, not the possibly-resynced global step,
    selects barrier rounds so all ranks agree on which calls rendezvous).
    """

    def __init__(self, coord, sync_steps: int, timeout_s: float = 300.0,
                 action: str = "log", registry=None):
        assert action in ("log", "abort"), action
        self.coord = coord
        self.sync_steps = max(int(sync_steps), 1)
        self.timeout_s = float(timeout_s)
        self.action = action
        self.registry = registry or get_registry()
        self._calls = 0

    @classmethod
    def from_cfg(cls, cfg: Optional[dict], coord, registry=None
                 ) -> Optional["GangWatchdog"]:
        """Build from a ``Resilience.watchdog`` block, or None when the
        gang mode is off (``gang_sync_steps`` unset/0) or the gang has a
        single member (nothing to rendezvous with)."""
        cfg = dict(cfg or {})
        sync_steps = int(cfg.get("gang_sync_steps") or 0)
        if sync_steps < 1 or getattr(coord, "world", 1) < 2:
            return None
        return cls(coord, sync_steps,
                   timeout_s=float(cfg.get("gang_timeout_s") or 300.0),
                   action=str(cfg.get("action") or "log"),
                   registry=registry)

    def check(self, step: int) -> None:
        """Rendezvous round (every ``sync_steps``-th call); on timeout log
        the straggler set + this rank's stacks, then log or abort."""
        from fleetx_tpu.observability import flight
        from fleetx_tpu.resilience.coordination import CoordinationTimeout

        self._calls += 1
        if self._calls % self.sync_steps:
            return
        try:
            self.coord.barrier("gang_watchdog", timeout_s=self.timeout_s)
        except CoordinationTimeout as e:
            self.registry.counter("watchdog_gang_stalls").inc()
            logger.error(
                "gang watchdog: barrier at step %d timed out after %.1fs — "
                "straggler ranks %s (arrived: %s); dumping local stacks\n%s",
                step, self.timeout_s, e.missing, e.arrived,
                _format_all_stacks())
            # the flight ring is this rank's half of the post-mortem the
            # straggler census starts: dump it BEFORE a possible abort
            flight.note("watchdog", "gang_stall", step=int(step),
                        missing=e.missing, arrived=e.arrived)
            flight.dump("gang_watchdog_stall")
            if self.action == "abort":
                logger.error("gang watchdog: aborting process (exit %d)",
                             ABORT_EXIT_CODE)
                os._exit(ABORT_EXIT_CODE)
