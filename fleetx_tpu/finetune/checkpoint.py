"""Adapter-only checkpoint artifact (docs/finetune.md "Adapter artifact").

A fine-tune run's durable product is TINY: the adapter leaves plus enough
provenance to prove what they belong to. The artifact is a ``step_<N>``
directory in the shared checkpoint idiom — ``state.npz`` payload,
``fleetx_integrity.json`` manifest (PR 7), ``fleetx_meta.json``
completion marker — so ``tools/verify_ckpt.py`` audits it unmodified and
the retention/latest-step helpers in ``core/checkpoint.py`` apply as-is.

The meta stamps three identities and the restore REFUSES loudly when any
has drifted (:class:`AdapterDriftError` naming the offending leaf /
fingerprint — an adapter is meaningless against the wrong base and must
never be silently merged):

- ``base_leaves``: per-leaf content digests of the frozen base the
  adapters were trained against (name → crc32/nbytes);
- ``spec_registry``: the partition-rule registry fingerprint
  (``parallel/rules.py``) — unlike full checkpoints, which re-shard onto
  current rules with a warning, adapters refuse on registry drift, since
  the rule table also defines the adapter leaf naming contract;
- ``base_ckpt``: the pretrain checkpoint directory path, recorded for
  operators (informational — the digests are the authority).

Payload integrity itself follows the PR 7 contract: file digests verified
before any byte is decoded, npz leaves re-verified against the per-leaf
digests computed at save.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import numpy as np

from fleetx_tpu.core import checkpoint as ckpt_lib
from fleetx_tpu.finetune import lora
from fleetx_tpu.parallel import rules as rules_lib
from fleetx_tpu.resilience import integrity
from fleetx_tpu.resilience.integrity import CheckpointIntegrityError
from fleetx_tpu.utils.log import logger

__all__ = ["AdapterDriftError", "ADAPTER_ARTIFACT", "save_adapter",
           "load_adapter", "apply_adapter_checkpoint", "adapter_bytes"]

#: meta marker distinguishing adapter artifacts from full checkpoints
ADAPTER_ARTIFACT = "lora_adapter"

_PAYLOAD = "state.npz"


class AdapterDriftError(RuntimeError):
    """An adapter artifact was offered a base (or registry) it was not
    trained against. Never absorbed by retry policies and never merged
    anyway — the caller must re-point the base or re-train the adapter."""


def _step_dir(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory), f"step_{int(step)}")


def save_adapter(directory: str, step: int, params: Any, *,
                 base_dir: Optional[str], rank: int, alpha: float,
                 base_digests: Optional[dict] = None,
                 extra_meta: Optional[dict] = None) -> str:
    """Publish one adapter-only artifact for ``step`` under ``directory``.

    ``params`` is the full fine-tune tree (base + adapters); only the
    adapter leaves are persisted, the base contributes its per-leaf
    digests — pass ``base_digests`` when the caller already holds them
    (the recipe's frozen-base audit just computed exactly these; a full
    re-digest is a whole-base host fetch + CRC). Write order follows the
    core codec's completion contract: payload → manifest → meta marker,
    each atomic, so a directory with a meta is always a fully-described
    artifact.
    """
    path = _step_dir(directory, step)
    os.makedirs(path, exist_ok=True)
    base_tree, adapters = lora.split_adapters(params)
    assert adapters, "params carry no adapter leaves — nothing to save"
    names = sorted(adapters)
    host = [np.ascontiguousarray(np.asarray(jax.device_get(adapters[n])))
            for n in names]
    arrays = {f"leaf_{i}": leaf for i, leaf in enumerate(host)}
    arrays["__names__"] = np.array(names)
    arrays["__dtypes__"] = np.array([str(l.dtype) for l in host])
    integrity.atomic_write(os.path.join(path, _PAYLOAD),
                           lambda f: np.savez(f, **arrays), mode="wb")
    leaf_digests = [integrity.digest_array(leaf) for leaf in host]
    integrity.write_manifest(path, leaves=leaf_digests)
    meta = {
        "step": int(step),
        "artifact": ADAPTER_ARTIFACT,
        "spec_family": "gpt_lora",
        # per-FAMILY fingerprint: the adapter's contract is its own rule
        # table, so an unrelated family's edit never bricks the artifact
        "spec_registry": rules_lib.family_fingerprint("gpt_lora"),
        "base_ckpt": os.path.abspath(base_dir) if base_dir else None,
        "lora": {"rank": int(rank), "alpha": float(alpha),
                 "names": names},
        "base_leaves": dict(base_digests) if base_digests is not None
        else lora.base_leaf_digests(base_tree),
    }
    meta.update(extra_meta or {})
    integrity.atomic_write(os.path.join(path, "fleetx_meta.json"),
                           lambda f: json.dump(meta, f))
    logger.info("saved adapter artifact: %s (%d leaves, %d bytes)", path,
                len(names), adapter_bytes(path))
    return path


def adapter_bytes(path: str) -> int:
    """On-disk payload bytes of one adapter step dir (the <5%-of-base
    acceptance measurement, tests/test_zz_finetune.py)."""
    target = os.path.join(path, _PAYLOAD)
    return os.path.getsize(target) if os.path.exists(target) else 0


def _read_meta(path: str) -> dict:
    """The artifact's meta dict; unreadable/corrupt is a loud failure (an
    adapter without provenance must not be merged)."""
    target = os.path.join(path, "fleetx_meta.json")
    try:
        with open(target) as f:
            meta = json.load(f)
    except (OSError, json.JSONDecodeError, ValueError) as e:
        raise CheckpointIntegrityError(
            f"adapter meta {target} unreadable ({e}) — refusing to merge "
            f"an adapter without provenance") from e
    if meta.get("artifact") != ADAPTER_ARTIFACT:
        raise AdapterDriftError(
            f"{path} is not an adapter artifact (artifact="
            f"{meta.get('artifact')!r}) — point adapter_dir at a "
            f"finetune/checkpoint.py save_adapter directory")
    return meta


def _check_registry(meta: dict, path: str) -> None:
    """Registry drift is a REFUSAL for adapters (full checkpoints only
    warn): the family's rule table also defines the adapter
    naming/placement contract the artifact was trained under. Scoped to
    the artifact's OWN family fingerprint, so edits to other families'
    tables never refuse a published adapter."""
    stamped = meta.get("spec_registry")
    family = meta.get("spec_family") or "gpt_lora"
    current = rules_lib.family_fingerprint(family)
    if stamped != current:
        raise AdapterDriftError(
            f"adapter {path} was saved under {family!r} rule table "
            f"{stamped} but the current table fingerprints as {current} "
            f"— the family's rules have changed since training; refusing "
            f"to merge (re-train the adapter or restore the rules)")


def _check_base(meta: dict, base_params: Any, path: str) -> None:
    """Refuse on base drift, naming the first mismatching leaf."""
    want = dict(meta.get("base_leaves") or {})
    got = lora.base_leaf_digests(base_params)
    missing = sorted(set(want) - set(got))
    if missing:
        raise AdapterDriftError(
            f"adapter {path} expects base leaf {missing[0]!r} which the "
            f"offered base tree lacks ({len(missing)} missing leaves) — "
            f"wrong or restructured base checkpoint")
    extra = sorted(set(got) - set(want))
    if extra:
        raise AdapterDriftError(
            f"offered base tree carries leaf {extra[0]!r} the adapter "
            f"{path} was not trained against ({len(extra)} extra leaves)")
    for name in sorted(want):
        w, g = want[name], got[name]
        if int(w["crc32"]) != int(g["crc32"]) or \
                int(w["nbytes"]) != int(g["nbytes"]):
            raise AdapterDriftError(
                f"base leaf {name!r} has drifted from the weights adapter "
                f"{path} was trained against (crc "
                f"{int(g['crc32']):#010x} != stamped "
                f"{int(w['crc32']):#010x}) — refusing to merge onto the "
                f"wrong base")


def load_adapter(directory: str, step: Optional[int] = None, *,
                 base_params: Any = None) -> tuple[dict, dict]:
    """Load (and fully verify) one adapter artifact.

    Returns ``(adapters_by_name, meta)``. Verification order: manifest
    file digests (payload bytes) → registry fingerprint → base per-leaf
    digests (when ``base_params`` is offered) → npz leaf digests. Any
    failure is a loud :class:`AdapterDriftError` /
    :class:`CheckpointIntegrityError` — never a silent merge.
    """
    directory = os.path.abspath(directory)
    step = step if step is not None else ckpt_lib.latest_step(directory)
    if step is None:
        raise FileNotFoundError(
            f"no adapter artifact under {directory}")
    path = _step_dir(directory, step)
    meta = _read_meta(path)
    manifest = integrity.read_manifest(path)
    if manifest is None:
        raise CheckpointIntegrityError(
            f"adapter {path} carries no integrity manifest — adapter "
            f"artifacts are always manifested; refusing to merge "
            f"unverifiable bytes")
    bad = integrity.verify_files(path, manifest)
    if bad:
        raise CheckpointIntegrityError(
            f"adapter {path} failed integrity verification: files {bad} "
            f"do not match the manifest digests")
    _check_registry(meta, path)
    if base_params is not None:
        _check_base(meta, base_params, path)
    leaf_digests = manifest.get("leaves") or []
    bad_leaves = integrity.verify_npz_leaves(path, leaf_digests)
    if bad_leaves:
        raise CheckpointIntegrityError(
            f"adapter {path} leaves {bad_leaves} do not match their "
            f"manifest digests — refusing to merge corrupt adapters")
    adapters: dict = {}
    with np.load(os.path.join(path, _PAYLOAD)) as data:
        names = [str(n) for n in data["__names__"]]
        dtypes = [str(d) for d in data["__dtypes__"]]
        for i, name in enumerate(names):
            arr = data[f"leaf_{i}"]
            if str(arr.dtype) != dtypes[i]:
                # extension dtype flattened by the npy format — re-view
                arr = arr.view(np.dtype(dtypes[i]))
            adapters[name] = arr
    logger.info("loaded adapter artifact %s (step %d, %d leaves%s)", path,
                int(step), len(adapters),
                ", base verified" if base_params is not None else "")
    return adapters, meta


def apply_adapter_checkpoint(base_params: Any, directory: str,
                             step: Optional[int] = None) -> Any:
    """Base params + adapter artifact → merged serving weights.

    The one-call serving path (``tools/serve.py``): verifies the artifact
    AND the offered base against the stamped digests, grafts the adapter
    leaves, and folds ``B@A`` into the kernels — the returned tree has
    the base model's exact structure, so the quantized decode programs
    run the fine-tuned weights with zero per-token adapter cost.
    """
    adapters, meta = load_adapter(directory, step, base_params=base_params)
    combined = lora.combine_adapters(base_params, adapters)
    return lora.merge_adapters(combined,
                               alpha=float(meta["lora"]["alpha"]))
