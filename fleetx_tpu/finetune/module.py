"""LoRA fine-tuning task module (docs/finetune.md).

``LoRAGPTModule`` is the ``GPTModule`` recipe with three changes and
nothing else:

- ``init_variables`` injects the ``lora_a``/``lora_b`` leaves next to the
  registry-named target kernels (``finetune/lora.py``), so the engine's
  TrainState carries base + adapters as ONE pytree;
- ``spec_family`` is ``gpt_lora`` — the engine, shardcheck, the ZeRO
  helpers and both checkpoint codecs resolve the adapted tree through the
  partition-rule registry with no hand-wiring;
- every pure function (training/validation loss, predict) folds the
  adapters into the base kernels first (``merge_adapters``), so the model
  code runs unmodified while gradients flow to the adapter leaves through
  the fold. The base stays bitwise frozen because the optimizer is
  masked (``lora.lora_optimizer``), not because the math hides it.

Config surface (the ``FineTune:`` YAML section)::

    FineTune:
      base_ckpt: ./output/pretrain      # pretrain checkpoint dir (step_N)
      adapter_dir: ./output/adapters    # where adapter artifacts land
      lora:
        rank: 8
        alpha: 16.0
"""

from __future__ import annotations

from typing import Any

import jax

from fleetx_tpu.core.module import GPTModule
from fleetx_tpu.finetune import lora
from fleetx_tpu.utils.log import logger


class LoRAGPTModule(GPTModule):
    """GPT fine-tuning task: frozen base + trainable low-rank adapters."""

    #: shadows GPTModule's property — the adapted tree is its own registry
    #: family (``parallel/rules.py``), base rules + the adapter rules
    spec_family = "gpt_lora"

    def __init__(self, cfg: Any):
        ft = dict(cfg.get("FineTune") or {}) if isinstance(cfg, dict) else {}
        lora_cfg = dict(ft.get("lora") or {})
        self.lora_rank = int(lora_cfg.get("rank") or 8)
        self.lora_alpha = float(lora_cfg.get("alpha")
                                or 2.0 * self.lora_rank)
        self.base_ckpt = ft.get("base_ckpt")
        self.adapter_dir = ft.get("adapter_dir")
        super().__init__(cfg)
        assert self.model_cfg.moe_num_experts == 0, \
            "LoRA targets the dense GPT stack (gpt_lora rules carry no " \
            "expert templates) — fine-tune the dense model"
        logger.info("LoRA adapters: rank=%d alpha=%.1f targets=%s",
                    self.lora_rank, self.lora_alpha,
                    sorted(lora.LORA_TARGETS))

    def init_variables(self, rng: jax.Array, batch: dict) -> Any:
        """Base init + adapter injection (A small-normal, B zeros — the
        starting model IS the base model; the base values are then
        overwritten by the pretrain restore, ``finetune/recipe.py``)."""
        params = super().init_variables(rng, batch)
        return lora.inject_adapters(params, rank=self.lora_rank,
                                    rng=jax.random.fold_in(rng, 0x10A))

    def _merged(self, params: Any) -> Any:
        """The effective (base ⊕ adapters) tree the model consumes."""
        return lora.merge_adapters(params, alpha=self.lora_alpha)

    def training_loss(self, params, batch, rng, step):
        """Fine-tune loss: the base loss over the merged kernels —
        gradients reach the adapter leaves through the fold."""
        return super().training_loss(self._merged(params), batch, rng,
                                     step)

    def validation_loss(self, params, batch):
        """Validation loss over the merged kernels."""
        return super().validation_loss(self._merged(params), batch)

    def predict_step(self, params, batch):
        """Forward logits over the merged kernels."""
        return super().predict_step(self._merged(params), batch)
