"""Parameter-efficient fine-tuning subsystem (docs/finetune.md).

LoRA adapters from pretrain checkpoint to quantized serving: adapter
injection over registry-named target matmuls (``lora.py``), the
``LoRAGPTModule`` task recipe (``module.py``), the verified adapter-only
checkpoint artifact (``checkpoint.py``) and the end-to-end orchestration
(``recipe.py``). Sharding resolves through the ``gpt_lora`` family of the
partition-rule registry (``parallel/rules.py``) — no hand-wiring in the
engine, the ZeRO helpers, shardcheck or either checkpoint codec.
"""

from fleetx_tpu.finetune.checkpoint import (AdapterDriftError,
                                            apply_adapter_checkpoint,
                                            load_adapter, save_adapter)
from fleetx_tpu.finetune.lora import (adapter_mask, inject_adapters,
                                      lora_optimizer, merge_adapters,
                                      split_adapters,
                                      trainable_params_frac)
from fleetx_tpu.finetune.module import LoRAGPTModule

__all__ = [
    "AdapterDriftError", "LoRAGPTModule", "adapter_mask",
    "apply_adapter_checkpoint", "inject_adapters", "load_adapter",
    "lora_optimizer", "merge_adapters", "save_adapter", "split_adapters",
    "trainable_params_frac",
]
