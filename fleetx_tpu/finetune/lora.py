"""LoRA adapter algebra: injection, masking, merge (docs/finetune.md).

Parameter-efficient fine-tuning following "Fine-Tuning and Serving Gemma
on Google Cloud TPU" (PAPERS.md): the pretrained base pytree stays
bitwise frozen while low-rank ``lora_a``/``lora_b`` leaves — injected as
SIBLINGS of the registry-named target kernels — carry all the learning.
For a target kernel ``W`` with input features ``in`` and output features
``out``, the adapter pair is

- ``A`` (``<kernel>_lora_a``): ``[*stack, *in, r]``, small normal init;
- ``B`` (``<kernel>_lora_b``): ``[*stack, r, *out]``, zero init,

and the effective kernel is ``W + (alpha / r) * A @ B`` — zero at step 0
(``B`` is zeros), so fine-tuning starts exactly at the base model. The
model code is untouched: kernels enter every matmul linearly, so folding
the delta into the kernel before ``model.apply`` is mathematically
identical to running adapters on the side, and autodiff routes gradients
to ``A``/``B`` through the fold.

Everything here is name-driven off the partition-rule registry
(``parallel/rules.py`` family ``gpt_lora``): the adapter leaf names are
what the rule table, the optimizer mask, the adapter-only checkpoint
codec (``finetune/checkpoint.py``) and shardcheck all key on, and the
flax boxing metadata for injected leaves is DERIVED from the registry
templates (:func:`adapter_axis_names`) so the parity gate in
``tests/test_zz_shardcheck.py`` pins both sides to one source of truth.

Scanned stacks ride along for free: a stacked target ``[L, *features]``
gets stacked adapters ``[L, *in, r]`` / ``[L, r, *out]`` and the fold is
a batched matmul over the leading stack dims.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax.core import meta

from fleetx_tpu.parallel import rules as rules_lib

__all__ = [
    "LORA_TARGETS", "ADAPTER_SUFFIXES", "is_adapter_name",
    "inject_adapters", "adapter_axis_names", "adapter_delta",
    "merge_adapters", "split_adapters", "combine_adapters", "adapter_mask",
    "lora_optimizer", "trainable_params_frac", "base_leaf_digests",
]

#: registry-named target matmuls → (feature_rank, n_in): how many trailing
#: dims are the kernel's feature axes, and how many of those are the
#: matmul's INPUT side (the rest are output). Leading dims beyond
#: feature_rank are scanned-stack dims (rules.STACK_AXES).
LORA_TARGETS: dict[str, tuple[int, int]] = {
    "attn/qkv_kernel": (4, 1),   # [h | 3, nh, hd]
    "attn/out_kernel": (3, 2),   # [nh, hd | h]
    "mlp/wi_kernel": (2, 1),     # [h | m]
    "mlp/wo_kernel": (2, 1),     # [m | h]
}

#: the leaf-name suffixes every consumer (rules, mask, codec) keys on
ADAPTER_SUFFIXES = ("_lora_a", "_lora_b")

#: init scale for A (B is zeros, so the starting delta is exactly 0)
_A_INIT_STDDEV = 0.02


def is_adapter_name(name: str) -> bool:
    """True when a slash-joined leaf path names an adapter leaf."""
    return name.endswith(ADAPTER_SUFFIXES)


def _unboxed_value(leaf: Any) -> Any:
    """A leaf's raw array, whether or not it is flax-boxed."""
    return leaf.unbox() if isinstance(leaf, meta.AxisMetadata) else leaf


def adapter_axis_names(family: str, name: str, ndim: int) -> tuple:
    """Full-rank logical axis names for one adapter leaf, derived from the
    family's registry rule (stack padding included) — the flax boxing
    metadata injection attaches so ``nn.get_partition_spec`` and the
    registry resolve identically (the shardcheck parity gate)."""
    matched = rules_lib._matches(family, name)
    if not matched:
        raise KeyError(
            f"no {family!r} rule matches adapter leaf {name!r} — add it to "
            f"PARTITION_RULES (parallel/rules.py)")
    return rules_lib._stack_padded(family, name, matched[0][2], ndim)


def inject_adapters(params: Any, rank: int, rng: jax.Array,
                    family: str = "gpt_lora",
                    targets: Optional[dict] = None) -> Any:
    """Add ``lora_a``/``lora_b`` siblings next to every target kernel.

    ``params`` may be boxed (``nn.Partitioned``, the engine's init tree)
    or raw; injected leaves are boxed iff their target is, with logical
    names derived from the registry (:func:`adapter_axis_names`). Pure
    jnp/`jax.random` ops, so the injection works under ``jax.eval_shape``
    — shardcheck audits the adapted abstract tree on CPU.
    """
    targets = targets or LORA_TARGETS
    counter = [0]

    def walk(node: Any, prefix: str) -> Any:
        if not isinstance(node, dict):
            return node
        out = {}
        for key, value in node.items():
            if isinstance(value, dict):
                out[key] = walk(value, f"{prefix}{key}/")
                continue
            out[key] = value
            full = f"{prefix}{key}"
            hit = next((t for t in targets
                        if full == t or full.endswith("/" + t)), None)
            if hit is None:
                continue
            feature_rank, n_in = targets[hit]
            kernel = _unboxed_value(value)
            shape = tuple(kernel.shape)
            n_stack = len(shape) - feature_rank
            assert 0 <= n_stack <= len(rules_lib.STACK_AXES), (full, shape)
            stack = shape[:n_stack]
            in_dims = shape[n_stack:n_stack + n_in]
            out_dims = shape[n_stack + n_in:]
            counter[0] += 1
            a = _A_INIT_STDDEV * jax.random.normal(
                jax.random.fold_in(rng, counter[0]),
                stack + in_dims + (int(rank),), kernel.dtype)
            b = jnp.zeros(stack + (int(rank),) + out_dims, kernel.dtype)
            for suffix, leaf in (("_lora_a", a), ("_lora_b", b)):
                leaf_key = key + suffix
                if isinstance(value, meta.AxisMetadata):
                    names = adapter_axis_names(
                        family, f"{prefix}{leaf_key}", leaf.ndim)
                    leaf = value.replace_boxed(leaf).replace(names=names)
                out[leaf_key] = leaf
        return out

    return walk(params, "")


def adapter_delta(a: jax.Array, b: jax.Array, kernel_shape: tuple) -> jax.Array:
    """``A @ B`` reshaped to the target kernel's shape.

    ``a`` is ``[*stack, *in, r]``, ``b`` is ``[*stack, r, *out]``; the
    stack depth is inferred from the ranks, the feature dims flatten into
    one matmul per stack entry, and the product unfolds back to
    ``kernel_shape`` — exact for every target regardless of scan/pp
    stacking.
    """
    n_stack = a.ndim + b.ndim - len(kernel_shape) - 2
    assert n_stack >= 0, (a.shape, b.shape, kernel_shape)
    r = a.shape[-1]
    stack = a.shape[:n_stack]
    af = a.reshape(stack + (-1, r))
    bf = b.reshape(stack + (r, -1))
    return jnp.matmul(af, bf).reshape(kernel_shape)


def merge_adapters(params: Any, alpha: float) -> Any:
    """Fold every adapter pair into its base kernel: ``W + (alpha/r)·A@B``.

    Returns a RAW (unboxed) tree in the base model's exact structure —
    the adapter leaves are consumed, so the result drops into
    ``model.apply``, the serving decode programs and the export path with
    no further plumbing. Used per-step by the fine-tune loss (gradients
    flow to A/B through the fold; the base enters as a frozen constant
    under the optimizer mask) and once at serving startup, where the
    merged weights pay nothing over the base model.
    """
    tree = meta.unbox(params)

    def walk(node: Any) -> Any:
        if not isinstance(node, dict):
            return node
        out = {}
        for key, value in node.items():
            if is_adapter_name(key):
                continue
            if isinstance(value, dict):
                out[key] = walk(value)
                continue
            a = node.get(key + "_lora_a")
            b = node.get(key + "_lora_b")
            if a is not None and b is not None:
                scale = jnp.asarray(float(alpha) / int(a.shape[-1]),
                                    value.dtype)
                delta = adapter_delta(a, b, tuple(value.shape))
                out[key] = value + scale * delta.astype(value.dtype)
            else:
                out[key] = value
        return out

    return walk(tree)


def split_adapters(params: Any) -> tuple[Any, dict]:
    """Split a fine-tune tree into ``(base_tree, adapters_by_name)``.

    The base tree keeps the model's structure (adapter leaves removed,
    kernels UNmerged); adapters come back as a flat slash-joined-name →
    array dict — the adapter-only checkpoint codec's storage unit."""
    tree = meta.unbox(params)
    adapters: dict = {}

    def walk(node: Any, prefix: str) -> Any:
        if not isinstance(node, dict):
            return node
        out = {}
        for key, value in node.items():
            full = f"{prefix}{key}"
            if is_adapter_name(key) and not isinstance(value, dict):
                adapters[full] = value
            elif isinstance(value, dict):
                out[key] = walk(value, full + "/")
            else:
                out[key] = value
        return out

    return walk(tree, ""), adapters


def combine_adapters(base_params: Any, adapters: dict) -> Any:
    """Graft flat-named adapter leaves back into a base tree — the inverse
    of :func:`split_adapters`, used by the adapter-checkpoint restore.
    Navigates each name through fresh copies of the nested dicts; a name
    whose scope the base tree lacks is a structural drift and raises."""
    def copy(node: Any) -> Any:
        if not isinstance(node, dict):
            return node
        return {k: copy(v) for k, v in node.items()}

    out = copy(meta.unbox(base_params))
    for name, leaf in adapters.items():
        parts = name.split("/")
        node = out
        for part in parts[:-1]:
            child = node.get(part)
            if not isinstance(child, dict):
                raise KeyError(
                    f"adapter leaf {name!r} does not fit the base tree — "
                    f"missing scope {part!r}")
            node = child
        node[parts[-1]] = leaf
    return out


def adapter_mask(tree: Any) -> Any:
    """Bool pytree over ``tree``: True exactly on adapter leaves.

    THE one trainability mask (docs/finetune.md): the optimizer wrap
    (:func:`lora_optimizer`) and the ``trainable_params_frac`` gauge both
    consume it, so what the optimizer updates and what the telemetry
    reports trainable can never disagree. Works on params, grads or
    updates alike — it keys on tree paths only. Flax metadata boxes count
    as LEAVES here, so ``optax.masked``'s ``MaskedNode`` replaces the
    whole box: the optimizer-state tree then carries MaskedNode at the
    same tree depth the sharding resolver sees after ``meta.unbox``, and
    the engine's out_shardings prefix-match holds."""
    def flag(kp, _leaf) -> bool:
        path = "/".join(rules_lib._keystr(k) for k in kp)
        return any(s in path for s in ADAPTER_SUFFIXES)

    return jax.tree_util.tree_map_with_path(
        flag, tree, is_leaf=lambda x: isinstance(x, meta.AxisMetadata))


def _frozen_mask(tree: Any) -> Any:
    """The mask's complement: True on every non-adapter (frozen) leaf."""
    return jax.tree.map(lambda m: not m, adapter_mask(tree))


def lora_optimizer(inner: Any) -> Any:
    """Mask an optimizer so ONLY adapter leaves ever update.

    ``optax.masked(inner, adapter_mask)`` runs the real transformation on
    the adapter leaves (its state — Adam moments — exists only there, so
    the optimizer state is adapter-sized too); the complementary
    ``set_to_zero`` turns every frozen leaf's update into an exact zero,
    and ``optax.apply_updates``' ``p + 0`` keeps the base pytree bitwise
    frozen (pinned by the fingerprint audit in tests/test_zz_finetune.py).
    """
    import optax

    return optax.chain(
        optax.masked(inner, adapter_mask),
        optax.masked(optax.set_to_zero(), _frozen_mask),
    )


def trainable_params_frac(params: Any) -> float:
    """Trainable (adapter) parameter count over the total — the gauge
    ``bench.py`` emits and ``tools/perf_gate.py`` gates."""
    mask_leaves = jax.tree.leaves(adapter_mask(meta.unbox(params)))
    leaves = jax.tree.leaves(meta.unbox(params))
    total = sum(int(np.prod(l.shape)) for l in leaves)
    trainable = sum(int(np.prod(l.shape))
                    for l, m in zip(leaves, mask_leaves) if m)
    return trainable / max(total, 1)


def base_leaf_digests(params: Any) -> dict:
    """Per-leaf content digests of the BASE (non-adapter) leaves, keyed by
    slash-joined name — the frozen-base identity the adapter checkpoint
    stamps at save and re-verifies at restore, so a drifted base is
    refused naming the exact leaf (docs/finetune.md "Drift refusal")."""
    from fleetx_tpu.resilience import integrity

    out = {}
    for name, leaf in rules_lib.tree_leaf_names(meta.unbox(params)):
        if not is_adapter_name(name):
            out[name] = integrity.digest_array(jax.device_get(leaf))
    return out
