"""Fine-tune orchestration: pretrain checkpoint → LoRA fit → adapter
artifact (docs/finetune.md "End-to-end recipe").

The engine needs no new hooks — the recipe composes existing pieces in a
fixed order:

1. ``engine.prepare`` builds the sharded TrainState (random base +
   injected adapters, ``LoRAGPTModule.init_variables``);
2. the pretrain checkpoint's params restore through the PR 7
   integrity-verified ``load_params`` DIRECTLY onto their registry
   shardings and are grafted over the random base leaves (adapters keep
   their fresh init — B is zeros, so the starting model IS the restored
   base);
3. ``engine.fit`` runs the ordinary loop; the masked optimizer
   (``lora.lora_optimizer``) keeps the base bitwise frozen;
4. the frozen-base audit re-digests every base leaf after fit and
   refuses to publish on any drift (naming the leaf);
5. ``save_adapter`` publishes the tiny adapter-only artifact, stamped
   with the base digests + registry fingerprint the serving restore
   re-verifies.

Grafting is idempotent (the base never changes), so resuming a fine-tune
run from its own full checkpoint (``Engine.save_load.ckpt_dir``) and
re-grafting the same base is safe by construction.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

import jax
from flax.core import meta

from fleetx_tpu.core import checkpoint as ckpt_lib
from fleetx_tpu.finetune import checkpoint as ft_ckpt
from fleetx_tpu.finetune import lora
from fleetx_tpu.observability.metrics import get_registry
from fleetx_tpu.parallel import rules as rules_lib
from fleetx_tpu.utils.log import logger

__all__ = ["graft_base_params", "prepare_finetune", "assert_base_frozen",
           "finetune"]


def graft_base_params(engine: Any, base_params: Any) -> None:
    """Overwrite the engine state's base leaves with restored pretrain
    values, keeping the adapter leaves' fresh init.

    ``base_params`` is the raw tree ``load_params`` returned (already
    registry-sharded on the engine's mesh — the ``gpt`` and ``gpt_lora``
    families share every base rule, so the placements coincide). A
    shape mismatch names the leaf: it means the fine-tune Model section
    disagrees with the checkpoint's architecture.
    """
    flat_base = dict(rules_lib.tree_leaf_names(meta.unbox(base_params)))
    grafted = []
    state_base = []

    def pick(kp, leaf):
        name = "/".join(rules_lib._keystr(k) for k in kp)
        got = flat_base.get(name)
        if got is None:
            if not lora.is_adapter_name(name):
                state_base.append(name)
            return leaf
        if tuple(got.shape) != tuple(leaf.shape) or \
                got.dtype != leaf.dtype:
            raise ValueError(
                f"base checkpoint leaf {name!r} is "
                f"{tuple(got.shape)}/{got.dtype} but the fine-tune model "
                f"expects {tuple(leaf.shape)}/{leaf.dtype} — the FineTune "
                f"Model section does not match the pretrain architecture")
        grafted.append(name)
        return got

    unboxed = jax.tree_util.tree_map_with_path(
        pick, meta.unbox(engine.state.params))
    missing = sorted(set(flat_base) - set(grafted))
    if missing:
        raise ValueError(
            f"base checkpoint carries leaf {missing[0]!r} the fine-tune "
            f"state lacks ({len(missing)} unmatched) — wrong module or "
            f"architecture for this checkpoint")
    if state_base:
        # the symmetric hole: a base leaf the checkpoint does NOT carry
        # would silently keep its seed-random init, and the run would
        # fine-tune (and stamp digests) against a partially random base
        raise ValueError(
            f"fine-tune base leaf {sorted(state_base)[0]!r} is absent "
            f"from the pretrain checkpoint ({len(state_base)} ungrafted) "
            f"— refusing to train against a partially random base")
    # re-attach the flax boxing metadata and the mesh placements
    boxed = jax.tree.map(
        lambda box, leaf: box.replace_boxed(leaf)
        if isinstance(box, meta.AxisMetadata) else leaf,
        jax.eval_shape(lambda: engine.state.params), unboxed,
        is_leaf=lambda x: isinstance(x, meta.AxisMetadata))
    with engine._ctx():
        boxed = jax.device_put(boxed, engine.state_shardings.params)
    engine.state = engine.state.replace(params=boxed)
    logger.info("grafted %d base leaves from the pretrain checkpoint",
                len(grafted))


def prepare_finetune(engine: Any, sample_batch: dict,
                     base_dir: Optional[str]) -> None:
    """Prepare the fine-tune state: engine init, verified base restore +
    graft, and the ``trainable_params_frac`` gauge (the same adapter mask
    the optimizer applies, ``lora.adapter_mask``)."""
    engine.prepare(sample_batch)
    if base_dir:
        base_params = ckpt_lib.load_params(
            str(base_dir), mesh=engine.mesh, layout=engine.spec_layout)
        graft_base_params(engine, base_params)
    frac = lora.trainable_params_frac(engine.state.params)
    get_registry().gauge("trainable_params_frac").set(frac)
    logger.info("trainable_params_frac: %.5f", frac)


def assert_base_frozen(before: dict, after: dict) -> None:
    """Refuse (naming the leaf) unless every base digest is bitwise
    unchanged — the fine-tune loop's frozen-base contract."""
    for name in sorted(before):
        b, a = before[name], after.get(name)
        if a is None or int(a["crc32"]) != int(b["crc32"]) or \
                int(a["nbytes"]) != int(b["nbytes"]):
            raise RuntimeError(
                f"frozen-base violation: leaf {name!r} changed during "
                f"fine-tuning — the optimizer mask did not hold; not "
                f"publishing an adapter trained off its declared base")


def finetune(engine: Any, train_dl: Iterable, valid_dl: Iterable = None, *,
             sample_batch: dict, base_dir: Optional[str],
             adapter_dir: str, epoch_num: int = 1) -> tuple[list, str]:
    """The whole recipe; returns ``(loss curve, adapter artifact path)``.

    Every checkpoint handoff is integrity-verified: the base restore
    (``load_params`` re-digests the PR 7 manifest), the frozen-base audit
    around ``fit``, and the adapter artifact's own manifest + stamped
    base digests that serving re-verifies before merging.
    """
    prepare_finetune(engine, sample_batch, base_dir)
    before = lora.base_leaf_digests(engine.state.params)
    losses = engine.fit(train_dl, valid_dl, epoch_num=epoch_num)
    after = lora.base_leaf_digests(engine.state.params)
    assert_base_frozen(before, after)
    module = engine.module
    step = int(jax.device_get(engine.state.step))
    # the audit just proved `after` describes the current base bit for
    # bit — hand it to the stamp so the publish never re-fetches and
    # re-CRCs the whole base a third time
    path = ft_ckpt.save_adapter(
        adapter_dir, step, engine.state.params, base_dir=base_dir,
        rank=module.lora_rank, alpha=module.lora_alpha,
        base_digests=after)
    return losses, path
