"""Learning-rate schedules.

Re-designs the reference schedulers (``ppfleetx/optims/lr_scheduler.py``) as
pure step→lr functions (optax schedules): no mutable scheduler object, the
schedule is traced into the jitted train step and the step counter lives in
the optimizer state — which is what makes checkpoint/resume exact.

- ``cosine_annealing_with_warmup``: Megatron schedule — linear warmup to
  ``max_lr``, cosine decay to ``min_lr`` over ``decay_steps``, constant
  ``min_lr`` after (reference ``lr_scheduler.py:134-162``).
- ``vit_lr``: warmup + cosine or linear decay to zero over total steps
  (reference ``ViTLRScheduler``, ``lr_scheduler.py:165-203``).
"""

from __future__ import annotations

import jax.numpy as jnp


def cosine_annealing_with_warmup(max_lr: float, min_lr: float = 0.0,
                                 warmup_steps: int = 0,
                                 decay_steps: int = 1):
    """Megatron cosine schedule (reference ``lr_scheduler.py:134-162``)."""
    warmup_steps = int(warmup_steps)
    decay_steps = max(int(decay_steps), 1)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = max_lr * step / jnp.maximum(warmup_steps, 1)
        progress = jnp.clip((step - warmup_steps) / jnp.maximum(decay_steps - warmup_steps, 1),
                            0.0, 1.0)
        cosine = min_lr + 0.5 * (max_lr - min_lr) * (1.0 + jnp.cos(jnp.pi * progress))
        return jnp.where(step < warmup_steps, warm, cosine)

    return schedule


def vit_lr(learning_rate: float, total_steps: int, warmup_steps: int = 0,
           decay_type: str = "cosine", min_lr: float = 0.0):
    """ViT warmup + cosine/linear decay (reference ``lr_scheduler.py:165-203``)."""
    total_steps = max(int(total_steps), 1)
    warmup_steps = int(warmup_steps)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = learning_rate * step / jnp.maximum(warmup_steps, 1)
        progress = jnp.clip((step - warmup_steps) / jnp.maximum(total_steps - warmup_steps, 1),
                            0.0, 1.0)
        if decay_type == "cosine":
            decayed = min_lr + 0.5 * (learning_rate - min_lr) * (1.0 + jnp.cos(jnp.pi * progress))
        elif decay_type == "linear":
            decayed = learning_rate + (min_lr - learning_rate) * progress
        else:
            raise ValueError(f"unknown decay_type {decay_type!r}")
        return jnp.where(step < warmup_steps, warm, decayed)

    return schedule


def constant_lr(learning_rate: float):
    """Fixed learning rate schedule."""
    def schedule(step):
        return jnp.full((), learning_rate, jnp.float32)

    return schedule


SCHEDULERS = {
    "CosineAnnealingWithWarmupDecay": "cosine",
    "cosine": "cosine",
    "ViTLRScheduler": "vit",
    "vit": "vit",
    "constant": "constant",
}


def build_lr_scheduler(cfg: dict):
    """Config-driven scheduler factory (reference ``optims/__init__.py:29-41``).

    Accepts the reference's YAML keys: ``name``, ``max_lr``/``learning_rate``,
    ``min_lr``, ``warmup_rate`` (fraction of decay_steps) or ``warmup_steps``,
    ``decay_steps``.
    """
    cfg = dict(cfg or {})
    name = SCHEDULERS.get(cfg.get("name", "cosine"))
    if name is None:
        raise ValueError(f"unknown lr scheduler {cfg.get('name')!r}")
    if name == "constant":
        return constant_lr(float(cfg.get("learning_rate", cfg.get("max_lr", 1e-4))))
    if name == "vit":
        return vit_lr(
            learning_rate=float(cfg.get("learning_rate", 1e-3)),
            total_steps=int(cfg.get("total_steps", cfg.get("decay_steps", 10000))),
            warmup_steps=int(cfg.get("warmup_steps", 0)),
            decay_type=cfg.get("decay_type", "cosine"),
            min_lr=float(cfg.get("min_lr", 0.0)),
        )
    max_lr = float(cfg.get("max_lr", cfg.get("learning_rate", 1e-4)))
    min_lr = float(cfg.get("min_lr", 0.0))
    decay_steps = int(cfg.get("decay_steps", 10000))
    if "warmup_steps" in cfg:
        warmup_steps = int(cfg["warmup_steps"])
    else:
        warmup_steps = int(float(cfg.get("warmup_rate", 0.0)) * decay_steps)
    return cosine_annealing_with_warmup(max_lr=max_lr, min_lr=min_lr,
                                        warmup_steps=warmup_steps,
                                        decay_steps=decay_steps)
