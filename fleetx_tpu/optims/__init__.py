"""Optimizers and LR schedules (reference ``ppfleetx/optims/``)."""

from fleetx_tpu.optims.lr_scheduler import (  # noqa: F401
    build_lr_scheduler,
    constant_lr,
    cosine_annealing_with_warmup,
    vit_lr,
)
from fleetx_tpu.optims.optimizer import (  # noqa: F401
    adamw,
    build_optimizer,
    decay_mask,
    is_no_decay_path,
    sgd,
)
