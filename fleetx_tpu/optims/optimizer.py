"""Optimizers: AdamW with Megatron-style decay masking + global-norm clip.

Re-designs the reference optimizer layer (``ppfleetx/optims/optimizer.py:91-112``
FusedAdamW over fused buffers; grad clip built at ``optims/__init__.py:49-53``).
On TPU there is nothing to hand-fuse — XLA fuses the update elementwise ops —
so the interesting parts are:

- weight-decay masking by parameter *name*: params whose path contains
  ``bias`` or a norm layer get no decay (reference ``optimizer.py:100-105``);
- global-norm clipping across the whole (possibly sharded) grad pytree —
  under pjit the norm reduction runs as XLA collectives over the mesh;
- multi-precision Adam: f32 master moments even for bf16 params;
- single-pass global norm (docs/zero_sharding.md): the norm is an O(params)
  reduction on the step's critical path, and the stock
  ``optax.clip_by_global_norm`` recomputes what the engine already measured
  for the ``grad_norm`` metric.  ``clip_by_precomputed_norm`` accepts the
  norm as an optax extra arg so the caller threads ONE reduction through
  metric + clip; ``adamw(fused_clip=True)`` goes further and owns the norm
  itself, returning ``(updates, opt_state, grad_norm)`` from ``update``.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax

NO_DECAY_SUBSTRINGS = ("bias", "norm", "layernorm")
NO_DECAY_EXACT = ("ln", "ln1", "ln2", "ln_f")


def is_no_decay_path(path: tuple) -> bool:
    """True if a param path should be excluded from weight decay.

    Mirrors the reference rule — name contains "bias" or "norm"
    (``optimizer.py:100-105``) — applied to flax param tree paths. Norm params
    are named ``scale``/``bias`` under ``ln*`` modules here.
    """
    keys = [getattr(p, "key", getattr(p, "name", str(p))).lower() for p in path]
    for k in keys:
        if any(tok in k for tok in NO_DECAY_SUBSTRINGS) or k in NO_DECAY_EXACT:
            return True
    return False


def decay_mask(params: Any) -> Any:
    """Pytree of bools: True where weight decay applies."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    mask = [not is_no_decay_path(path) for path, _ in flat]
    return jax.tree_util.tree_unflatten(treedef, mask)


def clip_by_precomputed_norm(max_norm: float) -> optax.GradientTransformationExtraArgs:
    """``optax.clip_by_global_norm`` that can reuse a norm computed upstream.

    The caller passes the already-reduced global norm as the ``grad_norm``
    extra arg (``optax.chain`` forwards extra args to every member), so the
    jitted step carries exactly ONE norm reduction shared by the
    ``grad_norm`` metric and the clip.  Without the extra arg the norm is
    computed here — standalone use keeps stock semantics.
    """

    def init(params):
        del params
        return optax.EmptyState()

    def update(updates, state, params=None, *, grad_norm=None, **extra):
        """Clip by ``grad_norm`` when threaded in, else compute the norm."""
        del params, extra
        g_norm = optax.global_norm(updates) if grad_norm is None else grad_norm
        # stock optax semantics: scale only when the norm exceeds the cap,
        # propagating NaN norms into the updates (the engine's finite-guard
        # then skips the step)
        trigger = jnp.squeeze(g_norm < max_norm)

        def clip_fn(t):
            return jax.lax.select(
                trigger, t, (t / g_norm.astype(t.dtype)) * max_norm)

        return jax.tree.map(clip_fn, updates), state

    return optax.GradientTransformationExtraArgs(init, update)


class FusedClipOptimizer:
    """Update path that owns the global norm: ``update`` computes it once,
    clips with it, and returns it — ``(updates, opt_state, grad_norm)``.

    Not an ``optax.GradientTransformation`` (the return arity differs);
    the engine detects the ``fused_clip`` attribute and skips its own
    ``optax.global_norm`` pass entirely.
    """

    fused_clip = True

    def __init__(self, inner: optax.GradientTransformation):
        self._inner = optax.with_extra_args_support(inner)

    def init(self, params):
        return self._inner.init(params)

    def update(self, grads, opt_state, params=None):
        """One norm reduction: clip with it, return it with the updates."""
        grad_norm = optax.global_norm(grads)
        updates, new_state = self._inner.update(
            grads, opt_state, params, grad_norm=grad_norm)
        return updates, new_state, grad_norm


def adamw(learning_rate, *, beta1: float = 0.9, beta2: float = 0.999,
          epsilon: float = 1e-8, weight_decay: float = 0.01,
          grad_clip: float | None = 1.0,
          multi_precision: bool = True, fused_clip: bool = False):
    """AdamW + global-norm clip + name-based decay mask.

    The decay mask is computed lazily from the param tree at ``init`` time via
    ``optax.masked`` with a callable mask, so the same transformation works for
    any model family.  ``fused_clip=True`` returns a ``FusedClipOptimizer``
    whose ``update`` is ``(updates, opt_state, grad_norm)`` — the single-pass
    norm owned by the optimizer instead of threaded in by the caller.
    """
    chain = []
    if grad_clip is not None and grad_clip > 0:
        chain.append(clip_by_precomputed_norm(grad_clip))
    chain.append(optax.scale_by_adam(
        b1=beta1, b2=beta2, eps=epsilon,
        mu_dtype=jnp.float32 if multi_precision else None))
    if weight_decay:
        chain.append(optax.add_decayed_weights(weight_decay, mask=decay_mask))
    chain.append(optax.scale_by_learning_rate(learning_rate))
    tx = optax.chain(*chain)
    return FusedClipOptimizer(tx) if fused_clip else tx


def sgd(learning_rate, *, momentum: float = 0.9,
        grad_clip: float | None = None, fused_clip: bool = False):
    """Plain SGD with optional momentum (reference Momentum optimizer)."""
    chain = []
    if grad_clip is not None and grad_clip > 0:
        chain.append(clip_by_precomputed_norm(grad_clip))
    chain.append(optax.sgd(learning_rate, momentum=momentum))
    tx = optax.chain(*chain)
    return FusedClipOptimizer(tx) if fused_clip else tx


OPTIMIZERS = {"FusedAdamW": adamw, "AdamW": adamw, "adamw": adamw,
              "Momentum": sgd, "sgd": sgd}


def build_optimizer(cfg: dict, lr_schedule) -> optax.GradientTransformation:
    """Config-driven optimizer factory (reference ``optims/__init__.py:44-62``).

    Accepts the reference YAML keys: ``name``, ``beta1/beta2/epsilon``,
    ``weight_decay``, ``grad_clip.clip_norm``, ``multi_precision``.
    """
    cfg = dict(cfg or {})
    name = cfg.get("name", "AdamW")
    fn = OPTIMIZERS.get(name)
    if fn is None:
        raise ValueError(f"unknown optimizer {name!r}")
    clip = cfg.get("grad_clip")
    clip_norm = None
    fused = bool(cfg.get("fused_clip"))
    if isinstance(clip, dict):
        clip_norm = float(clip.get("clip_norm", 1.0))
        fused = bool(clip.get("fused", fused))
    elif clip is not None:
        clip_norm = float(clip)
    if fn is adamw:
        return adamw(
            lr_schedule,
            beta1=float(cfg.get("beta1", 0.9)),
            beta2=float(cfg.get("beta2", 0.999)),
            epsilon=float(cfg.get("epsilon", 1e-8)),
            weight_decay=float(cfg.get("weight_decay", 0.01)),
            grad_clip=clip_norm,
            multi_precision=bool(cfg.get("multi_precision", True)),
            fused_clip=fused,
        )
    return sgd(lr_schedule, momentum=float(cfg.get("momentum", 0.9)),
               grad_clip=clip_norm, fused_clip=fused)
