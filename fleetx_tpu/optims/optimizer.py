"""Optimizers: AdamW with Megatron-style decay masking + global-norm clip.

Re-designs the reference optimizer layer (``ppfleetx/optims/optimizer.py:91-112``
FusedAdamW over fused buffers; grad clip built at ``optims/__init__.py:49-53``).
On TPU there is nothing to hand-fuse — XLA fuses the update elementwise ops —
so the interesting parts are:

- weight-decay masking by parameter *name*: params whose path contains
  ``bias`` or a norm layer get no decay (reference ``optimizer.py:100-105``);
- global-norm clipping across the whole (possibly sharded) grad pytree —
  under pjit the norm reduction runs as XLA collectives over the mesh;
- multi-precision Adam: f32 master moments even for bf16 params.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax

NO_DECAY_SUBSTRINGS = ("bias", "norm", "layernorm")
NO_DECAY_EXACT = ("ln", "ln1", "ln2", "ln_f")


def is_no_decay_path(path: tuple) -> bool:
    """True if a param path should be excluded from weight decay.

    Mirrors the reference rule — name contains "bias" or "norm"
    (``optimizer.py:100-105``) — applied to flax param tree paths. Norm params
    are named ``scale``/``bias`` under ``ln*`` modules here.
    """
    keys = [getattr(p, "key", getattr(p, "name", str(p))).lower() for p in path]
    for k in keys:
        if any(tok in k for tok in NO_DECAY_SUBSTRINGS) or k in NO_DECAY_EXACT:
            return True
    return False


def decay_mask(params: Any) -> Any:
    """Pytree of bools: True where weight decay applies."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    mask = [not is_no_decay_path(path) for path, _ in flat]
    return jax.tree_util.tree_unflatten(treedef, mask)


def adamw(learning_rate, *, beta1: float = 0.9, beta2: float = 0.999,
          epsilon: float = 1e-8, weight_decay: float = 0.01,
          grad_clip: float | None = 1.0,
          multi_precision: bool = True) -> optax.GradientTransformation:
    """AdamW + global-norm clip + name-based decay mask.

    The decay mask is computed lazily from the param tree at ``init`` time via
    ``optax.masked`` with a callable mask, so the same transformation works for
    any model family.
    """
    chain = []
    if grad_clip is not None and grad_clip > 0:
        chain.append(optax.clip_by_global_norm(grad_clip))
    chain.append(optax.scale_by_adam(
        b1=beta1, b2=beta2, eps=epsilon,
        mu_dtype=jnp.float32 if multi_precision else None))
    if weight_decay:
        chain.append(optax.add_decayed_weights(weight_decay, mask=decay_mask))
    chain.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*chain)


def sgd(learning_rate, *, momentum: float = 0.9,
        grad_clip: float | None = None) -> optax.GradientTransformation:
    """Plain SGD with optional momentum (reference Momentum optimizer)."""
    chain = []
    if grad_clip is not None and grad_clip > 0:
        chain.append(optax.clip_by_global_norm(grad_clip))
    chain.append(optax.sgd(learning_rate, momentum=momentum))
    return optax.chain(*chain)


OPTIMIZERS = {"FusedAdamW": adamw, "AdamW": adamw, "adamw": adamw,
              "Momentum": sgd, "sgd": sgd}


def build_optimizer(cfg: dict, lr_schedule) -> optax.GradientTransformation:
    """Config-driven optimizer factory (reference ``optims/__init__.py:44-62``).

    Accepts the reference YAML keys: ``name``, ``beta1/beta2/epsilon``,
    ``weight_decay``, ``grad_clip.clip_norm``, ``multi_precision``.
    """
    cfg = dict(cfg or {})
    name = cfg.get("name", "AdamW")
    fn = OPTIMIZERS.get(name)
    if fn is None:
        raise ValueError(f"unknown optimizer {name!r}")
    clip = cfg.get("grad_clip")
    clip_norm = None
    if isinstance(clip, dict):
        clip_norm = float(clip.get("clip_norm", 1.0))
    elif clip is not None:
        clip_norm = float(clip)
    if fn is adamw:
        return adamw(
            lr_schedule,
            beta1=float(cfg.get("beta1", 0.9)),
            beta2=float(cfg.get("beta2", 0.999)),
            epsilon=float(cfg.get("epsilon", 1e-8)),
            weight_decay=float(cfg.get("weight_decay", 0.01)),
            grad_clip=clip_norm,
            multi_precision=bool(cfg.get("multi_precision", True)),
        )
    return sgd(lr_schedule, momentum=float(cfg.get("momentum", 0.9)),
               grad_clip=clip_norm)
