"""Image preprocessing ops + config-declared chains.

Reference: ``ppfleetx/data/transforms/preprocess.py`` (DecodeImage l.37,
ResizeImage l.108, RandCropImage l.163, RandFlipImage, NormalizeImage l.232,
RandomErasing l.330) and the op-chain builder ``transforms/utils.py:18-41``.
Implemented on PIL + numpy; every op is a callable ``sample -> sample`` over
HWC uint8/float arrays.
"""

from __future__ import annotations

import io
import random
from typing import Any, Sequence

import numpy as np

from fleetx_tpu.utils.log import logger

try:
    from PIL import Image
except ImportError:  # pragma: no cover
    Image = None


class DecodeImage:
    """bytes/path → HWC uint8 RGB (reference ``DecodeImage``)."""

    def __init__(self, to_rgb: bool = True, channel_first: bool = False):
        self.to_rgb = to_rgb
        self.channel_first = channel_first

    def __call__(self, img):
        if isinstance(img, (bytes, bytearray)):
            img = Image.open(io.BytesIO(img))
        elif isinstance(img, str):
            img = Image.open(img)
        if Image is not None and isinstance(img, Image.Image):
            if self.to_rgb:
                img = img.convert("RGB")
            img = np.asarray(img)
        if self.channel_first:
            img = img.transpose(2, 0, 1)
        return img


class ResizeImage:
    """Resize shorter side (or fixed size) (reference ``ResizeImage``)."""

    def __init__(self, size=None, resize_short=None, interpolation="bilinear"):
        assert size is not None or resize_short is not None
        self.size = size
        self.resize_short = resize_short
        self.interpolation = getattr(
            Image, interpolation.upper(), Image.BILINEAR) if Image else None

    def __call__(self, img: np.ndarray) -> np.ndarray:
        h, w = img.shape[:2]
        if self.resize_short:
            scale = self.resize_short / min(h, w)
            out = (round(w * scale), round(h * scale))
        else:
            s = self.size
            out = (s, s) if isinstance(s, int) else (s[1], s[0])
        return np.asarray(Image.fromarray(img).resize(out, self.interpolation))


class CenterCropImage:
    """Center crop to ``size`` (reference CropImage)."""
    def __init__(self, size: int):
        self.size = size

    def __call__(self, img: np.ndarray) -> np.ndarray:
        h, w = img.shape[:2]
        s = self.size
        top, left = max((h - s) // 2, 0), max((w - s) // 2, 0)
        return img[top:top + s, left:left + s]


class RandCropImage:
    """Random resized crop (reference ``RandCropImage``)."""

    def __init__(self, size: int, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
        self.size = size
        self.scale = scale
        self.ratio = ratio

    def __call__(self, img: np.ndarray) -> np.ndarray:
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            aspect = random.uniform(*self.ratio)
            cw = int(round((target * aspect) ** 0.5))
            ch = int(round((target / aspect) ** 0.5))
            if cw <= w and ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                crop = img[top:top + ch, left:left + cw]
                return np.asarray(Image.fromarray(crop).resize(
                    (self.size, self.size), Image.BILINEAR))
        return np.asarray(Image.fromarray(img).resize(
            (self.size, self.size), Image.BILINEAR))


class RandFlipImage:
    """Random horizontal flip (reference RandFlipImage)."""
    def __init__(self, flip_code: int = 1, prob: float = 0.5):
        self.prob = prob

    def __call__(self, img: np.ndarray) -> np.ndarray:
        if random.random() < self.prob:
            return img[:, ::-1]
        return img


class NormalizeImage:
    """scale + mean/std normalize, optional CHW output (reference l.232)."""

    def __init__(self, scale=1.0 / 255.0, mean=(0.485, 0.456, 0.406),
                 std=(0.229, 0.224, 0.225), order="hwc", output_fp16: bool = False):
        self.scale = float(eval(scale)) if isinstance(scale, str) else float(scale)
        self.mean = np.asarray(mean, np.float32).reshape(1, 1, 3)
        self.std = np.asarray(std, np.float32).reshape(1, 1, 3)
        self.order = order
        self.dtype = np.float16 if output_fp16 else np.float32

    def __call__(self, img: np.ndarray) -> np.ndarray:
        x = (img.astype(np.float32) * self.scale - self.mean) / self.std
        if self.order == "chw":
            x = x.transpose(2, 0, 1)
        return x.astype(self.dtype)


class RandomErasing:
    """Random-erase augmentation (reference l.330)."""

    def __init__(self, prob: float = 0.25, scale=(0.02, 0.33),
                 ratio=(0.3, 3.3), value: float = 0.0):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def __call__(self, img: np.ndarray) -> np.ndarray:
        if random.random() >= self.prob:
            return img
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            aspect = random.uniform(*self.ratio)
            eh = int(round((target / aspect) ** 0.5))
            ew = int(round((target * aspect) ** 0.5))
            if eh < h and ew < w:
                top = random.randint(0, h - eh)
                left = random.randint(0, w - ew)
                img = img.copy()
                img[top:top + eh, left:left + ew] = self.value
                return img
        return img


class ToCHWImage:
    """Identity (reference l.281 transposes HWC → CHW). Kept so ported
    reference yamls build, but every model here is NHWC (TPU conv layout) —
    transposing to CHW in the loader only to transpose back on device would
    buy nothing, so the op is a declared no-op."""

    def __call__(self, img: np.ndarray) -> np.ndarray:
        return img


class ColorJitter:
    """Random brightness/contrast/saturation jitter (reference l.295)."""

    def __init__(self, brightness: float = 0.4, contrast: float = 0.4,
                 saturation: float = 0.4, hue: float = 0.0):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation
        if hue:
            logger.warning("ColorJitter hue=%s is not supported (needs HSV "
                           "round-trips); continuing without hue jitter", hue)

    def __call__(self, img: np.ndarray) -> np.ndarray:
        x = img.astype(np.float32)
        if self.brightness:
            x = x * random.uniform(1 - self.brightness, 1 + self.brightness)
        if self.contrast:
            f = random.uniform(1 - self.contrast, 1 + self.contrast)
            x = (x - x.mean()) * f + x.mean()
        if self.saturation:
            f = random.uniform(1 - self.saturation, 1 + self.saturation)
            grey = x.mean(axis=-1, keepdims=True)
            x = (x - grey) * f + grey
        return np.clip(x, 0, 255).astype(img.dtype)


OPS = {cls.__name__: cls for cls in
       (DecodeImage, ResizeImage, CenterCropImage, RandCropImage,
        RandFlipImage, NormalizeImage, RandomErasing, ToCHWImage,
        ColorJitter)}


def build_transforms(ops_cfg: Sequence[dict]):
    """[{OpName: {kwargs}}] → composed callable (reference ``transforms/utils.py``)."""
    ops = []
    names = []
    for item in ops_cfg or []:
        if isinstance(item, str):
            name, kwargs = item, {}
        else:
            (name, kwargs), = item.items()
        names.append(name)
        ops.append(OPS[name](**(kwargs or {})))
    if "ColorJitter" in names and "NormalizeImage" in names and \
            max(i for i, n in enumerate(names) if n == "ColorJitter") > \
            min(i for i, n in enumerate(names) if n == "NormalizeImage"):
        # the jitter clips to [0, 255]; after mean/std normalization that
        # would silently zero every below-mean pixel — op order is static,
        # so reject the misordered chain at build time
        raise ValueError("ColorJitter must come before NormalizeImage in "
                         "transform_ops")

    def apply(x: Any) -> Any:
        for op in ops:
            x = op(x)
        return x

    return apply
