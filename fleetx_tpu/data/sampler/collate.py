"""Composable batch-collate helpers.

Reference: ``ppfleetx/data/sampler/collate.py`` — ``Stack`` (l.27), ``Pad``
(l.70), ``Tuple`` (l.173), ``Dict`` (l.248). Same composition semantics
(each helper is a callable over a list of per-sample fields; ``Tuple`` /
``Dict`` route sample components to per-field collators), re-implemented
over numpy only.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np

__all__ = ["Stack", "Pad", "Tuple", "Dict"]


class Stack:
    """Stack equal-shape fields into ``[batch, ...]``; optional dtype cast."""

    def __init__(self, dtype=None, axis: int = 0):
        self.dtype = dtype
        self.axis = axis

    def __call__(self, data: Sequence[Any]) -> np.ndarray:
        out = np.stack([np.asarray(d) for d in data], axis=self.axis)
        return out.astype(self.dtype) if self.dtype else out


class Pad:
    """Pad ragged 1-d (or leading-dim) fields to the batch max length.

    ``ret_length`` additionally returns the true lengths (reference Pad
    semantics); ``pad_right=False`` left-pads (GPT prompt convention).
    """

    def __init__(self, pad_val=0, axis: int = 0, ret_length: bool = False,
                 dtype=None, pad_right: bool = True):
        self.pad_val = pad_val
        self.axis = axis
        self.ret_length = ret_length
        self.dtype = dtype
        self.pad_right = pad_right

    def __call__(self, data: Sequence[Any]):
        arrays = [np.asarray(d) for d in data]
        lengths = np.array([a.shape[self.axis] for a in arrays], np.int64)
        max_len = int(lengths.max()) if len(arrays) else 0
        out = []
        for a in arrays:
            pad_width = [(0, 0)] * a.ndim
            need = max_len - a.shape[self.axis]
            pad_width[self.axis] = (0, need) if self.pad_right else (need, 0)
            out.append(np.pad(a, pad_width, constant_values=self.pad_val))
        batch = np.stack(out)
        if self.dtype:
            batch = batch.astype(self.dtype)
        if self.ret_length:
            return batch, lengths
        return batch


class Tuple:
    """Route tuple/list sample components to per-component collators
    (reference l.173-246: ``Tuple(Stack(), Pad(0))`` etc.)."""

    def __init__(self, *fn: Callable):
        if len(fn) == 1 and isinstance(fn[0], (list, tuple)):
            fn = tuple(fn[0])
        self.fn = fn

    def __call__(self, data: Sequence[Sequence[Any]]):
        assert all(len(d) == len(self.fn) for d in data), \
            f"sample arity != {len(self.fn)} collators"
        out = []
        for i, f in enumerate(self.fn):
            result = f([d[i] for d in data])
            # flatten (batch, lengths) pairs the way the reference does
            if isinstance(result, tuple):
                out.extend(result)
            else:
                out.append(result)
        return tuple(out)


class Dict:
    """Route dict sample fields to per-key collators (reference l.248-317)."""

    def __init__(self, fn: dict[str, Callable]):
        self.fn = dict(fn)

    def __call__(self, data: Sequence[dict]):
        out = {}
        for key, f in self.fn.items():
            result = f([d[key] for d in data])
            if isinstance(result, tuple):
                out[key] = result[0]
                out[key + "_length"] = result[1]
            else:
                out[key] = result
        return out
