"""Distributed batch sampler with exact mid-epoch resume.

Re-designs the reference ``GPTBatchSampler`` (``ppfleetx/data/sampler/
batch_sampler.py:31-188``): global batches are laid out over the combined
data axes (dp × fsdp — the reference's dp × sharding, ``utils/env.py:76-96``)
and ``consumed_samples`` lets a restarted run continue from the exact sample
the checkpoint stopped at.
"""

from __future__ import annotations

import numpy as np


class DistributedBatchSampler:
    """Rank-sliced random batch sampler (reference ``batch_sampler.py:31-114``)."""

    def __init__(self, dataset_len: int, batch_size: int, *,
                 num_replicas: int = 1, rank: int = 0, shuffle: bool = False,
                 drop_last: bool = True, seed: int = 1234):
        assert 0 <= rank < num_replicas
        self.dataset_len = int(dataset_len)
        self.batch_size = int(batch_size)
        self.num_replicas = int(num_replicas)
        self.rank = int(rank)
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = int(epoch)

    def _indices(self) -> np.ndarray:
        idx = np.arange(self.dataset_len, dtype=np.int64)
        if self.shuffle:
            np.random.RandomState(self.seed + self.epoch).shuffle(idx)
        return idx

    def __iter__(self):
        idx = self._indices()
        global_bs = self.batch_size * self.num_replicas
        n_batches = (len(idx) // global_bs if self.drop_last
                     else -(-len(idx) // global_bs))
        for b in range(n_batches):
            chunk = idx[b * global_bs:(b + 1) * global_bs]
            mine = chunk[self.rank * self.batch_size:
                         (self.rank + 1) * self.batch_size]
            if len(mine) == self.batch_size or not self.drop_last:
                yield mine.tolist()

    def __len__(self) -> int:
        global_bs = self.batch_size * self.num_replicas
        return (self.dataset_len // global_bs if self.drop_last
                else -(-self.dataset_len // global_bs))


class GPTBatchSampler(DistributedBatchSampler):
    """Sequential sampler with ``consumed_samples`` resume
    (reference ``batch_sampler.py:116-188``)."""

    def __init__(self, dataset_len: int, batch_size: int, *,
                 num_replicas: int = 1, rank: int = 0,
                 consumed_samples: int = 0, drop_last: bool = True,
                 seed: int = 1234):
        super().__init__(dataset_len, batch_size, num_replicas=num_replicas,
                         rank=rank, shuffle=False, drop_last=drop_last,
                         seed=seed)
        self.consumed_samples = int(consumed_samples)

    def __iter__(self):
        global_bs = self.batch_size * self.num_replicas
        start = self.consumed_samples
        while start + global_bs <= self.dataset_len:
            chunk = np.arange(start, start + global_bs, dtype=np.int64)
            yield chunk[self.rank * self.batch_size:
                        (self.rank + 1) * self.batch_size].tolist()
            start += global_bs
            self.consumed_samples = start

    def __len__(self) -> int:
        global_bs = self.batch_size * self.num_replicas
        return max(0, (self.dataset_len - self.consumed_samples) // global_bs)
