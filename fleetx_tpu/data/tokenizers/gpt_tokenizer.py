"""GPT-2 byte-level BPE tokenizer.

Re-implements the reference tokenizer (``ppfleetx/data/tokenizers/
gpt_tokenizer.py:90-392``) from the algorithm: reversible byte→unicode
alphabet, greedy pair merging over a ranked merge table, and the GPT-2
pre-tokenisation regex. Two additions over the reference:

- ``train_bpe``: learns a vocab/merge table from raw text, so the stack is
  fully usable offline (the reference can only download pretrained files);
- no framework coupling — pure Python, numpy-out encode for the dataset
  pipeline.
"""

from __future__ import annotations

import json
import os
from functools import lru_cache

import regex as re

# GPT-2 pre-tokeniser (reference gpt_tokenizer.py pattern)
PRETOKENIZE_PAT = re.compile(
    r"""'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+""")


@lru_cache()
def bytes_to_unicode() -> dict[int, str]:
    """Reversible byte→printable-unicode map (reference ``bytes_to_unicode``)."""
    bs = (list(range(ord("!"), ord("~") + 1)) + list(range(ord("¡"), ord("¬") + 1))
          + list(range(ord("®"), ord("ÿ") + 1)))
    cs = bs[:]
    n = 0
    for b in range(2 ** 8):
        if b not in bs:
            bs.append(b)
            cs.append(2 ** 8 + n)
            n += 1
    return dict(zip(bs, [chr(c) for c in cs]))


def get_pairs(word: tuple[str, ...]) -> set[tuple[str, str]]:
    return {(word[i], word[i + 1]) for i in range(len(word) - 1)}


class GPTTokenizer:
    """Byte-level BPE with a ranked merge table.

    ``vocab``: token string → id. ``merges``: ordered list of merge pairs.
    """

    def __init__(self, vocab: dict[str, int], merges: list[tuple[str, str]],
                 eos_token: str = "<|endoftext|>"):
        self.encoder = dict(vocab)
        self.decoder = {v: k for k, v in self.encoder.items()}
        self.bpe_ranks = {tuple(m): i for i, m in enumerate(merges)}
        self.byte_encoder = bytes_to_unicode()
        self.byte_decoder = {v: k for k, v in self.byte_encoder.items()}
        self.cache: dict[str, str] = {}
        self.eos_token = eos_token
        if eos_token not in self.encoder:
            self.encoder[eos_token] = len(self.encoder)
            self.decoder[self.encoder[eos_token]] = eos_token
        self.eos_token_id = self.encoder[eos_token]
        # reference alias: eod == eos for GPT pretraining (gpt_tokenizer.py)
        self.eod_token_id = self.eos_token_id

    # -- construction --------------------------------------------------------
    @classmethod
    def from_files(cls, vocab_file: str, merges_file: str) -> "GPTTokenizer":
        """Load standard GPT-2 ``vocab.json`` + ``merges.txt`` (local paths
        or URLs — URLs go through the download cache, reference
        ``gpt_tokenizer.py:106-140`` + ``utils/download.py``)."""
        from fleetx_tpu.utils.download import cached_path

        vocab_file = cached_path(vocab_file, sub_dir="tokenizers")
        merges_file = cached_path(merges_file, sub_dir="tokenizers")
        with open(vocab_file, encoding="utf-8") as f:
            vocab = json.load(f)
        merges = []
        with open(merges_file, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#version"):
                    continue
                a, b = line.split()
                merges.append((a, b))
        return cls(vocab, merges)

    @classmethod
    def from_pretrained(cls, path: str) -> "GPTTokenizer":
        return cls.from_files(os.path.join(path, "vocab.json"),
                              os.path.join(path, "merges.txt"))

    def save_pretrained(self, path: str) -> None:
        """Write vocab.json + merges.txt under ``path``."""
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "vocab.json"), "w", encoding="utf-8") as f:
            json.dump(self.encoder, f, ensure_ascii=False)
        merges = sorted(self.bpe_ranks.items(), key=lambda kv: kv[1])
        with open(os.path.join(path, "merges.txt"), "w", encoding="utf-8") as f:
            f.write("#version: 0.2\n")
            for (a, b), _ in merges:
                f.write(f"{a} {b}\n")

    @property
    def vocab_size(self) -> int:
        return len(self.encoder)

    # -- core ----------------------------------------------------------------
    def bpe(self, token: str) -> str:
        """Greedy merge loop over one pre-token (canonical GPT-2 BPE)."""
        if token in self.cache:
            return self.cache[token]
        word = tuple(token)
        pairs = get_pairs(word)
        if not pairs:
            return token
        while True:
            bigram = min(pairs, key=lambda p: self.bpe_ranks.get(p, float("inf")))
            if bigram not in self.bpe_ranks:
                break
            first, second = bigram
            new_word: list[str] = []
            i = 0
            while i < len(word):
                try:
                    j = word.index(first, i)
                except ValueError:
                    new_word.extend(word[i:])
                    break
                new_word.extend(word[i:j])
                i = j
                if i < len(word) - 1 and word[i + 1] == second:
                    new_word.append(first + second)
                    i += 2
                else:
                    new_word.append(word[i])
                    i += 1
            word = tuple(new_word)
            if len(word) == 1:
                break
            pairs = get_pairs(word)
        out = " ".join(word)
        self.cache[token] = out  # fleetx: noqa[FX014] -- idempotent memo write: BPE is deterministic per token, the GIL keeps the dict store atomic, and a lost race costs one recompute — a cache lock would serialise every handler thread
        return out

    def encode(self, text: str) -> list[int]:
        """Text -> token ids."""
        ids: list[int] = []
        for tok in PRETOKENIZE_PAT.findall(text):
            mapped = "".join(self.byte_encoder[b] for b in tok.encode("utf-8"))
            ids.extend(self.encoder[t] for t in self.bpe(mapped).split(" "))
        return ids

    def decode(self, ids) -> str:
        """Token ids -> text."""
        # ids beyond the vocab (model vocabs are padded past the tokenizer's,
        # e.g. 50304 vs 50257) decode to nothing rather than crashing
        text = "".join(self.decoder.get(int(i), "") for i in ids)
        data = bytearray(self.byte_decoder[c] for c in text
                         if c in self.byte_decoder)
        # tokens not from the byte alphabet (e.g. <|endoftext|>) decode as-is
        out = data.decode("utf-8", errors="replace")
        if self.eos_token in text:
            # preserve explicit eos markers textually
            pass
        return out

    def __call__(self, text: str) -> list[int]:
        return self.encode(text)


def _count_words(texts) -> dict:
    """Pretokenize + byte-map ``texts`` into word -> count (shared by both
    BPE trainers, which must stay bit-identical)."""
    byte_encoder = bytes_to_unicode()
    word_counts: dict[tuple[str, ...], int] = {}
    for text in texts:
        for tok in PRETOKENIZE_PAT.findall(text):
            mapped = tuple(byte_encoder[b] for b in tok.encode("utf-8"))
            if mapped:
                word_counts[mapped] = word_counts.get(mapped, 0) + 1
    return word_counts


def _apply_merge(word: tuple, best: tuple, merged: str) -> tuple:
    """Rewrite ``word`` with every (non-overlapping, left-to-right)
    occurrence of pair ``best`` fused into ``merged``."""
    out: list[str] = []
    i = 0
    while i < len(word):
        if i < len(word) - 1 and (word[i], word[i + 1]) == best:
            out.append(merged)
            i += 2
        else:
            out.append(word[i])
            i += 1
    return tuple(out)


def _train_bpe_naive(texts, vocab_size: int, eos_token: str = "<|endoftext|>"):
    """Naive BPE trainer: full pair recount per merge, O(merges x words).

    Kept as the executable specification for ``train_bpe`` (the incremental
    trainer must reproduce its output bit-identically — see
    ``tests/test_data.py``); use ``train_bpe`` for anything bigger than a
    test corpus.
    """
    alphabet = sorted(bytes_to_unicode().values())
    vocab = {ch: i for i, ch in enumerate(alphabet)}
    merges: list[tuple[str, str]] = []

    words = _count_words(texts)
    while len(vocab) < vocab_size - 1:  # -1 reserves the eos slot
        pair_counts: dict[tuple[str, str], int] = {}
        for word, cnt in words.items():
            for p in zip(word, word[1:]):
                pair_counts[p] = pair_counts.get(p, 0) + cnt
        if not pair_counts:
            break
        best = max(pair_counts.items(), key=lambda kv: (kv[1], kv[0]))[0]
        merges.append(best)
        merged = best[0] + best[1]
        vocab[merged] = len(vocab)
        new_words = {}
        for word, cnt in words.items():
            out = _apply_merge(word, best, merged)
            new_words[out] = new_words.get(out, 0) + cnt
        words = new_words

    return GPTTokenizer(vocab, merges, eos_token=eos_token)


def _inv_str(s: str) -> tuple:
    """Order-inverting key for strings: ``a < b  <=>  _inv_str(a) > _inv_str(b)``.

    Negated code points, with a ``+1`` sentinel so that a proper prefix
    (which sorts *before* its extension) maps to a *larger* key.
    """
    return tuple(-ord(c) for c in s) + (1,)


def train_bpe(texts, vocab_size: int, eos_token: str = "<|endoftext|>"):
    """Learn a byte-level BPE vocab + merges from an iterable of texts.

    Same algorithm and selection order as ``_train_bpe_naive`` (most
    frequent pair first, ties broken by lexicographically largest pair),
    but with *incremental* pair counting: each merge touches only the words
    containing the merged pair, and the arg-max is a lazy max-heap instead
    of a full recount. This makes a real vocab (16k-50k merges) over a
    tens-of-MB corpus train in minutes where the naive recount takes hours.
    """
    import heapq

    alphabet = sorted(bytes_to_unicode().values())
    vocab = {ch: i for i, ch in enumerate(alphabet)}
    merges: list[tuple[str, str]] = []

    words = _count_words(texts)
    pair_counts: dict[tuple[str, str], int] = {}
    # pair -> set of words currently containing it (occurrence index)
    where: dict[tuple[str, str], set] = {}
    for word, cnt in words.items():
        for p in zip(word, word[1:]):
            pair_counts[p] = pair_counts.get(p, 0) + cnt
            where.setdefault(p, set()).add(word)

    # lazy max-heap over (count, pair); mutated entries are stale and get
    # validated against pair_counts at pop time
    heap = [(-c, _inv_str(p[0]), _inv_str(p[1]), p)
            for p, c in pair_counts.items()]
    heapq.heapify(heap)

    def push(p: tuple[str, str]) -> None:
        heapq.heappush(heap, (-pair_counts[p], _inv_str(p[0]),
                              _inv_str(p[1]), p))

    while len(vocab) < vocab_size - 1:  # -1 reserves the eos slot
        best = None
        while heap:
            neg_c, _, _, p = heapq.heappop(heap)
            if neg_c < 0 and pair_counts.get(p, 0) == -neg_c:
                best = p
                break
        if best is None:
            break
        merges.append(best)
        merged = best[0] + best[1]
        vocab[merged] = len(vocab)

        changed: list[tuple[tuple, tuple, int]] = []
        for word in list(where.get(best, ())):
            cnt = words.pop(word, 0)
            if cnt == 0:
                continue
            changed.append((word, _apply_merge(word, best, merged), cnt))

        touched: set = set()
        for old, new, cnt in changed:
            for p in zip(old, old[1:]):
                pair_counts[p] -= cnt
                occ = where.get(p)
                if occ is not None:
                    occ.discard(old)
                touched.add(p)
        for _, new, cnt in changed:
            words[new] = words.get(new, 0) + cnt
        # occurrence/count updates keyed by the FINAL accumulated words so
        # two old words collapsing into one new word index it once
        for _, new, cnt in changed:
            for p in zip(new, new[1:]):
                pair_counts[p] = pair_counts.get(p, 0) + cnt
                where.setdefault(p, set()).add(new)
                touched.add(p)
        for p in touched:
            if pair_counts.get(p, 0) <= 0:
                pair_counts.pop(p, None)
                where.pop(p, None)
            else:
                push(p)

    return GPTTokenizer(vocab, merges, eos_token=eos_token)
