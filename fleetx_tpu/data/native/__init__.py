"""ctypes binding for the native index builder.

The reference JIT-compiles its pybind11 helper with ``make`` on first use
(``ppfleetx/data/dataset/gpt_dataset.py:47-69``); this does the same for a
plain C-ABI shared object (the image has no pybind11 — ctypes avoids any
build-time Python dependency). ``index_builder`` raises ImportError-style
failures loudly; callers decide whether to fall back to the numpy path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libindex_builder.so")
_lock = threading.Lock()


class _IndexBuilder:
    """Lazy build-on-first-use wrapper (reference compile.py semantics)."""

    def __init__(self) -> None:
        self._lib: ctypes.CDLL | None = None

    def _ensure(self) -> ctypes.CDLL:
        if self._lib is not None:
            return self._lib
        with _lock:
            if self._lib is not None:
                return self._lib
            src = os.path.join(_DIR, "index_builder.cpp")
            if not os.path.exists(_SO) or (
                    os.path.getmtime(_SO) < os.path.getmtime(src)):
                subprocess.check_call(  # fleetx: noqa[FX016] -- serialising the first-use compile IS the lock's job: concurrent loaders must block here rather than race make / dlopen a half-written .so
                    ["make", "-C", _DIR], stdout=subprocess.DEVNULL)
            lib = ctypes.CDLL(_SO)
            lib.build_sample_idx.argtypes = [
                ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64)]
            lib.build_sample_idx.restype = None
            lib.build_blending_indices.argtypes = [
                ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
                ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int64)]
            lib.build_blending_indices.restype = None
            self._lib = lib
            return lib

    @staticmethod
    def _ptr(arr: np.ndarray, ctype):
        return arr.ctypes.data_as(ctypes.POINTER(ctype))

    def build_sample_idx(self, sizes: np.ndarray, doc_idx: np.ndarray,
                         seq_length: int, num_samples: int) -> np.ndarray:
        """[num_samples+1, 2] (doc_idx position, token offset) — identical to
        the numpy ``gpt_dataset.build_sample_idx``."""
        lib = self._ensure()
        sizes = np.ascontiguousarray(sizes, np.int32)
        doc_idx = np.ascontiguousarray(doc_idx, np.int32)
        total = int(sizes[doc_idx].astype(np.int64).sum())
        num_samples = min(int(num_samples), (total - 1) // int(seq_length))
        out = np.empty((num_samples + 1, 2), np.int64)
        lib.build_sample_idx(
            self._ptr(sizes, ctypes.c_int32), self._ptr(doc_idx, ctypes.c_int32),
            len(doc_idx), int(seq_length), num_samples,
            self._ptr(out, ctypes.c_int64))
        return out

    def build_blending_indices(self, weights: np.ndarray,
                               num_samples: int) -> tuple[np.ndarray, np.ndarray]:
        """(dataset_index, dataset_sample_index) for weighted corpus blending."""
        lib = self._ensure()
        weights = np.ascontiguousarray(weights, np.float64)
        assert len(weights) <= 256, "at most 256 blended datasets"
        ds_idx = np.empty(int(num_samples), np.int32)
        ds_sample_idx = np.empty(int(num_samples), np.int64)
        lib.build_blending_indices(
            self._ptr(weights, ctypes.c_double), len(weights), int(num_samples),
            self._ptr(ds_idx, ctypes.c_int32),
            self._ptr(ds_sample_idx, ctypes.c_int64))
        return ds_idx, ds_sample_idx


index_builder = _IndexBuilder()
