// Native dataset-index builder (C ABI, consumed via ctypes).
//
// TPU-native counterpart of the reference's only in-repo native component,
// the pybind11 helper `ppfleetx/data/data_tools/cpp/fast_index_map_helpers.cpp`
// (build_sample_idx l.92-190, build_blending_indices l.32-89). The Python
// side (`fleetx_tpu/data/dataset/gpt_dataset.py`) has a vectorised numpy
// fallback; this builder must produce byte-identical outputs (asserted by
// tests/test_native_index.py) while using O(1) memory per step instead of
// materialising the cumulative-length array.
//
// Build: `make -C fleetx_tpu/data/native` (done automatically on first use).

#include <cstdint>

extern "C" {

// Sample index for GPT pretraining: sample i starts at stream position
// i*seq_length of the doc_idx-ordered token stream. Writes
// (doc_idx position, token offset) rows into out[(num_samples+1) x 2].
// num_samples must already be clamped to (total_tokens-1)/seq_length.
void build_sample_idx(const int32_t* sizes, const int32_t* doc_idx,
                      int64_t n_docs, int64_t seq_length, int64_t num_samples,
                      int64_t* out) {
  int64_t pos = 0;          // index into doc_idx
  int64_t cum_before = 0;   // tokens in docs [0, pos)
  for (int64_t i = 0; i <= num_samples; ++i) {
    const int64_t start = i * seq_length;
    while (pos < n_docs &&
           cum_before + static_cast<int64_t>(sizes[doc_idx[pos]]) <= start) {
      cum_before += static_cast<int64_t>(sizes[doc_idx[pos]]);
      ++pos;
    }
    out[2 * i] = pos;
    out[2 * i + 1] = start - cum_before;
  }
}

// Error-minimising greedy assignment of samples to weighted datasets
// (multi-corpus blending, reference build_blending_indices l.32-89):
// at every step pick the dataset whose achieved fraction lags its weight
// the most.
void build_blending_indices(const double* weights, int64_t n_datasets,
                            int64_t num_samples, int32_t* dataset_index,
                            int64_t* dataset_sample_index) {
  int64_t counts[256];
  for (int64_t d = 0; d < n_datasets && d < 256; ++d) counts[d] = 0;
  for (int64_t i = 0; i < num_samples; ++i) {
    const double target = static_cast<double>(i + 1);
    int64_t best = 0;
    double best_err = weights[0] * target - static_cast<double>(counts[0]);
    for (int64_t d = 1; d < n_datasets; ++d) {
      const double err = weights[d] * target - static_cast<double>(counts[d]);
      if (err > best_err) {
        best_err = err;
        best = d;
      }
    }
    dataset_index[i] = static_cast<int32_t>(best);
    dataset_sample_index[i] = counts[best];
    ++counts[best];
  }
}

}  // extern "C"
