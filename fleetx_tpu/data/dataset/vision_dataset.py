"""Vision datasets: list-file image folders, CIFAR, synthetic.

Reference: ``ppfleetx/data/dataset/vision_dataset.py`` (GeneralClsDataset
l.26, ImageFolder l.105, CIFAR l.295). All return ``{"images": HWC float,
"labels": int}`` samples for ``GeneralClsModule``.
"""

from __future__ import annotations

import os
import pickle

import numpy as np

from fleetx_tpu.data.transforms.preprocess import build_transforms

DEFAULT_TRANSFORM_OPS = [{"DecodeImage": {}},
                         {"ResizeImage": {"size": 224}},
                         {"NormalizeImage": {}}]


class GeneralClsDataset:
    """ImageNet-style ``<root>/<list_file>`` with ``path label`` lines
    (reference ``GeneralClsDataset``)."""

    def __init__(self, image_root: str, cls_label_path: str, transform_ops=None,
                 delimiter: str = " "):
        self.root = image_root
        self.transform = build_transforms(transform_ops
                                          or DEFAULT_TRANSFORM_OPS)
        self.samples: list[tuple[str, int]] = []
        with open(cls_label_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                path, label = line.rsplit(delimiter, 1)
                self.samples.append((path, int(label)))

    def __len__(self) -> int:
        return len(self.samples)

    def __getitem__(self, i: int) -> dict:
        path, label = self.samples[i]
        img = self.transform(os.path.join(self.root, path))
        return {"images": np.asarray(img, np.float32), "labels": np.int32(label)}


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp")


class ImageFolder:
    """``root/<class>/**/<image>`` directory-tree dataset (reference
    ``ImageFolder``, ``vision_dataset.py:105``): class names are the sorted
    first-level directory names; images found recursively."""

    def __init__(self, root: str, transform_ops=None):
        self.root = root
        self.transform = build_transforms(transform_ops
                                          or DEFAULT_TRANSFORM_OPS)
        self.classes = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(self.classes)}
        self.samples: list[tuple[str, int]] = []
        for cls in self.classes:
            for dirpath, _, files in sorted(os.walk(os.path.join(root, cls))):
                for name in sorted(files):
                    if name.lower().endswith(IMG_EXTENSIONS):
                        self.samples.append(
                            (os.path.join(dirpath, name),
                             self.class_to_idx[cls]))

    def __len__(self) -> int:
        return len(self.samples)

    def __getitem__(self, i: int) -> dict:
        path, label = self.samples[i]
        return {"images": np.asarray(self.transform(path), np.float32),
                "labels": np.int32(label)}


class CIFAR10:
    """CIFAR-10 from the standard local python-pickle batches
    (reference ``CIFAR``; no download — zero-egress environment)."""

    def __init__(self, data_dir: str, mode: str = "train", transform_ops=None):
        files = ([f"data_batch_{i}" for i in range(1, 6)] if mode == "train"
                 else ["test_batch"])
        xs, ys = [], []
        for name in files:
            with open(os.path.join(data_dir, name), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(d[b"data"])
            ys.extend(d[b"labels"])
        self.images = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        self.labels = np.asarray(ys, np.int32)
        self.transform = build_transforms(transform_ops) if transform_ops else None

    def __len__(self) -> int:
        return len(self.labels)

    def __getitem__(self, i: int) -> dict:
        img = self.images[i]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32) / 255.0
        return {"images": np.asarray(img, np.float32), "labels": self.labels[i]}


class SyntheticVisionDataset:
    """Random-image dataset for smoke runs and throughput benchmarking."""

    def __init__(self, *, num_samples: int, image_size: int = 224,
                 num_classes: int = 1000, seed: int = 0, **_unused):
        self.num_samples = int(num_samples)
        self.image_size = int(image_size)
        self.num_classes = int(num_classes)
        self.seed = int(seed)

    def __len__(self) -> int:
        return self.num_samples

    def __getitem__(self, i: int) -> dict:
        rng = np.random.RandomState(self.seed + int(i))
        img = rng.randn(self.image_size, self.image_size, 3).astype(np.float32)
        return {"images": img,
                "labels": np.int32(rng.randint(0, self.num_classes))}
