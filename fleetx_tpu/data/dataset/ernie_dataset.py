"""ERNIE masked-LM pretraining datasets.

The reference's ``ernie_dataset.py`` is a 20-line stub (it never shipped a
working ERNIE data path); here the batch contract the model needs —
``input_ids / token_type_ids / attention_mask / mlm_labels /
next_sentence_labels`` — is produced two ways:

- ``ErnieDataset``: BERT-style dynamic masking over the same memmap
  ``{prefix}_ids.npy`` / ``{prefix}_idx.npz`` pair the GPT pipeline uses
  (tools/preprocess_data.py output): 15% of positions masked (80% [MASK],
  10% random, 10% kept); next-sentence pairs are adjacent spans of one
  document, negatives pair spans of two different documents.
- ``SyntheticErnieDataset``: deterministic random batches for smoke runs.
"""

from __future__ import annotations

import numpy as np

# unmasked-position sentinel in mlm_labels; must equal the model side's
# fleetx_tpu.models.ernie.model.IGNORE_INDEX (asserted in tests/test_ernie.py)
# — kept as a local literal so dataloader workers never import jax/flax
IGNORE_INDEX = -100


def apply_mlm_mask(tokens: np.ndarray, rng: np.random.RandomState, *,
                   vocab_size: int, mask_id: int, mask_prob: float = 0.15,
                   special_ids: tuple = ()) -> tuple[np.ndarray, np.ndarray]:
    """BERT masking: returns (masked_tokens, mlm_labels) with IGNORE_INDEX
    on unmasked positions (ignored by the criterion)."""
    tokens = tokens.copy()
    labels = np.full_like(tokens, IGNORE_INDEX)
    maskable = ~np.isin(tokens, list(special_ids))
    pick = (rng.rand(*tokens.shape) < mask_prob) & maskable
    labels[pick] = tokens[pick]
    roll = rng.rand(*tokens.shape)
    tokens[pick & (roll < 0.8)] = mask_id
    rand_pick = pick & (roll >= 0.8) & (roll < 0.9)
    tokens[rand_pick] = rng.randint(0, vocab_size, rand_pick.sum())
    return tokens, labels


class ErnieDataset:
    """Sentence-pair masked-LM dataset over a memmap token stream."""

    def __init__(self, data_prefix: str, *, num_samples: int,
                 seq_length: int = 512, vocab_size: int = 40000,
                 seed: int = 1234, cls_id: int = 1, sep_id: int = 2,
                 mask_id: int = 3, **_unused):
        self.tokens = np.load(data_prefix + "_ids.npy", mmap_mode="r")
        idx = np.load(data_prefix + "_idx.npz")
        self.doc_lens = idx["lens"].astype(np.int64)
        self.doc_starts = np.concatenate([[0], np.cumsum(self.doc_lens)])
        self.num_samples = int(num_samples)
        self.seq_length = int(seq_length)
        self.vocab_size = int(vocab_size)
        self.seed = int(seed)
        self.cls_id, self.sep_id, self.mask_id = cls_id, sep_id, mask_id

    def __len__(self) -> int:
        return self.num_samples

    def _doc_slice(self, doc: int, off: int, length: int) -> np.ndarray:
        """``length`` tokens of document ``doc`` starting at ``off``,
        wrapping WITHIN the document when it is too short. Reads only the
        needed positions from the memmap (O(length), not O(doc_len))."""
        start = int(self.doc_starts[doc])
        dl = max(int(self.doc_lens[doc]), 1)
        if off + length <= dl:  # common case: one contiguous read
            return np.asarray(self.tokens[start + off: start + off + length],
                              np.int64)
        idx = start + (int(off) + np.arange(length)) % dl
        return np.asarray(self.tokens[idx], np.int64)

    def __getitem__(self, i: int) -> dict:
        rng = np.random.RandomState(self.seed + int(i))
        s = self.seq_length
        half = (s - 3) // 2
        blen = s - 3 - half
        # BERT NSP semantics (VERDICT r3 weakness #5): "next" pairs are
        # ADJACENT spans of the SAME document; negatives pair spans from
        # two DIFFERENT documents — the earlier swap-order proxy carried
        # zero signal (both segments were independent random draws)
        ndocs = len(self.doc_lens)
        is_next = int(rng.rand() < 0.5)
        doc_a = int(rng.randint(0, ndocs))
        if is_next:
            dl = int(self.doc_lens[doc_a])
            off = int(rng.randint(0, max(dl - (half + blen), 1)))
            a = self._doc_slice(doc_a, off, half)
            b = self._doc_slice(doc_a, off + half, blen)
        else:
            doc_b = int(rng.randint(0, max(ndocs - 1, 1)))
            if ndocs > 1 and doc_b >= doc_a:
                doc_b += 1
            a = self._doc_slice(doc_a,
                                rng.randint(0, max(int(self.doc_lens[doc_a])
                                                   - half, 1)), half)
            b = self._doc_slice(doc_b,
                                rng.randint(0, max(int(self.doc_lens[doc_b])
                                                   - blen, 1)), blen)
        ids = np.concatenate([[self.cls_id], a, [self.sep_id], b,
                              [self.sep_id]]).astype(np.int64)
        token_type = np.concatenate([
            np.zeros(2 + len(a), np.int32), np.ones(len(b) + 1, np.int32)])
        masked, labels = apply_mlm_mask(
            ids, rng, vocab_size=self.vocab_size, mask_id=self.mask_id,
            special_ids=(self.cls_id, self.sep_id))
        return {
            "input_ids": masked.astype(np.int32),
            "token_type_ids": token_type,
            "attention_mask": np.ones(s, np.int32),
            "mlm_labels": labels.astype(np.int32),
            "next_sentence_labels": np.int32(is_next),
        }


class SyntheticErnieDataset:
    """Deterministic random masked-LM batches (zero data files)."""

    def __init__(self, *, num_samples: int = 1024, seq_length: int = 512,
                 vocab_size: int = 40000, seed: int = 1234, mask_id: int = 3,
                 **_unused):
        self.num_samples = int(num_samples)
        self.seq_length = int(seq_length)
        self.vocab_size = int(vocab_size)
        self.seed = int(seed)
        self.mask_id = mask_id

    def __len__(self) -> int:
        return self.num_samples

    def __getitem__(self, i: int) -> dict:
        rng = np.random.RandomState(self.seed + int(i))
        s = self.seq_length
        ids = rng.randint(4, self.vocab_size, size=s).astype(np.int64)
        masked, labels = apply_mlm_mask(ids, rng, vocab_size=self.vocab_size,
                                        mask_id=self.mask_id)
        return {
            "input_ids": masked.astype(np.int32),
            "token_type_ids": (np.arange(s) >= s // 2).astype(np.int32),
            "attention_mask": np.ones(s, np.int32),
            "mlm_labels": labels.astype(np.int32),
            "next_sentence_labels": np.int32(rng.rand() < 0.5),
        }
