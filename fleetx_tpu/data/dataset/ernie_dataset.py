"""ERNIE masked-LM pretraining datasets.

The reference's ``ernie_dataset.py`` is a 20-line stub (it never shipped a
working ERNIE data path); here the batch contract the model needs —
``input_ids / token_type_ids / attention_mask / mlm_labels /
next_sentence_labels`` — is produced two ways:

- ``ErnieDataset``: BERT-style dynamic masking over the same memmap
  ``{prefix}_ids.npy`` / ``{prefix}_idx.npz`` pair the GPT pipeline uses
  (tools/preprocess_data.py output): 15% of positions masked (80% [MASK],
  10% random, 10% kept), sentence-pair rows with a random 50% swap for the
  next-sentence objective.
- ``SyntheticErnieDataset``: deterministic random batches for smoke runs.
"""

from __future__ import annotations

import numpy as np


def apply_mlm_mask(tokens: np.ndarray, rng: np.random.RandomState, *,
                   vocab_size: int, mask_id: int, mask_prob: float = 0.15,
                   special_ids: tuple = ()) -> tuple[np.ndarray, np.ndarray]:
    """BERT masking: returns (masked_tokens, mlm_labels) with -100 on
    unmasked positions (ignored by the criterion)."""
    tokens = tokens.copy()
    labels = np.full_like(tokens, -100)
    maskable = ~np.isin(tokens, list(special_ids))
    pick = (rng.rand(*tokens.shape) < mask_prob) & maskable
    labels[pick] = tokens[pick]
    roll = rng.rand(*tokens.shape)
    tokens[pick & (roll < 0.8)] = mask_id
    rand_pick = pick & (roll >= 0.8) & (roll < 0.9)
    tokens[rand_pick] = rng.randint(0, vocab_size, rand_pick.sum())
    return tokens, labels


class ErnieDataset:
    """Sentence-pair masked-LM dataset over a memmap token stream."""

    def __init__(self, data_prefix: str, *, num_samples: int,
                 seq_length: int = 512, vocab_size: int = 40000,
                 seed: int = 1234, cls_id: int = 1, sep_id: int = 2,
                 mask_id: int = 3, **_unused):
        self.tokens = np.load(data_prefix + "_ids.npy", mmap_mode="r")
        idx = np.load(data_prefix + "_idx.npz")
        self.doc_lens = idx["lens"].astype(np.int64)
        self.doc_starts = np.concatenate([[0], np.cumsum(self.doc_lens)])
        self.num_samples = int(num_samples)
        self.seq_length = int(seq_length)
        self.vocab_size = int(vocab_size)
        self.seed = int(seed)
        self.cls_id, self.sep_id, self.mask_id = cls_id, sep_id, mask_id

    def __len__(self) -> int:
        return self.num_samples

    def _segment(self, rng: np.random.RandomState, length: int) -> np.ndarray:
        doc = int(rng.randint(0, len(self.doc_lens)))
        start = int(self.doc_starts[doc])
        dl = int(self.doc_lens[doc])
        off = int(rng.randint(0, max(dl - length, 1)))
        seg = np.asarray(self.tokens[start + off: start + off + length],
                         np.int64)
        if len(seg) < length:  # short doc: pad by wrapping
            seg = np.pad(seg, (0, length - len(seg)), mode="wrap")
        return seg

    def __getitem__(self, i: int) -> dict:
        rng = np.random.RandomState(self.seed + int(i))
        s = self.seq_length
        half = (s - 3) // 2
        a = self._segment(rng, half)
        b = self._segment(rng, s - 3 - half)
        is_next = int(rng.rand() < 0.5)
        if not is_next:
            a, b = b, a  # "random" pair proxy: swapped order
        ids = np.concatenate([[self.cls_id], a, [self.sep_id], b,
                              [self.sep_id]]).astype(np.int64)
        token_type = np.concatenate([
            np.zeros(2 + len(a), np.int32), np.ones(len(b) + 1, np.int32)])
        masked, labels = apply_mlm_mask(
            ids, rng, vocab_size=self.vocab_size, mask_id=self.mask_id,
            special_ids=(self.cls_id, self.sep_id))
        return {
            "input_ids": masked.astype(np.int32),
            "token_type_ids": token_type,
            "attention_mask": np.ones(s, np.int32),
            "mlm_labels": labels.astype(np.int32),
            "next_sentence_labels": np.int32(is_next),
        }


class SyntheticErnieDataset:
    """Deterministic random masked-LM batches (zero data files)."""

    def __init__(self, *, num_samples: int = 1024, seq_length: int = 512,
                 vocab_size: int = 40000, seed: int = 1234, mask_id: int = 3,
                 **_unused):
        self.num_samples = int(num_samples)
        self.seq_length = int(seq_length)
        self.vocab_size = int(vocab_size)
        self.seed = int(seed)
        self.mask_id = mask_id

    def __len__(self) -> int:
        return self.num_samples

    def __getitem__(self, i: int) -> dict:
        rng = np.random.RandomState(self.seed + int(i))
        s = self.seq_length
        ids = rng.randint(4, self.vocab_size, size=s).astype(np.int64)
        masked, labels = apply_mlm_mask(ids, rng, vocab_size=self.vocab_size,
                                        mask_id=self.mask_id)
        return {
            "input_ids": masked.astype(np.int32),
            "token_type_ids": (np.arange(s) >= s // 2).astype(np.int32),
            "attention_mask": np.ones(s, np.int32),
            "mlm_labels": labels.astype(np.int32),
            "next_sentence_labels": np.int32(rng.rand() < 0.5),
        }
