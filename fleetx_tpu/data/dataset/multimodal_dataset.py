"""Imagen dataset: TSV + base64 images + precomputed T5 embeddings.

Reference: ``ppfleetx/data/dataset/multimodal_dataset.py:96-180`` — TSV
lines indexed by byte offset (l.124-141), images decoded from base64,
text features loaded from ``.npy`` (l.170-177; no text encoder runs
in-process). Same contract here, plus a synthetic variant so recipes run
with zero data files.
"""

from __future__ import annotations

import base64
import io
import os

import numpy as np

from fleetx_tpu.utils.log import logger


def _build_line_index(path: str) -> np.ndarray:
    """Byte offset of every line (reference l.124-141); cached as .idx.npy."""
    cache = path + ".idx.npy"
    if os.path.exists(cache) and os.path.getmtime(cache) >= os.path.getmtime(path):
        return np.load(cache)
    offsets = [0]
    with open(path, "rb") as f:
        for line in f:
            offsets.append(offsets[-1] + len(line))
    idx = np.asarray(offsets[:-1], np.int64)
    try:
        np.save(cache, idx, allow_pickle=False)
    except OSError:
        logger.warning("could not cache line index next to %s", path)
    return idx


class ImagenDataset:
    """TSV rows ``caption\\tbase64(image)``; T5 features memmapped from
    ``{embeds_prefix}_embeds.npy`` [N, T, D] + ``{embeds_prefix}_mask.npy``.

    Returns dict batches matching ``ImagenModule``: images NHWC in [-1, 1].
    """

    def __init__(self, tsv_path: str, *, embeds_prefix: str,
                 image_size: int = 64, lowres_size: int | None = None,
                 channels: int = 3, **_unused):
        self.tsv_path = tsv_path
        self.offsets = _build_line_index(tsv_path)
        self.image_size = int(image_size)
        self.lowres_size = lowres_size
        self.channels = channels
        self.text_embeds = np.load(embeds_prefix + "_embeds.npy",
                                   mmap_mode="r")
        self.text_mask = np.load(embeds_prefix + "_mask.npy", mmap_mode="r")
        assert len(self.text_embeds) >= len(self.offsets), \
            "fewer T5 embedding rows than TSV lines"

    def __len__(self) -> int:
        return len(self.offsets)

    def _decode_image(self, b64: str) -> np.ndarray:
        from PIL import Image

        img = Image.open(io.BytesIO(base64.b64decode(b64))).convert("RGB")
        img = img.resize((self.image_size, self.image_size), Image.BICUBIC)
        arr = np.asarray(img, np.float32) / 127.5 - 1.0
        return arr

    def __getitem__(self, i: int) -> dict:
        with open(self.tsv_path, "rb") as f:
            f.seek(int(self.offsets[i]))
            line = f.readline().decode("utf-8", errors="replace").rstrip("\n")
        _caption, b64 = line.split("\t", 1)
        image = self._decode_image(b64)
        out = {
            "images": image,
            "text_embeds": np.asarray(self.text_embeds[i], np.float32),
            "text_mask": np.asarray(self.text_mask[i], np.int32),
        }
        if self.lowres_size:
            from PIL import Image

            small = Image.fromarray(
                ((image + 1.0) * 127.5).astype(np.uint8)).resize(
                (self.lowres_size, self.lowres_size), Image.BICUBIC)
            out["lowres_images"] = (np.asarray(small, np.float32) / 127.5
                                    - 1.0)
        return out


class SyntheticImagenDataset:
    """Deterministic random images + text features (smoke/bench runs)."""

    def __init__(self, *, num_samples: int = 1024, image_size: int = 64,
                 lowres_size: int | None = None, text_len: int = 16,
                 text_embed_dim: int = 64, channels: int = 3, seed: int = 0,
                 **_unused):
        self.num_samples = int(num_samples)
        self.image_size = int(image_size)
        self.lowres_size = lowres_size
        self.text_len = text_len
        self.text_embed_dim = text_embed_dim
        self.channels = channels
        self.seed = seed

    def __len__(self) -> int:
        return self.num_samples

    def __getitem__(self, i: int) -> dict:
        rng = np.random.RandomState(self.seed + int(i))
        s = self.image_size
        out = {
            "images": rng.uniform(-1, 1, (s, s, self.channels)).astype(np.float32),
            "text_embeds": rng.randn(self.text_len,
                                     self.text_embed_dim).astype(np.float32),
            "text_mask": (np.arange(self.text_len)
                          < rng.randint(1, self.text_len + 1)).astype(np.int32),
        }
        if self.lowres_size:
            ls = int(self.lowres_size)
            out["lowres_images"] = rng.uniform(
                -1, 1, (ls, ls, self.channels)).astype(np.float32)
        return out
