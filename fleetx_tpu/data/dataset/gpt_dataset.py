"""Megatron-style memmap pretraining dataset.

Re-designs the reference ``GPTDataset`` (``ppfleetx/data/dataset/
gpt_dataset.py:32-197``) and its index-mapping construction
(``gpt_dataset.py:253-373`` + the C++ helper ``fast_index_map_helpers.cpp``):

- on-disk format is identical in spirit: ``{prefix}_ids.npy`` — one flat
  token stream — and ``{prefix}_idx.npz`` with per-document lengths;
- the doc/sample/shuffle index triple is built deterministically from
  (num_samples, seq_length, seed) and cached as ``.npy`` next to the data;
- index construction is **vectorised numpy** (cumsum + searchsorted) instead
  of a Python loop, so it stays O(tokens) with C-speed constants; a native
  C++ builder (``fleetx_tpu/data/native``) is used when built, and must
  produce byte-identical outputs;
- samples stitch across document boundaries exactly like the reference
  (``gpt_dataset.py:152-185``), returning
  ``[tokens, position_ids, labels, loss_mask]`` with loss masked at eos.
"""

from __future__ import annotations

import hashlib
import os
import time

import numpy as np

from fleetx_tpu.utils.log import logger


# --------------------------------------------------------------------------
# index construction (reference gpt_dataset.py:253-373 / C++ helper)
# --------------------------------------------------------------------------


def build_doc_idx(documents: np.ndarray, num_epochs: int,
                  rng: np.random.RandomState,
                  separate_last_epoch: bool) -> np.ndarray:
    """Epoch-replicated shuffled document order (reference ``_build_doc_idx``)."""
    if not separate_last_epoch or num_epochs == 1:
        doc_idx = np.tile(documents, num_epochs)
        rng.shuffle(doc_idx)
        return doc_idx.astype(np.int32)
    head = build_doc_idx(documents, num_epochs - 1, rng, False)
    tail = build_doc_idx(documents, 1, rng, False)
    return np.concatenate([head, tail]).astype(np.int32)


def build_sample_idx(sizes: np.ndarray, doc_idx: np.ndarray, seq_length: int,
                     num_samples: int) -> np.ndarray:
    """[num_samples+1, 2] (doc_idx position, token offset) per sample start.

    Vectorised equivalent of the reference C++ ``build_sample_idx``
    (``fast_index_map_helpers.cpp:92-190``): sample ``i`` starts at stream
    position ``i * seq_length`` of the doc_idx-ordered token stream (each
    sample consumes seq_length tokens; one extra token overlaps for labels).
    """
    lens = sizes[doc_idx].astype(np.int64)
    cum = np.cumsum(lens)
    total_tokens = int(cum[-1])
    max_samples = (total_tokens - 1) // seq_length
    num_samples = min(num_samples, max_samples)
    starts = np.arange(num_samples + 1, dtype=np.int64) * seq_length
    pos = np.searchsorted(cum, starts, side="right")
    prev_cum = np.where(pos > 0, cum[pos - 1], 0)
    offsets = starts - prev_cum
    out = np.empty((num_samples + 1, 2), np.int64)
    out[:, 0] = pos
    out[:, 1] = offsets
    return out


def build_shuffle_idx(num_samples: int, total_size: int,
                      rng: np.random.RandomState) -> np.ndarray:
    """Shuffle within [0, num_samples) and [num_samples, total) separately
    (reference ``_build_shuffle_idx``: keeps the last partial epoch's samples
    after the full epochs)."""
    dtype = np.int64 if total_size >= np.iinfo(np.int32).max - 1 else np.int32
    head = np.arange(num_samples, dtype=dtype)
    rng.shuffle(head)
    if total_size <= num_samples:
        return head
    tail = np.arange(num_samples, total_size, dtype=dtype)
    rng.shuffle(tail)
    return np.concatenate([head, tail])


def _num_epochs(tokens_per_epoch: int, seq_length: int, num_samples: int) -> int:
    epochs = 0
    total = 0
    while True:
        epochs += 1
        total += tokens_per_epoch
        if (total - 1) // seq_length >= num_samples:
            return epochs


def build_index_mappings(name: str, cache_dir: str, sizes: np.ndarray,
                         documents: np.ndarray, num_samples: int,
                         seq_length: int, seed: int):
    """Build (or load cached) doc/sample/shuffle index triple.

    Cached as ``{name}_{hash}_{doc,sample,shuffle}_idx.npy`` — the hash keys
    the inputs, replacing the reference's filename convention
    (``gpt_dataset.py:268-282``) with something collision-safe.
    """
    key = hashlib.md5(
        f"{name}-{len(documents)}-{num_samples}-{seq_length}-{seed}".encode()
    ).hexdigest()[:10]
    os.makedirs(cache_dir, exist_ok=True)
    paths = {
        kind: os.path.join(cache_dir, f"{name}_{key}_{kind}_idx.npy")
        for kind in ("doc", "sample", "shuffle")
    }
    if all(os.path.exists(p) for p in paths.values()):
        return tuple(np.load(paths[k], mmap_mode="r")
                     for k in ("doc", "sample", "shuffle"))

    # multi-host: only process 0 builds; others poll for the published files
    # (reference rank-0-builds + dist.barrier, gpt_dataset.py:284-373 — the
    # barrier becomes a filesystem wait on atomically-renamed outputs)
    try:
        import jax
        n_proc, proc_id = jax.process_count(), jax.process_index()
    except Exception:
        n_proc, proc_id = 1, 0
    if n_proc > 1 and proc_id != 0:
        deadline = time.time() + 600.0
        while not all(os.path.exists(p) for p in paths.values()):
            if time.time() > deadline:
                raise TimeoutError(
                    f"index mappings for {name} not published by process 0 "
                    f"within 600s under {cache_dir}")
            time.sleep(1.0)
        return tuple(np.load(paths[k], mmap_mode="r")
                     for k in ("doc", "sample", "shuffle"))

    rng = np.random.RandomState(seed)
    tokens_per_epoch = int(sizes[documents].sum())
    num_epochs = _num_epochs(tokens_per_epoch, seq_length, num_samples)
    # separate_last_epoch logic (reference gpt_dataset.py:284-302): don't let
    # the final partial epoch leak shuffled into the full epochs
    if num_epochs == 1:
        separate_last_epoch = False
    else:
        samples_wo_last = ((num_epochs - 1) * tokens_per_epoch - 1) // seq_length
        last_epoch_samples = num_samples - samples_wo_last
        samples_per_epoch = (tokens_per_epoch - 1) // seq_length
        separate_last_epoch = last_epoch_samples < int(0.8 * samples_per_epoch)

    doc_idx = build_doc_idx(documents, num_epochs, rng, separate_last_epoch)

    try:
        from fleetx_tpu.data.native import index_builder
        sample_idx = index_builder.build_sample_idx(
            sizes.astype(np.int32), doc_idx, seq_length, num_samples)
    except Exception as e:  # toolchain missing — numpy path is byte-identical
        logger.warning("native index builder unavailable (%s: %s); "
                       "using numpy fallback", type(e).__name__, e)
        sample_idx = build_sample_idx(sizes, doc_idx, seq_length, num_samples)

    if separate_last_epoch:
        num_samples_ = samples_wo_last
    else:
        num_samples_ = sample_idx.shape[0] - 1
    shuffle_idx = build_shuffle_idx(num_samples_, sample_idx.shape[0] - 1, rng)

    # atomic publish: write to a tmp name, then rename — concurrent same-host
    # processes and the multi-host pollers above never see partial files
    for kind, arr in (("doc", doc_idx), ("sample", sample_idx),
                      ("shuffle", shuffle_idx)):
        tmp = paths[kind][:-len(".npy")] + f".tmp{os.getpid()}.npy"
        np.save(tmp, arr, allow_pickle=False)
        os.replace(tmp, paths[kind])
    logger.info("built index mappings for %s: %d samples, %d epochs",
                name, sample_idx.shape[0] - 1, num_epochs)
    return doc_idx, sample_idx, shuffle_idx


# --------------------------------------------------------------------------
# dataset
# --------------------------------------------------------------------------


class GPTDataset:
    """Pretraining dataset over a memmapped token stream.

    ``data_prefix`` names ``{prefix}_ids.npy`` (flat token array) and
    ``{prefix}_idx.npz`` with key ``lens`` (per-doc lengths). Returns dict
    batches matching the model contract.
    """

    def __init__(self, data_prefix: str, *, name: str = "train",
                 num_samples: int, seq_length: int = 1024, seed: int = 1234,
                 eos_id: int = 50256, documents: np.ndarray | None = None,
                 cache_dir: str | None = None):
        self.tokens = np.load(data_prefix + "_ids.npy", mmap_mode="r")
        idx = np.load(data_prefix + "_idx.npz")
        self.doc_lens = idx["lens"].astype(np.int64)
        self.doc_starts = np.concatenate([[0], np.cumsum(self.doc_lens)])
        self.seq_length = int(seq_length)
        self.eos_id = int(eos_id)
        if documents is None:
            documents = np.arange(len(self.doc_lens), dtype=np.int32)
        cache_dir = cache_dir or os.path.dirname(os.path.abspath(data_prefix))
        self.doc_idx, self.sample_idx, self.shuffle_idx = build_index_mappings(
            name, cache_dir, self.doc_lens, documents, num_samples,
            self.seq_length, seed)

    def __len__(self) -> int:
        return self.shuffle_idx.shape[0]

    def _gather(self, idx: int) -> np.ndarray:
        """seq_length+1 contiguous stream tokens, stitched across docs
        (reference ``_construct_sample``/``__getitem__`` l.134-185)."""
        pos_f, off_f = self.sample_idx[idx]
        pos_l, off_l = self.sample_idx[idx + 1]
        parts = []
        need = self.seq_length + 1
        pos, off = int(pos_f), int(off_f)
        while need > 0:
            doc = int(self.doc_idx[pos])
            start = self.doc_starts[doc] + off
            take = min(need, int(self.doc_lens[doc]) - off)
            parts.append(self.tokens[start:start + take])
            need -= take
            pos += 1
            off = 0
        return np.concatenate(parts).astype(np.int64)

    def __getitem__(self, i: int) -> dict:
        sample = self._gather(int(self.shuffle_idx[i]))
        tokens = sample[:-1].astype(np.int32)
        labels = sample[1:].astype(np.int32)
        loss_mask = np.ones(self.seq_length, np.float32)
        loss_mask[tokens == self.eos_id] = 0.0  # reference gpt_dataset.py:145
        position_ids = np.arange(self.seq_length, dtype=np.int32)
        return {"tokens": tokens, "position_ids": position_ids,
                "labels": labels, "loss_mask": loss_mask}


def build_blending_indices(weights: np.ndarray,
                           num_samples: int) -> tuple[np.ndarray, np.ndarray]:
    """Greedy weighted assignment of samples to datasets (numpy counterpart
    of the native ``build_blending_indices``; reference
    ``fast_index_map_helpers.cpp:32-89``)."""
    weights = np.asarray(weights, np.float64)
    counts = np.zeros(len(weights), np.int64)
    ds_idx = np.empty(num_samples, np.int32)
    ds_sample_idx = np.empty(num_samples, np.int64)
    for i in range(num_samples):
        errs = weights * (i + 1) - counts
        best = int(np.argmax(errs))
        ds_idx[i] = best
        ds_sample_idx[i] = counts[best]
        counts[best] += 1
    return ds_idx, ds_sample_idx


class BlendedDataset:
    """Weighted mixture of datasets (reference multi-corpus blending via
    ``build_blending_indices``). ``datasets`` map-style; ``weights`` are
    normalised; sample ``i`` of the blend comes from
    ``datasets[dataset_index[i]][dataset_sample_index[i] % len]``."""

    def __init__(self, datasets: list, weights: list[float], num_samples: int):
        assert len(datasets) == len(weights) and datasets
        w = np.asarray(weights, np.float64)
        w = w / w.sum()
        self.datasets = datasets
        try:
            from fleetx_tpu.data.native import index_builder
            self.dataset_index, self.dataset_sample_index = \
                index_builder.build_blending_indices(w, num_samples)
        except Exception as e:
            logger.warning("native blending builder unavailable (%s); "
                           "using numpy fallback", e)
            self.dataset_index, self.dataset_sample_index = \
                build_blending_indices(w, num_samples)

    def __len__(self) -> int:
        return len(self.dataset_index)

    def __getitem__(self, i: int) -> dict:
        ds = self.datasets[int(self.dataset_index[i])]
        return ds[int(self.dataset_sample_index[i]) % len(ds)]


class SyntheticGPTDataset:
    """Deterministic random-token dataset for smoke runs and benchmarking —
    lets ``tools/train.py`` run with zero data files (the reference demands a
    downloaded 300M-token demo set before anything runs)."""

    def __init__(self, *, num_samples: int, seq_length: int = 1024,
                 vocab_size: int = 50304, seed: int = 1234, **_unused):
        self.num_samples = int(num_samples)
        self.seq_length = int(seq_length)
        self.vocab_size = int(vocab_size)
        self.seed = int(seed)

    def __len__(self) -> int:
        return self.num_samples

    def __getitem__(self, i: int) -> dict:
        rng = np.random.RandomState(self.seed + int(i))
        sample = rng.randint(0, self.vocab_size, size=self.seq_length + 1)
        return {
            "tokens": sample[:-1].astype(np.int32),
            "position_ids": np.arange(self.seq_length, dtype=np.int32),
            "labels": sample[1:].astype(np.int32),
            "loss_mask": np.ones(self.seq_length, np.float32),
        }


def write_corpus(prefix: str, docs: list[list[int]], dtype=np.uint16) -> None:
    """Write the ``_ids.npy`` / ``_idx.npz`` pair (preprocessing output
    format, reference ``preprocess_data.py``)."""
    flat = np.concatenate([np.asarray(d, dtype=dtype) for d in docs])
    np.save(prefix + "_ids.npy", flat, allow_pickle=False)
    np.savez(prefix + "_idx.npz", lens=np.array([len(d) for d in docs], np.int64))
