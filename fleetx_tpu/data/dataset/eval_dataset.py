"""Offline evaluation datasets: WikiText perplexity + LAMBADA accuracy.

Re-designs ``LM_Eval_Dataset`` / ``Lambada_Eval_Dataset``
(``ppfleetx/data/dataset/gpt_dataset.py:462-627``):

- ``LMEvalDataset``: overlapping evaluation windows over one token stream —
  window ``i`` re-feeds ``seq_len`` tokens of context but counts loss only
  on its last ``overlapping_eval`` new tokens (the first window counts all);
- ``LambadaEvalDataset``: each sample is (context, target last word);
  accuracy requires every target token to be the argmax prediction.

Both are tokenizer-agnostic (consume token ids); file loaders using our BPE
tokenizer sit alongside.
"""

from __future__ import annotations

import json

import numpy as np


class LMEvalDataset:
    """Sliding-window perplexity dataset (reference ``gpt_dataset.py:462-560``)."""

    def __init__(self, tokens, seq_length: int, *, overlapping_eval: int = 32,
                 pad_id: int = 0):
        self.tokens = np.asarray(tokens, np.int64)
        self.seq_length = int(seq_length)
        self.overlap = int(overlapping_eval) or self.seq_length
        self.pad_id = int(pad_id)
        n_tokens = len(self.tokens) - 1  # targets are shifted by one
        if n_tokens <= self.seq_length:
            self.num_samples = 1
        else:
            self.num_samples = 1 + int(
                np.ceil((n_tokens - self.seq_length) / self.overlap))

    def __len__(self) -> int:
        return self.num_samples

    def __getitem__(self, i: int) -> dict:
        S = self.seq_length
        n_targets = len(self.tokens) - 1
        # window i ends at target `end`; only its `new` trailing targets are
        # counted, so the windows tile all targets exactly once
        # (reference l.539-556)
        if i == 0:
            end = min(S, n_targets)
            new_tokens = end
        else:
            end = min(S + i * self.overlap, n_targets)
            new_tokens = end - (S + (i - 1) * self.overlap)
        start = max(end - S, 0)
        chunk = self.tokens[start:end + 1]
        tokens = np.full(S, self.pad_id, np.int32)
        labels = np.full(S, self.pad_id, np.int32)
        mask = np.zeros(S, np.float32)
        n = len(chunk) - 1
        tokens[:n] = chunk[:-1]
        labels[:n] = chunk[1:]
        mask[max(n - new_tokens, 0):n] = 1.0
        return {"tokens": tokens, "position_ids": np.arange(S, dtype=np.int32),
                "labels": labels, "loss_mask": mask}


class LambadaEvalDataset:
    """Last-word cloze accuracy dataset (reference ``gpt_dataset.py:562-627``)."""

    def __init__(self, pairs: list[tuple[list[int], list[int]]],
                 seq_length: int, *, pad_id: int = 0):
        self.pairs = pairs
        self.seq_length = int(seq_length)
        self.pad_id = int(pad_id)

    def __len__(self) -> int:
        return len(self.pairs)

    def __getitem__(self, i: int) -> dict:
        S = self.seq_length
        ctx, target = self.pairs[i]
        full = (list(ctx) + list(target))[-(S + 1):]
        tokens = np.full(S, self.pad_id, np.int32)
        labels = np.full(S, self.pad_id, np.int32)
        mask = np.zeros(S, np.float32)
        n = len(full) - 1
        tokens[:n] = full[:-1]
        labels[:n] = full[1:]
        mask[n - len(target):n] = 1.0  # judge only the target word's tokens
        return {"tokens": tokens, "position_ids": np.arange(S, dtype=np.int32),
                "labels": labels, "loss_mask": mask}


# ----------------------------------------------------------------- loaders


def lm_eval_from_text(path: str, tokenizer, seq_length: int,
                      overlapping_eval: int = 32) -> LMEvalDataset:
    """WikiText-style raw text file → PPL dataset (reference wikitext
    detokenization is upstream preprocessing; we evaluate the file as-is)."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    return LMEvalDataset(np.asarray(tokenizer.encode(text)), seq_length,
                         overlapping_eval=overlapping_eval,
                         pad_id=tokenizer.eos_token_id)


def lambada_from_jsonl(path: str, tokenizer, seq_length: int) -> LambadaEvalDataset:
    """LAMBADA jsonl ({"text": ...} lines): split off the last word as the
    cloze target (reference ``gpt_dataset.py:575-590``)."""
    pairs = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            text = json.loads(line)["text"]
            ctx, last = text.rsplit(" ", 1)
            pairs.append((tokenizer.encode(ctx), tokenizer.encode(" " + last)))
    return LambadaEvalDataset(pairs, seq_length, pad_id=tokenizer.eos_token_id)
