"""Minimal host-side data loader: sampler → collated numpy batches.

The reference leans on ``paddle.io.DataLoader`` worker processes; on TPU the
input pipeline is host-side numpy feeding a device-sharded ``device_put``
(``EagerEngine.shard_batch``), so a thin prefetching iterator suffices —
XLA overlaps the host work with device steps via async dispatch.
"""

from __future__ import annotations

import threading
import queue as queue_mod
from typing import Callable, Iterable, Optional

import numpy as np


class StopAwareQueue:
    """Bounded producer→consumer hand-off whose blocking ``put`` polls a
    consumer-owned stop flag.

    The shutdown contract shared by ``DataLoader.__iter__`` and
    ``prefetch.DevicePrefetcher``: a producer thread must never outlive a
    consumer that walked away mid-epoch, so ``put`` gives up within one
    poll interval of ``stop()`` instead of blocking on a full queue
    forever.
    """

    _POLL_S = 0.1

    def __init__(self, maxsize: int):
        self._q: queue_mod.Queue = queue_mod.Queue(maxsize=max(int(maxsize), 1))
        self._stop = threading.Event()

    def put(self, item) -> bool:
        """Producer-side put; False once the consumer has stopped."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=self._POLL_S)
                return True
            except queue_mod.Full:
                continue
        return False

    def get(self):
        """Consumer-side blocking get."""
        return self._q.get()

    def stop(self) -> None:
        """Consumer signals abandonment; pending puts unblock promptly."""
        self._stop.set()

    def drain(self) -> None:
        """Discard queued items (lets a producer blocked in put() exit)."""
        try:
            while True:
                self._q.get_nowait()
        except queue_mod.Empty:
            pass


def default_collate(samples: list) -> dict:
    """Stack dict-of-array samples into a batch (reference ``Stack`` collate,
    ``data/sampler/collate.py:27``)."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(np.stack(col) for col in zip(*samples))
    return np.stack(samples)


class DataLoader:
    """Iterates a batch sampler over a dataset, collating to numpy.

    ``prefetch`` > 0 runs assembly in a background thread so host batch
    construction overlaps device execution.
    """

    def __init__(self, dataset, batch_sampler: Iterable,
                 collate_fn: Optional[Callable] = None, prefetch: int = 2):
        self.dataset = dataset
        self.batch_sampler = batch_sampler
        self.collate_fn = collate_fn or default_collate
        self.prefetch = int(prefetch)

    def _make(self, indices) -> dict:
        return self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self):
        if self.prefetch <= 0:
            for indices in self.batch_sampler:
                yield self._make(indices)
            return
        q = StopAwareQueue(self.prefetch)
        sentinel = object()
        error: list[BaseException] = []

        def producer():
            try:
                for indices in self.batch_sampler:
                    if not q.put(self._make(indices)):
                        return  # consumer abandoned the iterator
            except BaseException as e:  # noqa: BLE001 — re-raised below
                # a raising _make used to hit a bare `finally: put(sentinel)`
                # and the epoch ended CLEANLY with the error swallowed;
                # carry it to the consumer instead
                error.append(e)
            q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True,
                             name="fleetx-dataloader")
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    if error:
                        raise error[0]
                    break
                yield item
        finally:
            q.stop()
            # deterministic shutdown: once close()/GC of this generator
            # returns, the producer has exited and will never touch the
            # batch_sampler again — a rollback can then safely rewind
            # sampler.consumed_samples without racing a live producer
            # (docs/resilience.md); stop-aware puts bound the join. A
            # timed-out join (dataset read hung on I/O) is logged loudly
            # because that guarantee then does NOT hold.
            t.join(timeout=5.0)
            if t.is_alive():
                from fleetx_tpu.utils.log import logger

                logger.error(
                    "dataloader producer did not exit within its join "
                    "timeout — batch_sampler may still be advanced by the "
                    "hung thread")

    def __len__(self) -> int:
        return len(self.batch_sampler)
