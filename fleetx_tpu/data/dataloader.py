"""Minimal host-side data loader: sampler → collated numpy batches.

The reference leans on ``paddle.io.DataLoader`` worker processes; on TPU the
input pipeline is host-side numpy feeding a device-sharded ``device_put``
(``EagerEngine.shard_batch``), so a thin prefetching iterator suffices —
XLA overlaps the host work with device steps via async dispatch.
"""

from __future__ import annotations

import threading
import queue as queue_mod
from typing import Callable, Iterable, Optional

import numpy as np


def default_collate(samples: list) -> dict:
    """Stack dict-of-array samples into a batch (reference ``Stack`` collate,
    ``data/sampler/collate.py:27``)."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(np.stack(col) for col in zip(*samples))
    return np.stack(samples)


class DataLoader:
    """Iterates a batch sampler over a dataset, collating to numpy.

    ``prefetch`` > 0 runs assembly in a background thread so host batch
    construction overlaps device execution.
    """

    def __init__(self, dataset, batch_sampler: Iterable,
                 collate_fn: Optional[Callable] = None, prefetch: int = 2):
        self.dataset = dataset
        self.batch_sampler = batch_sampler
        self.collate_fn = collate_fn or default_collate
        self.prefetch = int(prefetch)

    def _make(self, indices) -> dict:
        return self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self):
        if self.prefetch <= 0:
            for indices in self.batch_sampler:
                yield self._make(indices)
            return
        q: queue_mod.Queue = queue_mod.Queue(maxsize=self.prefetch)
        sentinel = object()

        def producer():
            try:
                for indices in self.batch_sampler:
                    q.put(self._make(indices))
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item

    def __len__(self) -> int:
        return len(self.batch_sampler)
