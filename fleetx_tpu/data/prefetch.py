"""Device-side input double buffering (docs/bandwidth_levers.md).

``EagerEngine.fit`` historically ran ``next(batch_iter)`` → ``shard_batch``
(a blocking per-leaf ``jax.device_put``) → ``train_step`` serially, so the
host-to-device copy of batch N sat on the step-N critical path.
``DevicePrefetcher`` moves it off: a background thread pulls host batches
and shards them onto the mesh a depth-bounded queue ahead, so the transfer
for batch N+1 overlaps the device executing step N. The consumer's wait in
``__next__`` is then pure input starvation — which is exactly what the
``data_stall`` derived metric should integrate — while the producer's
``device_put`` time is recorded under the separate ``shard_batch_async``
span so it never counts as consumer-blocked time.

The shutdown contract (stop-aware bounded puts, producer exceptions
re-raised consumer-side) is ``dataloader.StopAwareQueue`` — one
implementation shared with ``DataLoader.__iter__``.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterator, Optional

from fleetx_tpu.data.dataloader import StopAwareQueue

__all__ = ["DevicePrefetcher"]


class _ProducerError:
    """Marker carrying a producer-side exception to the consumer."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class DevicePrefetcher:
    """Iterator of device-sharded batches, produced ``depth`` ahead.

    ``shard_fn`` (typically ``EagerEngine.shard_batch``) runs on the
    producer thread — ``jax.device_put`` is thread-safe and the transfers
    it enqueues proceed while the main thread dispatches train steps.
    """

    _SENTINEL = object()

    def __init__(self, host_iter: Iterator, shard_fn: Callable[[Any], Any],
                 depth: int = 2, obs: Optional[Any] = None):
        self._queue = StopAwareQueue(depth)
        self._done = False
        self._thread = threading.Thread(
            target=self._produce, args=(host_iter, shard_fn, obs),
            daemon=True, name="fleetx-device-prefetch")
        self._thread.start()

    # ------------------------------------------------------------- producer
    def _produce(self, host_iter: Iterator, shard_fn: Callable,
                 obs: Optional[Any]) -> None:
        try:
            for batch in host_iter:
                # span name deliberately differs from the engine's
                # "shard_batch": this copy overlaps device compute, so it
                # must not feed the data-stall integral
                # (Observability.stall_seconds_total)
                if obs is not None and getattr(obs, "enabled", False):
                    with obs.timed_span("shard_batch_async"):
                        sharded = shard_fn(batch)
                else:
                    sharded = shard_fn(batch)
                if not self._queue.put(sharded):
                    return  # consumer closed the prefetcher
        except BaseException as e:  # noqa: BLE001 — re-raised consumer-side
            self._queue.put(_ProducerError(e))
            return
        self._queue.put(self._SENTINEL)

    # ------------------------------------------------------------- consumer
    def __iter__(self) -> "DevicePrefetcher":
        return self

    def __next__(self) -> Any:
        if self._done:
            raise StopIteration
        item = self._queue.get()
        if item is self._SENTINEL:
            self._done = True
            raise StopIteration
        if isinstance(item, _ProducerError):
            self._done = True
            raise item.exc
        return item

    def close(self) -> bool:
        """Release the producer thread (idempotent; safe mid-iteration).

        Returns True when the producer actually exited — False means the
        join timed out (e.g. ``shard_fn`` or the host iterator is hung on
        I/O) and the underlying host iterator is STILL EXECUTING on the
        producer thread: callers must not close() that generator (it would
        raise ``ValueError: generator already executing``) nor assume
        exclusive access to its sampler.
        """
        self._queue.stop()
        self._queue.drain()  # unblock a producer waiting in put()
        self._thread.join(timeout=5.0)
        return not self._thread.is_alive()
