"""Config-driven data builders (reference ``ppfleetx/data/__init__.py:25-73``).

The reference resolves dataset/sampler/collate classes with ``eval()`` over
config strings; here an explicit registry does the same without arbitrary
code execution.
"""

from __future__ import annotations

from fleetx_tpu.data.dataloader import DataLoader, default_collate
from fleetx_tpu.data.dataset.ernie_dataset import (
    ErnieDataset, SyntheticErnieDataset)
from fleetx_tpu.data.dataset.gpt_dataset import (
    BlendedDataset, GPTDataset, SyntheticGPTDataset, write_corpus)
from fleetx_tpu.data.dataset.multimodal_dataset import (
    ImagenDataset, SyntheticImagenDataset)
from fleetx_tpu.data.dataset.vision_dataset import (
    CIFAR10, GeneralClsDataset, ImageFolder, SyntheticVisionDataset)
from fleetx_tpu.data.sampler.batch_sampler import (
    DistributedBatchSampler, GPTBatchSampler)

DATASETS = {"GPTDataset": GPTDataset,
            "SyntheticGPTDataset": SyntheticGPTDataset,
            "BlendedDataset": BlendedDataset,
            "ErnieDataset": ErnieDataset,
            "SyntheticErnieDataset": SyntheticErnieDataset,
            "GeneralClsDataset": GeneralClsDataset,
            "ImageFolder": ImageFolder,
            "CIFAR10": CIFAR10,
            "SyntheticVisionDataset": SyntheticVisionDataset,
            "ImagenDataset": ImagenDataset,
            "SyntheticImagenDataset": SyntheticImagenDataset}
SAMPLERS = {"GPTBatchSampler": GPTBatchSampler,
            "DistributedBatchSampler": DistributedBatchSampler}

__all__ = ["DataLoader", "default_collate", "GPTDataset", "write_corpus",
           "DistributedBatchSampler", "GPTBatchSampler",
           "build_dataset", "build_dataloader"]


def build_dataset(cfg: dict, mode: str = "Train", **overrides):
    """Build a dataset from a config ``Data.{mode}.dataset`` section."""
    section = dict((cfg.get(mode) or cfg).get("dataset") or {})
    name = section.pop("name", "GPTDataset")
    cls = DATASETS.get(name)
    if cls is None:
        raise ValueError(f"unknown dataset {name!r}")
    section.pop("split", None)  # handled by callers building per-split sets
    if name == "BlendedDataset":
        # weighted mixture: build each child dataset recursively, passing
        # the same shape overrides (seq_length, vocab_size, ...)
        children = [build_dataset({"dataset": child}, mode="_child_",
                                  **overrides)
                    for child in (section.get("datasets") or [])]
        return BlendedDataset(children, section.get("weights"),
                              int(section.get("num_samples")))
    section.update(overrides)
    input_dir = section.pop("input_dir", None)
    if input_dir is not None and "data_prefix" not in section:
        section["data_prefix"] = input_dir
    seq_named = ("GPTDataset", "SyntheticGPTDataset", "ErnieDataset",
                 "SyntheticErnieDataset")
    if name in seq_named:
        section.setdefault("seq_length", section.pop("max_seq_len", 1024))
    else:  # vision/multimodal datasets have no sequence axis
        section.pop("seq_length", None)
        section.pop("max_seq_len", None)
    if name not in ("SyntheticGPTDataset", "ErnieDataset",
                    "SyntheticErnieDataset"):
        # vocab_size is plumbed from Model config (token range must match
        # the embedding table); other datasets carry their own vocabulary
        section.pop("vocab_size", None)
    return cls(**section)


def build_dataloader(cfg: dict, mode: str = "Train", *,
                     num_replicas: int = 1, rank: int = 0,
                     consumed_samples: int = 0, batch_size: int | None = None,
                     **dataset_overrides):
    """Dataset + sampler + loader from a config ``Data.{mode}`` section
    (reference ``build_dataloader``, ``data/__init__.py:42-73``).
    ``batch_size`` overrides the config value (per-host batch derived by the
    caller from global_batch_size / process count)."""
    section = dict(cfg.get(mode) or cfg)
    dataset = build_dataset(cfg, mode, **dataset_overrides)
    sampler_cfg = dict(section.get("sampler") or {})
    name = sampler_cfg.pop("name",
                           "GPTBatchSampler" if mode == "Train"
                           else "DistributedBatchSampler")
    loader_cfg = dict(section.get("loader") or {})
    if batch_size is None:
        batch_size = int(loader_cfg.get("batch_size",
                                        sampler_cfg.pop("batch_size", 1)))
    sampler_cfg.pop("batch_size", None)
    kwargs = dict(num_replicas=num_replicas, rank=rank,
                  drop_last=bool(sampler_cfg.pop("drop_last", True)))
    if name == "GPTBatchSampler":
        kwargs["consumed_samples"] = consumed_samples
    else:
        kwargs["shuffle"] = bool(sampler_cfg.pop("shuffle", False))
    # forward remaining sampler keys (seed, ...) so nothing is swallowed;
    # unknown keys fail fast in the sampler constructor
    kwargs.update(sampler_cfg)
    sampler = SAMPLERS[name](len(dataset), batch_size, **kwargs)
    return DataLoader(dataset, sampler,
                      prefetch=int(loader_cfg.get("prefetch", 2)))
