"""Sharded checkpoint save/load/resume.

Re-designs the reference checkpoint path (``ppfleetx/core/engine/
eager_engine.py:581-660``). The reference writes per-(mp, sharding, pp)-rank
directories plus a meta file with epoch/step/rng; restore must re-assemble the
same topology. Here checkpoints are *topology-free*: Orbax records each array
with its global shape and the restore call re-shards onto whatever mesh the
new run uses — resharding across different dp/tp/fsdp degrees is free.

Saved payload per step: the full TrainState (params, optimizer state, step,
dropout rng) + a JSON meta dict (consumed_samples, epoch, host rng state) so
a resumed run continues the loss curve exactly.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Optional

import time

import jax

from fleetx_tpu.observability.metrics import get_registry
from fleetx_tpu.observability.trace import span
from fleetx_tpu.utils.log import logger

try:
    import orbax.checkpoint as ocp
except ImportError:  # pragma: no cover
    ocp = None

_META_NAME = "fleetx_meta.json"
_checkpointer = None
_pending: list[tuple[str, dict]] = []


def _get_checkpointer():
    """One shared StandardCheckpointer (its async machinery owns threads)."""
    global _checkpointer
    assert ocp is not None, "orbax-checkpoint is required for checkpointing"
    if _checkpointer is None:
        _checkpointer = ocp.StandardCheckpointer()
    return _checkpointer


def _step_dir(directory: str, step: int) -> str:
    return os.path.join(directory, f"step_{step}")


def _tree_bytes(state: Any) -> int:
    """Payload size of a pytree (telemetry: HBM/disk traffic per save)."""
    total = 0
    for leaf in jax.tree.leaves(state):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None:
            size = getattr(leaf, "size", None)
            dtype = getattr(leaf, "dtype", None)
            nbytes = (size * dtype.itemsize) if size and dtype else 0
        total += int(nbytes)
    return total


def save_checkpoint(directory: str, step: int, state: Any,
                    meta: Optional[dict] = None,
                    async_save: bool = False) -> str:
    """Write a sharded checkpoint for ``step`` under ``directory``.

    A step dir without its meta file is a half-written save (e.g. a
    preemption between the state write and the meta write); it is removed
    and overwritten rather than left to block every later save at this step.

    ``async_save``: return as soon as device arrays are snapshotted — disk
    I/O overlaps subsequent training steps. The meta file (the completion
    marker) is written by ``finalize_async_saves``, which callers invoke
    before the next save and at shutdown; an unfinalized save is simply a
    half-written checkpoint the next run cleans up.
    """
    finalize_async_saves()  # at most one outstanding async save
    path = os.path.abspath(_step_dir(directory, step))
    if os.path.isdir(path) and not os.path.exists(os.path.join(path, _META_NAME)):
        logger.info("removing half-written checkpoint: %s", path)
        shutil.rmtree(path)
    ckptr = _get_checkpointer()
    reg = get_registry()
    t0 = time.perf_counter()
    with span("checkpoint_write", step=int(step)):
        ckptr.save(os.path.join(path, "state"), state, force=True)
        full_meta = dict(meta or {}, step=int(step))
        if async_save:
            _pending.append((path, full_meta))
            logger.info("async checkpoint started: %s", path)
        else:
            ckptr.wait_until_finished()
            _write_meta(path, full_meta)
            logger.info("saved checkpoint: %s", path)
    # duration/bytes telemetry: async saves report the (short) snapshot
    # window here; the drain shows up under ckpt_finalize
    nbytes = _tree_bytes(state)
    reg.histogram("ckpt_save").record(time.perf_counter() - t0)
    reg.counter("ckpt_saves_total").inc()
    reg.gauge("ckpt_bytes").set(nbytes)
    reg.counter("ckpt_bytes_total").inc(nbytes)
    return path


def _write_meta(path: str, meta: dict) -> None:
    if jax.process_index() == 0:
        with open(os.path.join(path, _META_NAME), "w") as f:
            json.dump(meta, f)


def finalize_async_saves() -> None:
    """Block until outstanding async saves are durable and mark them complete."""
    if not _pending:
        return
    with span("ckpt_finalize"), get_registry().timer("ckpt_finalize"):
        _get_checkpointer().wait_until_finished()
        while _pending:
            path, meta = _pending.pop(0)
            _write_meta(path, meta)
            logger.info("async checkpoint finalized: %s", path)


def latest_step(directory: str) -> Optional[int]:
    """Highest completed step under ``directory`` (None if none)."""
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        if name.startswith("step_"):
            full = os.path.join(directory, name, _META_NAME)
            if os.path.exists(full):
                try:
                    steps.append(int(name[len("step_"):]))
                except ValueError:
                    continue
    return max(steps) if steps else None


def peek_meta(directory: str) -> Optional[dict]:
    """Read the latest checkpoint's meta dict without touching array data —
    used by the CLI to seed the sampler's ``consumed_samples`` before the
    engine restores the full state."""
    step = latest_step(directory)
    if step is None:
        return None
    with open(os.path.join(_step_dir(directory, step), _META_NAME)) as f:
        return json.load(f)


def load_params(directory: str, step: Optional[int] = None) -> Any:
    """Restore only the params subtree of a saved TrainState.

    Eval/generation tools have no optimizer, so they can't construct the
    full abstract TrainState; instead the checkpoint's own metadata supplies
    shapes/dtypes for a structure-faithful restore, and ``params`` is
    extracted from the result.
    """
    step = step if step is not None else latest_step(directory)
    assert step is not None, f"no checkpoint found under {directory}"
    path = os.path.join(os.path.abspath(_step_dir(directory, step)), "state")
    ckptr = _get_checkpointer()
    md = ckptr.metadata(path)
    tree = getattr(md, "item_metadata", md)
    abstract = jax.tree.map(
        lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype), tree,
        is_leaf=lambda m: hasattr(m, "shape") and hasattr(m, "dtype"))
    state = ckptr.restore(path, abstract)
    logger.info("restored params from %s (step %d)", path, step)
    return state["params"]


def load_checkpoint(directory: str, step: int, abstract_state: Any,
                    adapt_layout: bool = False) -> tuple[Any, dict]:
    """Restore a checkpoint, re-sharding to ``abstract_state``'s shardings.

    ``abstract_state`` is a pytree of ``jax.ShapeDtypeStruct`` leaves carrying
    ``sharding`` attributes (the engine builds it from its mesh) — Orbax loads
    each shard directly onto its destination devices.

    ``adapt_layout``: when a leaf's stored shape differs from the requested
    one only by a reshape of the leading (stage/repeat/layer) dims — the
    pipeline layouts ``[L] / [S, L/S] / [V, S, L/(V*S)]`` — restore with the
    stored shape and reshape. The reference cannot restore across
    topologies at all (per-rank dirs must match, ``eager_engine.py:617-660``).
    """
    path = os.path.abspath(_step_dir(directory, step))
    ckptr = _get_checkpointer()
    request = abstract_state
    reshaped: list[str] = []
    if adapt_layout:
        import re

        def norm(kp) -> str:
            # attribute vs dict-key paths must compare equal
            # (".params['gpt']" == "['params']['gpt']")
            return re.sub(r"\W+", "/", jax.tree_util.keystr(kp)).strip("/")

        md = ckptr.metadata(os.path.join(path, "state"))
        stored = getattr(md, "item_metadata", md)
        stored_leaves = {}

        def record(kp, m):
            if hasattr(m, "shape"):
                stored_leaves[norm(kp)] = tuple(m.shape)
            return m

        jax.tree_util.tree_map_with_path(
            record, stored,
            is_leaf=lambda m: hasattr(m, "shape") and hasattr(m, "dtype"))

        def adapt(kp, want):
            key = norm(kp)
            have = stored_leaves.get(key)
            if have is None or tuple(want.shape) == have:
                return want
            # compatible iff both flatten to the same total with identical
            # trailing (feature) dims — i.e. only the stage split differs
            import numpy as np
            if int(np.prod(have)) == int(np.prod(want.shape)):
                reshaped.append(key)
                sharding = None
                if getattr(want, "sharding", None) is not None:
                    # restore replicated on the same mesh; the engine
                    # re-places the adapted state onto its shardings
                    from jax.sharding import NamedSharding, PartitionSpec
                    sharding = NamedSharding(want.sharding.mesh,
                                             PartitionSpec())
                return jax.ShapeDtypeStruct(have, want.dtype,
                                            sharding=sharding)
            return want

        request = jax.tree_util.tree_map_with_path(adapt, abstract_state)

    reg = get_registry()
    t0 = time.perf_counter()
    with span("checkpoint_restore", step=int(step)):
        state = ckptr.restore(os.path.join(path, "state"), request)
    reg.histogram("ckpt_restore").record(time.perf_counter() - t0)
    reg.counter("ckpt_restores_total").inc()
    reg.gauge("ckpt_bytes").set(_tree_bytes(state))
    if reshaped:
        logger.info("adapting pipeline layout of %d leaves on restore",
                    len(reshaped))
        state = jax.tree.map(
            lambda got, want: jnp_reshape_to(got, want.shape)
            if got.shape != want.shape else got,
            state, abstract_state)
    with open(os.path.join(path, _META_NAME)) as f:
        meta = json.load(f)
    logger.info("restored checkpoint: %s (step %d)", path, meta.get("step", step))
    return state, meta


def jnp_reshape_to(arr: Any, shape: tuple) -> Any:
    """Reshape helper kept importable for tree_map closures."""
    import jax.numpy as jnp

    return jnp.reshape(arr, shape)
