"""Sharded checkpoint save/load/resume.

Re-designs the reference checkpoint path (``ppfleetx/core/engine/
eager_engine.py:581-660``). The reference writes per-(mp, sharding, pp)-rank
directories plus a meta file with epoch/step/rng; restore must re-assemble the
same topology. Here checkpoints are *topology-free*: Orbax records each array
with its global shape and the restore call re-shards onto whatever mesh the
new run uses — resharding across different dp/tp/fsdp degrees is free.

Saved payload per step: the full TrainState (params, optimizer state, step,
dropout rng) + a JSON meta dict (consumed_samples, epoch, host rng state) so
a resumed run continues the loss curve exactly.

Multi-host commit protocol (docs/resilience.md): a checkpoint is complete
only when EVERY process's shard writes are durable, so ``save_checkpoint``
runs a two-phase commit — all ranks finish their state writes, a gang
barrier (``resilience/coordination.py``) proves it, and only then is the
meta completion marker published. Two storage modes share the protocol:

- shared storage (the TPU-pod default): Orbax global arrays, rank 0 alone
  writes the meta/gc/rmtree side (the existing gating);
- per-rank directories (``set_per_rank_mode``; host-local SSDs and the
  multi-process CPU-mesh test gang, where XLA has no cross-process
  computations and Orbax's multihost sync therefore cannot run): each rank
  owns its directory via a host-local npz codec and writes its own meta —
  still only after the gang barrier, so no rank's directory can claim a
  step its peers never finished.

Restore dispatches on the on-disk layout, so either mode's checkpoints
load anywhere.

Integrity (docs/resilience.md "Integrity"): every save publishes a
``fleetx_integrity.json`` manifest (per-file crc32 digests of the payload,
plus per-leaf digests where the full state is host-resident at save) next
to the meta marker. Restore re-digests before trusting a byte and raises
:class:`CheckpointIntegrityError` on any mismatch — the engine then falls
back to the newest checkpoint that verifies. In per-rank mode the save
READ-BACK verifies its own npz against the in-memory digests, and on
gangs that outcome is each rank's vote in the ``ckpt_commit`` agreement:
one corrupt shard aborts the commit on all ranks.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Optional

import time

import jax
import numpy as np

from fleetx_tpu.observability.metrics import get_registry
from fleetx_tpu.observability.trace import span
from fleetx_tpu.resilience import coordination
from fleetx_tpu.resilience import faults as faults_mod
from fleetx_tpu.resilience import integrity
from fleetx_tpu.resilience.integrity import (CheckpointIntegrityError,
                                             WriteVerifyError)
from fleetx_tpu.resilience.policy import call_with_retry
from fleetx_tpu.utils.log import logger

try:
    import orbax.checkpoint as ocp
except ImportError:  # pragma: no cover
    ocp = None

_META_NAME = "fleetx_meta.json"
#: host-local codec marker: a step dir carrying this file was written in
#: per-rank mode and restores through the npz path on any topology
_LOCAL_STATE = "state.npz"
_checkpointer = None
_pending: list[tuple] = []
_per_rank = False


_gang_commit = True

#: integrity manifests + restore verification (engine-scoped global like
#: the fault plan; default ON — persisted state is never trusted blindly)
_verify = True

#: newest step per directory with verified evidence IN THIS PROCESS (a
#: save whose read-back passed, or a restore whose digests matched) —
#: retention GC never prunes it, so a fall-back target always survives
_last_verified: dict[str, int] = {}


def set_verify_mode(on: bool) -> None:
    """Enable/disable integrity manifests and digest verification
    (``Resilience.integrity.verify_checkpoints``; engine-scoped global,
    newest engine wins — same convention as the fault plan)."""
    global _verify
    _verify = bool(on)


def verify_mode() -> bool:
    """True when manifests are written and restores verify digests."""
    return _verify


def _record_verified(directory: str, step: int) -> None:
    """Note ``step`` as this process's newest verified step under
    ``directory`` (monotonic; consumed by ``gc_checkpoints``)."""
    key = os.path.abspath(directory)
    if step >= _last_verified.get(key, -1):
        _last_verified[key] = int(step)


def _record_refused(directory: str, step: int) -> None:
    """Demote a step that FAILED verification: a save-time "verified"
    record is stale once the bytes rot on disk, and gc trusting it would
    protect the corrupt step while pruning the good fall-back."""
    key = os.path.abspath(directory)
    if _last_verified.get(key) == int(step):
        del _last_verified[key]


def _record_refused_path(path: str) -> None:
    """``_record_refused`` keyed by a ``step_<N>`` directory path."""
    name = os.path.basename(os.path.abspath(path))
    if name.startswith("step_"):
        try:
            _record_refused(os.path.dirname(os.path.abspath(path)),
                            int(name[len("step_"):]))
        except ValueError:
            pass


def set_gang_commit(on: bool) -> None:
    """Whether checkpoint completion requires the gang agreement (the
    two-phase commit barrier / abandon vote). Engine-scoped global like
    the fault plan; the engine DISABLES it when the resilience runtime is
    off: without the runtime's voted loop exits, ranks can leave ``fit``
    at different times, and an unmatched barrier would wedge a healthy
    rank's save for the full agreement deadline."""
    global _gang_commit
    _gang_commit = bool(on)


def set_per_rank_mode(on: bool) -> None:
    """Select the per-rank-directory storage mode (engine-scoped global,
    newest engine wins — same convention as the fault plan).

    In this mode each process owns its checkpoint directory outright: the
    state payload is a host-local npz snapshot (Orbax's multihost
    machinery assumes one shared directory and hardcodes process 0 as the
    numpy writer) and every rank publishes its own meta. The gang barrier
    in ``save_checkpoint`` still gates completion on ALL ranks' writes.
    """
    global _per_rank
    _per_rank = bool(on)


def per_rank_mode() -> bool:
    """True when checkpoints are per-rank-directory host-local snapshots."""
    return _per_rank


def _is_meta_writer() -> bool:
    """Whether THIS process publishes meta files / prunes directories:
    rank 0 on shared storage, every rank for its own per-rank directory."""
    return _per_rank or jax.process_index() == 0


def _get_checkpointer():
    """One shared StandardCheckpointer (its async machinery owns threads)."""
    global _checkpointer
    assert ocp is not None, "orbax-checkpoint is required for checkpointing"
    if _checkpointer is None:
        _checkpointer = ocp.StandardCheckpointer()
    return _checkpointer


def _step_dir(directory: str, step: int) -> str:
    return os.path.join(directory, f"step_{step}")


def _tree_bytes(state: Any) -> int:
    """Payload size of a pytree (telemetry: HBM/disk traffic per save)."""
    total = 0
    for leaf in jax.tree.leaves(state):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None:
            size = getattr(leaf, "size", None)
            dtype = getattr(leaf, "dtype", None)
            nbytes = (size * dtype.itemsize) if size and dtype else 0
        total += int(nbytes)
    return total


#: the tmp+fsync+os.replace dance is ONE implementation, owned by the
#: integrity module (its manifest writes share it with the state/meta
#: writers here)
_atomic_write = integrity.atomic_write


def _save_state_local(path: str, state: Any,
                      host_leaves: Optional[list] = None) -> None:
    """Per-rank codec: the whole state pytree as ONE atomic npz snapshot.

    Leaves are host-fetched and written in flatten order; the treedef
    lives in code (the engine rebuilds the same TrainState), mirroring the
    unboxed-tree stance of the Orbax path. Temp-file + ``os.replace`` so a
    mid-write crash never leaves a torn payload behind the meta marker.
    ``host_leaves`` reuses the host copies the caller already fetched for
    digesting — one HBM→host transfer per save, not two.

    Extension dtypes (``ml_dtypes`` bfloat16 & friends) don't survive the
    npy format — they come back as raw void (``|V2``) — so the true dtype
    names ride along in a ``__dtypes__`` entry and restore re-views the
    raw bytes.
    """
    os.makedirs(path, exist_ok=True)
    target = os.path.join(path, _LOCAL_STATE)
    if host_leaves is None:
        host_leaves = [np.asarray(leaf)
                       for leaf in jax.tree.leaves(jax.device_get(state))]
    arrays = {f"leaf_{i}": leaf for i, leaf in enumerate(host_leaves)}
    arrays["__dtypes__"] = np.array(
        [str(arrays[f"leaf_{i}"].dtype) for i in range(len(host_leaves))])
    _atomic_write(target, lambda f: np.savez(f, **arrays), mode="wb")


def _restore_state_local(path: str, abstract_state: Any,
                         manifest: Optional[dict] = None) -> Any:
    """Load an npz snapshot into ``abstract_state``'s structure.

    Leading-dim reshapes (the pipeline-layout adaptation of the Orbax
    path) are applied whenever a stored leaf's element count matches the
    requested shape; a genuine mismatch fails loudly with the leaf index.

    With a ``manifest`` carrying per-leaf digests, every leaf's RAW bytes
    (before the extension-dtype re-view and any requested cast) are
    verified against the digests computed at save —
    :class:`CheckpointIntegrityError` on mismatch, never a silent
    restore of corrupt values.
    """
    leaves, treedef = jax.tree.flatten(abstract_state)
    leaf_digests = (manifest or {}).get("leaves") or []
    got = []
    with np.load(os.path.join(path, _LOCAL_STATE)) as data:
        dtypes = [str(d) for d in data["__dtypes__"]] \
            if "__dtypes__" in data else None
        for i, want in enumerate(leaves):
            arr = data[f"leaf_{i}"]
            if i < len(leaf_digests):
                digest = leaf_digests[i]
                host = np.ascontiguousarray(arr)
                if int(host.nbytes) != int(digest["nbytes"]) or \
                        integrity.digest_bytes(host.tobytes()) != \
                        int(digest["crc32"]):
                    _record_refused_path(path)
                    raise CheckpointIntegrityError(
                        f"checkpoint leaf {i} of {path} does not match "
                        f"its manifest digest — refusing to restore "
                        f"corrupt state")
            if dtypes is not None and str(arr.dtype) != dtypes[i]:
                # extension dtype flattened to raw void by the npy format
                # (ml_dtypes bfloat16 → |V2): re-view the original dtype
                arr = arr.view(np.dtype(dtypes[i]))
            shape = tuple(getattr(want, "shape", arr.shape))
            if tuple(arr.shape) != shape:
                if arr.size != int(np.prod(shape)):
                    raise ValueError(
                        f"checkpoint leaf {i} has shape {arr.shape}, "
                        f"requested {shape} — incompatible state structure")
                arr = arr.reshape(shape)
            want_dtype = getattr(want, "dtype", None)
            if want_dtype is not None and arr.dtype != want_dtype:
                # restore into the REQUESTED dtype like the Orbax path —
                # resuming under a changed precision config must not
                # silently keep training at the stored dtype
                arr = arr.astype(want_dtype)
            got.append(arr)
    return jax.tree.unflatten(treedef, got)


def save_checkpoint(directory: str, step: int, state: Any,
                    meta: Optional[dict] = None,
                    async_save: bool = False) -> str:
    """Write a sharded checkpoint for ``step`` under ``directory``.

    A step dir without its meta file is a half-written save (e.g. a
    preemption between the state write and the meta write); it is removed
    and overwritten rather than left to block every later save at this step.

    Two-phase commit on multi-process gangs: after the state write, a gang
    barrier proves EVERY rank's shards are durable before any meta marker
    is published — without it, rank 0 could mark a step complete that a
    slow peer never finished, and the next resume would restore a
    half-existent checkpoint. Single-process runs pay nothing (the local
    coordinator's barrier is a no-op).

    ``async_save``: return as soon as device arrays are snapshotted — disk
    I/O overlaps subsequent training steps. The meta file (the completion
    marker) is written by ``finalize_async_saves``, which callers invoke
    before the next save and at shutdown; an unfinalized save is simply a
    half-written checkpoint the next run cleans up. In per-rank mode the
    npz snapshot is synchronous and cheap, so async degrades to sync.

    Integrity: the manifest (per-file digests + per-leaf digests where
    the full state is host-resident at save) is published between the
    commit agreement and the meta marker, so a manifest always describes
    durable bytes. The per-rank codec additionally READ-BACK verifies its
    just-written npz against the in-memory digests — a torn write retries
    under the policy, a sticky one (dying disk, ``corrupt_ckpt_at``
    drill) becomes this rank's FAILED ``ckpt_commit`` vote and aborts the
    commit on every rank (no meta anywhere), or raises loudly off-gang.
    """
    finalize_async_saves()  # at most one outstanding async save
    path = os.path.abspath(_step_dir(directory, step))
    if _is_meta_writer() and os.path.isdir(path) and \
            _read_meta(path) is None:
        # covers both the missing-meta (crash between state and meta
        # writes) and corrupt-meta (crash mid-json.dump before the write
        # became atomic) shapes of a half-written save; meta-writer gated
        # like _write_meta/gc_checkpoints — N hosts racing rmtree on
        # shared storage crash each other with ENOENT/ENOTEMPTY
        logger.info("removing half-written checkpoint: %s", path)
        shutil.rmtree(path)
    if _per_rank and async_save:
        async_save = False
    reg = get_registry()
    t0 = time.perf_counter()
    retries = reg.counter("ckpt_retries_total")
    # per-leaf digests need the full state host-resident at save time:
    # always true for the per-rank codec (whose host fetch is shared with
    # the digest pass — ONE HBM→host transfer per save) and for
    # single-process sync Orbax saves; a multi-process shared-Orbax host
    # holds only its local shards (a device_get would gather peers'
    # shards over the fabric) and an async save must not block on the
    # fetch — those manifests are files-only
    leaf_digests = None
    host_leaves = None
    if _verify and (_per_rank or
                    (not async_save and jax.process_count() == 1)):
        host_leaves = [np.asarray(leaf)
                       for leaf in jax.tree.leaves(jax.device_get(state))]
        leaf_digests = [integrity.digest_array(leaf)
                        for leaf in host_leaves]

    def _write_state():
        # injection point first so an injected transient failure exercises
        # the same retry path a real I/O blip would
        faults_mod.fire("ckpt_write")
        if _per_rank:
            _save_state_local(path, state, host_leaves=host_leaves)
        else:
            ckptr = _get_checkpointer()
            ckptr.save(os.path.join(path, "state"), state, force=True)
            if not async_save:
                # orbax commits in the background even for "sync" callers:
                # the real disk error surfaces HERE, so the drain must live
                # inside the retried fn — a failure re-dispatches the whole
                # save (force=True overwrites the partial attempt)
                ckptr.wait_until_finished()
        # corruption injection AFTER the write, BEFORE the read-back: the
        # drill is a byte rotting between the write and its verification
        faults_mod.fire_path("ckpt_written", path, int(step))
        if _per_rank and leaf_digests is not None:
            bad = integrity.verify_npz_leaves(path, leaf_digests)
            reg.counter("ckpt_verify_total").inc()
            if bad:
                reg.counter("ckpt_verify_failed").inc()
                raise WriteVerifyError(
                    f"read-back verification of {path} failed: leaves "
                    f"{bad} differ from the digests computed at save")

    verify_failed = False
    with span("checkpoint_write", step=int(step)):
        try:
            call_with_retry(_write_state, desc="checkpoint state write",
                            counter=retries)
        except WriteVerifyError:
            # sticky read-back failure (retries exhausted): off-gang (or
            # with one process, where the commit agreement is a no-op)
            # this is a loud refusal; on a real gang the outcome becomes
            # this rank's vote so the commit aborts EVERYWHERE, never
            # half-publishes
            if not _gang_commit or \
                    coordination.get_coordinator().world == 1:
                raise
            verify_failed = True
        full_meta = dict(meta or {}, step=int(step))
        if async_save:
            _pending.append((path, full_meta, leaf_digests))
            logger.info("async checkpoint started: %s", path)
        else:
            # phase boundary: every rank's state is durable AND verified
            # before ANY rank publishes a completion marker; one corrupt
            # shard aborts the commit on all ranks
            gang_failed = verify_failed
            if _gang_commit:
                gang_failed = coordination.get_coordinator().any_flag(
                    "ckpt_commit", verify_failed)
            if gang_failed:
                reg.counter("ckpt_commit_aborts").inc()
                logger.error(
                    "checkpoint commit ABORTED for step %d (%s) — no "
                    "completion marker published on any rank; training "
                    "continues and the next periodic save retries",
                    int(step), "local shard failed read-back verification"
                    if verify_failed else "a peer rank's shard failed "
                    "verification")
                if _is_meta_writer():
                    shutil.rmtree(path, ignore_errors=True)
            else:
                if _verify and _is_meta_writer():
                    # manifest between the commit agreement and the meta
                    # marker: it must describe durable bytes, and a dir
                    # with a manifest but no meta is still half-written
                    integrity.write_manifest(path, leaves=leaf_digests)
                call_with_retry(lambda: _write_meta(path, full_meta),
                                desc="checkpoint meta write",
                                counter=retries)
                _record_verified(directory, int(step))
                logger.info("saved checkpoint: %s", path)
    # duration/bytes telemetry: async saves report the (short) snapshot
    # window here; the drain shows up under ckpt_finalize
    nbytes = _tree_bytes(state)
    reg.histogram("ckpt_save").record(time.perf_counter() - t0)
    reg.counter("ckpt_saves_total").inc()
    reg.gauge("ckpt_bytes").set(nbytes)
    reg.counter("ckpt_bytes_total").inc(nbytes)
    return path


def _write_meta(path: str, meta: dict) -> None:
    """Atomically publish the completion marker: temp file + ``os.replace``.

    The meta file is what ``latest_step`` counts as "this checkpoint is
    complete", so it must appear all-or-nothing — a crash mid-``json.dump``
    into the final name would leave a truncated marker that a resume
    counts as a complete checkpoint and then dies parsing.
    """
    if _is_meta_writer():
        _atomic_write(os.path.join(path, _META_NAME),
                      lambda f: json.dump(meta, f))


def _read_meta(path: str) -> Optional[dict]:
    """The step dir's meta dict, or None when absent/corrupt (with a
    warning for the corrupt case — it means a pre-atomic-write crash or
    storage damage, and the dir must not count as a complete checkpoint).

    Transient READ failures are retried under the process retry policy and
    only classified as "incomplete" once exhausted: an I/O blip on an
    intact meta must not make the resume path skip (or ``save_checkpoint``
    delete) a perfectly good checkpoint.
    """
    target = os.path.join(path, _META_NAME)
    if not os.path.exists(target):
        return None

    def _load() -> str:
        with open(target) as f:
            return f.read()

    try:
        raw = call_with_retry(_load, desc="checkpoint meta read")
    except OSError as e:
        logger.warning("unreadable checkpoint meta %s (%s) — treating %s "
                       "as incomplete", target, e, path)
        return None
    try:
        meta = json.loads(raw)
    except (json.JSONDecodeError, UnicodeDecodeError, ValueError) as e:
        logger.warning("corrupt checkpoint meta %s (%s) — treating %s as "
                       "incomplete", target, e, path)
        return None
    if not isinstance(meta, dict):
        logger.warning("checkpoint meta %s is not a dict — treating %s as "
                       "incomplete", target, path)
        return None
    return meta


def finalize_async_saves() -> None:
    """Block until outstanding async saves are durable and mark them complete.

    A sticky background-commit failure (orbax re-raises the stored error
    from ``wait_until_finished``; the device snapshot is gone, so the save
    cannot be re-dispatched) ABANDONS the pending save instead of killing
    training: the training state is intact, the half-written dir is
    removed immediately (periodic saves never revisit that step, so
    nothing else would reclaim the partial payload), and the loss is
    recorded loudly (``ckpt_failed_total`` + an error log) so a persistent
    storage problem is visible, not masked.

    On a gang the abandon decision is itself COLLECTIVE: every rank votes
    its local commit outcome into the ``ckpt_commit`` agreement (the
    async form of the two-phase barrier), and ANY failure abandons the
    save on ALL ranks — no rank may publish a completion marker for a
    step a peer never committed, and because the failure path still
    participates in the vote, the agreement generation counters stay in
    lockstep (a rank that skipped the rendezvous would pair every later
    commit barrier with the wrong save).
    """
    if not _pending:
        return
    reg = get_registry()
    retries = reg.counter("ckpt_retries_total")
    with span("ckpt_finalize"), reg.timer("ckpt_finalize"):
        error: Optional[BaseException] = None
        try:
            _get_checkpointer().wait_until_finished()
        except Exception as e:  # noqa: BLE001 — abandoning, not crashing
            error = e
        # phase boundary of the async variant, fused with the failure
        # vote: every rank's background commit must have drained before
        # any completion marker appears anywhere
        gang_failed = error is not None
        if _gang_commit:
            gang_failed = coordination.get_coordinator().any_flag(
                "ckpt_commit", error is not None)
        if gang_failed:
            abandoned = [item[0] for item in _pending]
            _pending.clear()
            reg.counter("ckpt_failed_total").inc(len(abandoned))
            if error is not None:
                logger.error(
                    "async checkpoint commit FAILED (%s: %s) — abandoning "
                    "%s; training continues, the next periodic save retries "
                    "from scratch", type(error).__name__, error, abandoned)
            else:
                logger.error(
                    "async checkpoint commit failed on a PEER rank — "
                    "abandoning %s here too (a checkpoint is complete only "
                    "when every rank's shards are)", abandoned)
            # remove the half-written dirs NOW: periodic saves advance
            # monotonically and never revisit these steps, so nothing else
            # would ever reclaim the (potentially huge) partial payloads
            if _is_meta_writer():
                for path in abandoned:
                    shutil.rmtree(path, ignore_errors=True)
            return
        while _pending:
            item = _pending.pop(0)
            path, meta = item[0], item[1]
            leaves = item[2] if len(item) > 2 else None
            if _verify and _is_meta_writer():
                # the background commit has drained: the files are durable
                # and digestable now, not at dispatch time
                integrity.write_manifest(path, leaves=leaves)
            call_with_retry(lambda: _write_meta(path, meta),
                            desc="checkpoint meta write", counter=retries)
            _record_verified(os.path.dirname(path), int(meta.get("step", 0)))
            logger.info("async checkpoint finalized: %s", path)


def join_commit_vote() -> None:
    """The idle side of the two-phase commit rendezvous.

    A gang rank whose stream ran dry keeps matching its peers' save
    rendezvous (the commit agreement is a collective), but its step has
    not advanced since its last save — re-writing the unchanged state was
    PR 6's acknowledged wasted I/O. This publishes ONLY the rank's
    (healthy) commit vote; a peer's failed vote is observed and logged,
    since the peers abandon that save on their side. No-op when the gang
    commit is off (single process, or resilience disabled)."""
    if not _gang_commit:
        return
    if coordination.get_coordinator().any_flag("ckpt_commit", False):
        logger.error("checkpoint commit aborted by a peer rank at the "
                     "save rendezvous (this rank was idle — nothing to "
                     "abandon locally)")


def completed_steps(directory: str) -> list[int]:
    """Sorted steps with a parseable completion marker under ``directory``.

    Step dirs with a missing or corrupt meta file are skipped with a
    warning (from ``_read_meta``) instead of crashing the resume path —
    they are half-written saves that ``save_checkpoint`` cleans up when it
    next writes that step.
    """
    if not os.path.isdir(directory):
        return []
    steps = []
    for name in os.listdir(directory):
        if not name.startswith("step_"):
            continue
        try:
            step = int(name[len("step_"):])
        except ValueError:
            continue
        if _read_meta(os.path.join(directory, name)) is not None:
            steps.append(step)
    return sorted(steps)


def latest_step(directory: str) -> Optional[int]:
    """Highest completed step under ``directory`` (None if none)."""
    steps = completed_steps(directory)
    return steps[-1] if steps else None


def latest_verified_step(directory: str) -> Optional[int]:
    """Newest completed step under ``directory`` that is not PROVABLY
    corrupt: its manifest re-digests clean, or it predates manifests
    (``unverified`` — usable, just unprovable). Provably-corrupt steps
    are skipped with an error log, so resume targeting lands on the step
    a verified restore will actually accept."""
    for step in reversed(completed_steps(directory)):
        path = _step_dir(directory, step)
        # files-only: the archive's file digest covers every leaf byte,
        # and the restore this peek is targeting re-verifies leaves
        # anyway — no need to decode the npz twice per resume
        report = integrity.verify_checkpoint_dir(path, files_only=True)
        if report["status"] != "corrupt":
            return step
        _record_refused(directory, step)
        logger.error(
            "checkpoint %s failed integrity verification (files: %s, "
            "leaves: %s) — skipping it as a resume candidate", path,
            report["mismatched_files"], report["mismatched_leaves"])
    return None


def peek_meta(directory: str) -> Optional[dict]:
    """Read the latest checkpoint's meta dict without touching array data —
    used by the CLI to seed the sampler's ``consumed_samples`` before the
    engine restores the full state. Corrupt metas are skipped (the
    previous completed step wins), and with verification on the peek
    targets the newest step whose digests hold, so the sampler rewind
    matches the step the verified restore will land on."""
    step = latest_verified_step(directory) if _verify \
        else latest_step(directory)
    if step is None:
        return None
    return _read_meta(_step_dir(directory, step))


def gc_checkpoints(directory: str, keep_last: int,
                   keep_every: int = 0) -> int:
    """Prune old completed step dirs; returns how many were removed.

    Retention: the newest ``keep_last`` completed steps always survive
    (floored at 1 — the newest completed step is NEVER pruned, it is the
    resume point), plus every step divisible by ``keep_every`` when set
    (periodic keep-forever archives), plus the newest step this process
    has VERIFIED (save read-back or restore digest match) — GC never
    prunes past it, so a restore that refuses a newer corrupt step always
    has its fall-back target on disk. Half-written dirs are not touched —
    ``save_checkpoint`` owns those. Pruned dirs bump ``ckpt_gc_total``.

    Meta-writer gated (same convention as ``_write_meta``): on multi-host
    fleets with shared checkpoint storage, N hosts racing ``rmtree`` on
    the same dirs would leave partially-deleted checkpoints that still
    look complete; in per-rank mode every host prunes its own directory.
    """
    if not _is_meta_writer():
        return 0
    steps = completed_steps(directory)
    if not steps:
        return 0
    keep = set(steps[-max(int(keep_last), 1):])
    if keep_every:
        keep.update(s for s in steps if s % int(keep_every) == 0)
    verified = _last_verified.get(os.path.abspath(directory))
    if verified is not None:
        keep.add(verified)
    pruned = 0
    for s in steps:
        if s in keep:
            continue
        path = _step_dir(directory, s)
        logger.info("checkpoint gc: pruning %s", path)
        shutil.rmtree(path, ignore_errors=True)
        pruned += 1
    if pruned:
        get_registry().counter("ckpt_gc_total").inc(pruned)
    return pruned


def _verify_payload_or_raise(path: str, step: int) -> Optional[dict]:
    """The pre-restore integrity gate shared by both codecs: fire the
    ``corrupt_restore_at`` drill point, then re-digest every payload file
    against the manifest BEFORE any byte is deserialized. Returns the
    manifest (None when absent — a pre-integrity checkpoint restores
    unverified with an info log) or raises
    :class:`CheckpointIntegrityError` naming the mismatched files."""
    faults_mod.fire_path("ckpt_restore", path, int(step))
    if not _verify:
        return None
    manifest = integrity.read_manifest(path)
    if manifest is None:
        logger.info("no integrity manifest under %s — restoring "
                    "unverified (pre-integrity checkpoint)", path)
        return None
    reg = get_registry()
    reg.counter("ckpt_verify_total").inc()
    bad = integrity.verify_files(path, manifest)
    if bad:
        reg.counter("ckpt_verify_failed").inc()
        _record_refused(os.path.dirname(path), int(step))
        raise CheckpointIntegrityError(
            f"checkpoint {path} failed integrity verification: files "
            f"{bad} do not match the manifest digests — refusing to "
            f"restore corrupt state")
    return manifest


def _check_spec_provenance(meta: Optional[dict], path: str) -> None:
    """Both codecs run restores through this: a checkpoint stamped with a
    DIFFERENT registry fingerprint (``parallel/rules.py`` changed since
    the save) is loud in the logs — resharding across rule revisions is
    supported (checkpoints are topology-free), but it must never be
    invisible."""
    if not meta:
        return
    from fleetx_tpu.parallel import rules as rules_lib

    stamped = meta.get("spec_registry")
    if stamped and stamped != rules_lib.registry_fingerprint():
        logger.warning(
            "checkpoint %s was saved under partition-rule registry %s but "
            "the current registry is %s (family %s) — the restore re-shards "
            "onto the CURRENT rules; run tools/shardcheck.py if this is "
            "unexpected", path, stamped, rules_lib.registry_fingerprint(),
            meta.get("spec_family"))


def load_params(directory: str, step: Optional[int] = None,
                mesh: Any = None, family: Optional[str] = None,
                layout: Any = None) -> Any:
    """Restore only the params subtree of a saved TrainState.

    Eval/generation tools have no optimizer, so they can't construct the
    full abstract TrainState; instead the checkpoint's own metadata supplies
    shapes/dtypes for a structure-faithful restore, and ``params`` is
    extracted from the result.

    With a ``mesh`` (plus ``family``, defaulting to the one stamped in the
    checkpoint meta by ``EagerEngine.save``), each leaf restores DIRECTLY
    onto its registry sharding (``parallel/rules.py``) — Orbax loads every
    shard to its destination devices instead of materialising the whole
    tree replicated first, which is what lets a large checkpoint load on a
    mesh whose per-device HBM cannot hold the full tree.
    """
    step = step if step is not None else latest_step(directory)
    assert step is not None, f"no checkpoint found under {directory}"
    step_path = os.path.abspath(_step_dir(directory, step))
    _verify_payload_or_raise(step_path, int(step))
    step_meta = _read_meta(step_path)
    _check_spec_provenance(step_meta, step_path)
    path = os.path.join(step_path, "state")
    ckptr = _get_checkpointer()
    md = ckptr.metadata(path)
    tree = getattr(md, "item_metadata", md)
    sharding_for = None
    if mesh is not None:
        from fleetx_tpu.parallel import rules as rules_lib

        family = family or (step_meta or {}).get("spec_family")
        if family is None:
            logger.warning("load_params: no spec family stamped or given — "
                           "restoring replicated")
        else:
            from jax.sharding import NamedSharding, PartitionSpec

            def sharding_for(kp, m):
                name = "/".join(rules_lib._keystr(k) for k in kp)
                return NamedSharding(mesh, PartitionSpec(
                    *rules_lib.spec_for(family, name, tuple(m.shape),
                                        layout)))
    def abstract_leaf(kp, m):
        sharding = sharding_for(kp, m) if sharding_for else None
        return jax.ShapeDtypeStruct(m.shape, m.dtype, sharding=sharding)

    abstract = jax.tree_util.tree_map_with_path(
        abstract_leaf, tree,
        is_leaf=lambda m: hasattr(m, "shape") and hasattr(m, "dtype"))
    state = ckptr.restore(path, abstract)
    logger.info("restored params from %s (step %d%s)", path, step,
                ", registry-sharded" if sharding_for else "")
    return state["params"]


def load_checkpoint(directory: str, step: int, abstract_state: Any,
                    adapt_layout: bool = False) -> tuple[Any, dict]:
    """Restore a checkpoint, re-sharding to ``abstract_state``'s shardings.

    ``abstract_state`` is a pytree of ``jax.ShapeDtypeStruct`` leaves carrying
    ``sharding`` attributes (the engine builds it from its mesh) — Orbax loads
    each shard directly onto its destination devices.

    ``adapt_layout``: when a leaf's stored shape differs from the requested
    one only by a reshape of the leading (stage/repeat/layer) dims — the
    pipeline layouts ``[L] / [S, L/S] / [V, S, L/(V*S)]`` — restore with the
    stored shape and reshape. The reference cannot restore across
    topologies at all (per-rank dirs must match, ``eager_engine.py:617-660``).

    Dispatches on the on-disk layout: a ``state.npz`` payload (per-rank
    mode) restores through the host-local codec — which applies the same
    size-preserving reshapes — and an Orbax ``state/`` directory through
    the sharded path, so checkpoints from either storage mode load on any
    topology.

    Integrity: the payload's file digests are verified BEFORE any byte is
    deserialized and the per-leaf digests after (pre-cast for the npz
    codec, post-restore for single-process Orbax); any mismatch raises
    :class:`CheckpointIntegrityError` — the loud refusal the engine's
    fall-back loop consumes. Pre-integrity checkpoints (no manifest)
    restore unverified with an info log.
    """
    path = os.path.abspath(_step_dir(directory, step))
    manifest = _verify_payload_or_raise(path, int(step))
    # spec provenance covers BOTH codecs: the npz branch and the Orbax
    # branch below re-shard onto the CURRENT registry either way
    _check_spec_provenance(_read_meta(path), path)
    if os.path.exists(os.path.join(path, _LOCAL_STATE)):
        reg = get_registry()
        t0 = time.perf_counter()
        with span("checkpoint_restore", step=int(step)):
            state = call_with_retry(
                lambda: _restore_state_local(path, abstract_state,
                                             manifest=manifest),
                desc="checkpoint restore",
                counter=reg.counter("ckpt_retries_total"))
        reg.histogram("ckpt_restore").record(time.perf_counter() - t0)
        reg.counter("ckpt_restores_total").inc()
        reg.gauge("ckpt_bytes").set(_tree_bytes(state))
        meta = _read_meta(path)
        if meta is None:
            raise RuntimeError(
                f"checkpoint meta unreadable/corrupt for {path} — refusing "
                f"to resume without step/consumed_samples")
        if manifest is not None:
            _record_verified(directory, int(step))
        logger.info("restored checkpoint: %s (step %d)", path,
                    meta.get("step", step))
        return state, meta
    ckptr = _get_checkpointer()
    request = abstract_state
    reshaped: list[str] = []
    if adapt_layout:
        import re

        def norm(kp) -> str:
            # attribute vs dict-key paths must compare equal
            # (".params['gpt']" == "['params']['gpt']")
            return re.sub(r"\W+", "/", jax.tree_util.keystr(kp)).strip("/")

        md = ckptr.metadata(os.path.join(path, "state"))
        stored = getattr(md, "item_metadata", md)
        stored_leaves = {}

        def record(kp, m):
            if hasattr(m, "shape"):
                stored_leaves[norm(kp)] = tuple(m.shape)
            return m

        jax.tree_util.tree_map_with_path(
            record, stored,
            is_leaf=lambda m: hasattr(m, "shape") and hasattr(m, "dtype"))

        def adapt(kp, want):
            key = norm(kp)
            have = stored_leaves.get(key)
            if have is None or tuple(want.shape) == have:
                return want
            # compatible iff both flatten to the same total with identical
            # trailing (feature) dims — i.e. only the stage split differs
            import numpy as np
            if int(np.prod(have)) == int(np.prod(want.shape)):
                reshaped.append(key)
                sharding = None
                if getattr(want, "sharding", None) is not None:
                    # restore replicated on the same mesh; the engine
                    # re-places the adapted state onto its shardings
                    from jax.sharding import NamedSharding, PartitionSpec
                    sharding = NamedSharding(want.sharding.mesh,
                                             PartitionSpec())
                return jax.ShapeDtypeStruct(have, want.dtype,
                                            sharding=sharding)
            return want

        request = jax.tree_util.tree_map_with_path(adapt, abstract_state)

    reg = get_registry()
    t0 = time.perf_counter()
    with span("checkpoint_restore", step=int(step)):
        state = call_with_retry(
            lambda: ckptr.restore(os.path.join(path, "state"), request),
            desc="checkpoint restore",
            counter=reg.counter("ckpt_retries_total"))
    reg.histogram("ckpt_restore").record(time.perf_counter() - t0)
    reg.counter("ckpt_restores_total").inc()
    reg.gauge("ckpt_bytes").set(_tree_bytes(state))
    if _verify and manifest is not None and manifest.get("leaves") and \
            jax.process_count() == 1:
        # end-to-end leaf check for the Orbax codec: the DESERIALIZED
        # content must match the digests computed at save (single-process
        # only — a multi-process host would gather peers' shards to
        # digest a global leaf; the file digests above already cover the
        # on-disk bytes there). Recast leaves are skipped by nbytes.
        bad = integrity.verify_leaves(
            jax.tree.leaves(jax.device_get(state)), manifest["leaves"])
        if bad:
            reg.counter("ckpt_verify_failed").inc()
            _record_refused(directory, int(step))
            raise CheckpointIntegrityError(
                f"checkpoint {path} failed integrity verification: "
                f"restored leaves {bad} do not match the manifest "
                f"digests — refusing to resume from corrupt state")
    if manifest is not None:
        _record_verified(directory, int(step))
    if reshaped:
        logger.info("adapting pipeline layout of %d leaves on restore",
                    len(reshaped))
        state = jax.tree.map(
            lambda got, want: jnp_reshape_to(got, want.shape)
            if got.shape != want.shape else got,
            state, abstract_state)
    meta = _read_meta(path)
    if meta is None:
        # the dir was selected as COMPLETE (latest_step read this meta);
        # silently substituting {} here would reset consumed_samples to 0
        # and replay the whole data prefix — fail loudly instead
        raise RuntimeError(
            f"checkpoint meta unreadable/corrupt for {path} — refusing to "
            f"resume without step/consumed_samples")
    logger.info("restored checkpoint: %s (step %d)", path, meta.get("step", step))
    return state, meta


def jnp_reshape_to(arr: Any, shape: tuple) -> Any:
    """Reshape helper kept importable for tree_map closures."""
    import jax.numpy as jnp

    return jnp.reshape(arr, shape)
