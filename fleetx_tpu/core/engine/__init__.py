from fleetx_tpu.core.engine.auto_engine import AutoEngine  # noqa: F401
from fleetx_tpu.core.engine.basic_engine import BasicEngine  # noqa: F401
from fleetx_tpu.core.engine.eager_engine import (  # noqa: F401
    EagerEngine, TrainState, ScalerState, batch_sharding)
