from fleetx_tpu.core.engine.eager_engine import (  # noqa: F401
    EagerEngine, TrainState, ScalerState, batch_sharding)
