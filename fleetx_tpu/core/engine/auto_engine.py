"""AutoEngine — API-parity alias for the reference's auto-parallel stack.

Reference: ``ppfleetx/core/engine/auto_engine.py:36-133`` wraps
``paddle.distributed.fleet.auto.Engine``, which compiles the dygraph model
into a distributed static program (mesh planning, partitioning, collective
insertion). In this framework that compilation model IS the default path:
``EagerEngine`` jits one mesh-sharded train step and GSPMD performs the
planning/partitioning the reference's auto stack hand-rolls (SURVEY.md §7
design stance). ``AutoEngine`` therefore subclasses ``EagerEngine``
unchanged — it exists so reference users find the name and so
``tools/auto.py`` mirrors the reference CLI surface.
"""

from __future__ import annotations

from fleetx_tpu.core.engine.eager_engine import EagerEngine


class AutoEngine(EagerEngine):
    """GSPMD-compiled engine (the reference auto stack, subsumed).

    Telemetry (docs/observability.md) is inherited wholesale: the same
    ``Observability:`` YAML block, spans and sinks apply, and every emitted
    record carries ``engine: "AutoEngine"`` so mixed eager/auto runs stay
    distinguishable in one metrics stream.
    """
