"""Abstract engine protocol (reference ``core/engine/basic_engine.py:16-39``)."""

from __future__ import annotations

from typing import Any, Iterable


class BasicEngine:
    """The engine surface every trainer implements."""

    def fit(self, train_data_loader: Iterable, valid_data_loader=None,
            epoch_num: int = 1):
        raise NotImplementedError

    def evaluate(self, valid_data_loader: Iterable, global_step: int = 0):
        raise NotImplementedError

    def predict(self, data: Any):
        raise NotImplementedError

    def save(self):
        raise NotImplementedError

    def load(self, directory: str | None = None):
        raise NotImplementedError

    def inference(self, data: Any):
        raise NotImplementedError
