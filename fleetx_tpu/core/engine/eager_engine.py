"""The trainer — reference ``EagerEngine`` re-designed for jit/GSPMD.

Reference: ``ppfleetx/core/engine/eager_engine.py:41-738``. The reference
engine imperatively wires AMP scalers, HCG process groups, sharded-model
wrappers and a hand-rolled train loop. Here the same capabilities collapse
into one jitted, mesh-sharded ``train_step``:

- hybrid parallelism (dp/tp/fsdp/sp): the state's shardings are derived from
  the model's logical axis metadata + one rule table
  (``parallel/sharding.py``) — GSPMD inserts every collective the reference
  hand-wires (``eager_engine.py:221-248`` wrap, ``385-399`` grad allreduce).
- AMP: bf16 compute by default; optional fp16 dynamic loss scaling
  (reference GradScaler, ``eager_engine.py:157-167``) implemented in-step.
- grad accumulation (``accumulate_steps``): ``lax.scan`` over micro-batches
  (reference splits local batch at ``utils/config.py:117``).
- train loop semantics: max_steps / logging_freq / eval_freq / save_steps /
  resume-skip (``eager_engine.py:250-330``) with the module's ips metric
  hooks (``language_module.py:58-67``).

Checkpointing is sharding-aware and topology-free (``core/checkpoint.py``).
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from flax import struct
from flax.core import meta
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fleetx_tpu.core import checkpoint as ckpt_lib
from fleetx_tpu.observability import MemoryMonitor, Observability, flight
from fleetx_tpu.observability.trace import ProfilerWindow
from fleetx_tpu.parallel import rules as rules_lib
from fleetx_tpu.parallel.mesh import build_mesh
from fleetx_tpu.parallel.sharding import zero_grad_specs, zero_sharding
from fleetx_tpu.resilience import Resilience, TrainingAborted, coordination
from fleetx_tpu.utils.log import logger, set_rank_context


class ScalerState(struct.PyTreeNode):
    """Dynamic fp16 loss-scale state (reference GradScaler config,
    ``eager_engine.py:157-164``: init 32768, incr_every_n 1000, x2 / x0.5)."""

    loss_scale: jax.Array     # f32 scalar
    growth_tracker: jax.Array  # i32 consecutive-finite counter


class TrainState(struct.PyTreeNode):
    """Jitted training state: step, params, optimizer state, fp16 scaler."""
    step: jax.Array            # i32 scalar
    params: Any                # boxed (nn.Partitioned) param pytree
    opt_state: Any
    scaler: Optional[ScalerState] = None


def _named_shardings(abstract_tree: Any, mesh: Mesh, rules,
                     family: Optional[str] = None,
                     layout: Optional[rules_lib.SpecLayout] = None) -> Any:
    """Abstract state → NamedSharding tree, resolved through the
    partition-rule registry (``parallel/rules.py``) for known model
    families — specs are DATA matched against leaf names, statically
    auditable by ``tools/shardcheck.py``, and an unmatched non-scalar leaf
    fails HERE (at prepare) instead of at jit bind time.

    Modules that declare no ``spec_family`` fall back to the flax logical
    annotations (replicated where unboxed) with a warning — custom task
    modules keep working, they just forgo the static audit.
    """
    if family is not None:
        return rules_lib.named_shardings(abstract_tree, mesh, family, layout)
    logger.warning(
        "module declares no spec_family — resolving shardings from flax "
        "logical metadata; register the model in parallel/rules.py "
        "PARTITION_RULES to get shardcheck coverage")
    specs = nn.get_partition_spec(abstract_tree)
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, nn.logical_to_mesh_axes(spec, rules)),
        specs, is_leaf=lambda x: isinstance(x, P))


def _device_hbm_gb(dist: dict) -> float:
    """Per-device HBM for the offload advisory: the YAML's
    ``auto_layout: {hbm_gb: N}`` wins, then the device's reported memory,
    then the v5e default of 16 (axon does not report ``memory_stats``)."""
    al = dist.get("auto_layout")
    if isinstance(al, dict) and al.get("hbm_gb"):
        return float(al["hbm_gb"])
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit")
        if limit:
            return float(limit) / (1 << 30)
    except Exception:  # noqa: BLE001 — backends without memory_stats
        pass
    return 16.0


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Global batches are sharded over the combined data axes (reference
    ``env.get_data_world_size``: dp x sharding, ``utils/env.py:76-96``);
    the axes come from the registry's ``batch`` rule, not a literal."""
    return NamedSharding(mesh, rules_lib.batch_spec())


from fleetx_tpu.core.engine.basic_engine import BasicEngine


class EagerEngine(BasicEngine):
    """Mesh-sharded trainer with the reference's loop semantics."""

    def __init__(self, cfg: dict, module, optimizer=None, lr_schedule=None,
                 mesh: Optional[Mesh] = None, mode: str = "train"):
        self.cfg = cfg or {}
        self.module = module
        self.mode = mode

        def _int(section, key, default):
            v = section.get(key, default)
            return default if v is None else int(v)

        eng = dict(self.cfg.get("Engine") or {})
        self.max_steps = _int(eng, "max_steps", 500000)
        self.logging_freq = _int(eng, "logging_freq", 1)
        self.eval_freq = _int(eng, "eval_freq", 0)
        self.eval_iters = _int(eng, "eval_iters", 10)
        self.accumulate_steps = max(_int(eng, "accumulate_steps", 1), 1)
        # device-side input double buffering (docs/bandwidth_levers.md):
        # depth of the prefetch-to-device queue; 0 = serial fetch→shard→step
        self.prefetch_to_device = _int(eng, "prefetch_to_device", 0)
        # "step" (GPT pretrain): loop the loader until max_steps; "epoch"
        # (ViT-style): stop after epoch_num passes (reference run_mode,
        # eager_engine.py:250-330)
        self.run_mode = str(eng.get("run_mode") or "step")
        save_load = dict(eng.get("save_load") or {})
        self.save_steps = _int(save_load, "save_steps", 0)
        self.output_dir = save_load.get("output_dir", "./output")
        self.ckpt_dir = save_load.get("ckpt_dir")
        self.async_save = bool(save_load.get("async_save"))
        # checkpoint retention GC (docs/resilience.md): keep the newest
        # keep_last completed steps (+ every keep_every-th forever); 0/None
        # keeps everything
        self.keep_last = _int(save_load, "keep_last", 0)
        self.keep_every = _int(save_load, "keep_every", 0)

        # fault-tolerant runtime (docs/resilience.md): retry policy, guard,
        # watchdog, preemption + fault injection; inert unless the
        # Resilience block enables it
        self.resilience = Resilience(self.cfg.get("Resilience"))

        # gang coordinator (docs/resilience.md multi-host section): the
        # local no-op on single-process runs, KV-store agreement on pods —
        # every recovery decision below routes through it
        self.coord = coordination.get_coordinator()
        # interleaved gang logs are unattributable without a rank tag;
        # single-process output stays byte-identical (empty prefix)
        set_rank_context(self.coord.rank, self.coord.world)
        # per-rank checkpoint directories (host-local SSDs / CPU-mesh test
        # gangs): each process owns <output_dir>/rank_<i> outright and the
        # checkpoint layer switches to the host-local codec
        self.per_rank_ckpt = bool(save_load.get("per_rank_dirs")) and \
            self.coord.world > 1
        if self.per_rank_ckpt:
            suffix = f"rank_{self.coord.rank}"
            self.output_dir = os.path.join(self.output_dir, suffix)
            if self.ckpt_dir:
                rank_dir = os.path.join(self.ckpt_dir, suffix)
                if os.path.isdir(rank_dir):
                    self.ckpt_dir = rank_dir
                else:
                    # warm start from a shared-layout checkpoint: restore
                    # dispatches on the on-disk layout, so the un-suffixed
                    # dir loads in per-rank mode too — rewriting it to a
                    # nonexistent rank dir would silently skip the resume
                    logger.warning(
                        "per_rank_dirs: %s has no %s subdirectory — "
                        "loading it as a shared-layout checkpoint",
                        self.ckpt_dir, suffix)
        if self.per_rank_ckpt and self.resilience.guard_skip:
            # per-rank gangs save/restore each rank's OWN step counter:
            # the in-step skip desynchronizes those counters, the saves
            # then carry divergent step names, and resume refuses them —
            # docs/resilience.md requires the skip off in this mode, so
            # enforce it (guard rollback stays available and collective)
            logger.warning(
                "per_rank_dirs: disabling guard.skip_nonfinite_update — "
                "the in-step skip desynchronizes per-rank step counters "
                "and a divergent-step resume is refused; use the guard's "
                "rollback action on per-rank gangs instead")
            self.resilience.guard_skip = False
            if self.resilience.guard is not None:
                self.resilience.guard.skip_active = False
        ckpt_lib.set_per_rank_mode(self.per_rank_ckpt)
        # the two-phase commit needs the resilience runtime's VOTED loop
        # exits: without them ranks can leave fit at different times and
        # an unmatched commit barrier would wedge a healthy rank's save
        ckpt_lib.set_gang_commit(self.resilience.enabled and
                                 self.coord.world > 1)
        # integrity manifests + verified restore (docs/resilience.md
        # "Integrity"; default ON — independent of Resilience.enable)
        ckpt_lib.set_verify_mode(self.resilience.integrity_verify)

        mp_cfg = dict(eng.get("mix_precision") or {})
        self.use_fp16_scaler = bool(mp_cfg.get("use_pure_fp16")) and (
            getattr(getattr(module, "model_cfg", None), "dtype", None) == jnp.float16)
        self.init_loss_scale = float(mp_cfg.get("scale_loss") or 32768.0)

        dist = dict(self.cfg.get("Distributed") or {})
        self.mesh = mesh if mesh is not None else build_mesh(dist)
        if self.coord.world > 1 and not self.per_rank_ckpt and all(
                d.process_index == jax.process_index()
                for d in np.asarray(self.mesh.devices).flat):
            # N processes with process-local meshes hold N independent
            # states: Orbax's multihost sync cannot coordinate their saves
            # into one shared directory (ranks would publish meta for
            # divergent steps and silently lose peers' checkpoints)
            raise ValueError(
                "a multi-process run on a process-local mesh requires "
                "Engine.save_load.per_rank_dirs: true — shared checkpoint "
                "storage only composes with a mesh that spans processes")
        # partition-rule registry (parallel/rules.py): the layout is the
        # logical->mesh table (also the flax activation-constraint context)
        # and the family names the PARTITION_RULES table that shards this
        # module's parameter tree — specs are data, audited statically by
        # tools/shardcheck.py before they ever reach a jit bind
        self.spec_layout = rules_lib.SpecLayout.from_dist_config(dist)
        self.spec_family = rules_lib.family_of(module)
        self.rules = self.spec_layout.axis_rules()
        self.sharding_stage = int((dist.get("sharding") or {}).get("sharding_stage") or 0)
        self.sharding_offload = bool(
            (dist.get("sharding") or {}).get("sharding_offload"))
        # overlapped sharded update (docs/bandwidth_levers.md): params LIVE
        # fsdp-sharded across steps and are allgathered inside the loss —
        # the gather lands at the step head where it overlaps the forward,
        # instead of serializing after the optimizer at the step tail
        self.overlap_update = bool(
            (dist.get("sharding") or {}).get("overlap_update"))
        if self.overlap_update and self.sharding_stage < 2:
            logger.warning(
                "sharding.overlap_update needs sharding_stage >= 2 (the "
                "update must consume reduce-scattered grad shards); "
                "continuing without overlap")
            self.overlap_update = False
        if self.sharding_offload:
            # offload is a fit-enabler that costs ~2.8x step time on-chip
            # (BENCHMARKS.md); flag configs that would fit without it
            from fleetx_tpu.parallel.auto_layout import (advice_inputs,
                                                         offload_is_needed)

            data_world = (int(dist.get("dp_degree") or 1)
                          * int(dist.get("fsdp_degree") or 1))
            mdl, mb, gran = advice_inputs(self.cfg, data_world=data_world)
            hbm_gb = _device_hbm_gb(dist)
            if not offload_is_needed(mdl, dist, micro_batch=mb,
                                     recompute=gran, hbm_gb=hbm_gb):
                logger.warning(
                    "sharding_offload is on but the step estimate fits HBM "
                    "without it — offload costs ~2.8x step time and should "
                    "only be used when the model otherwise does not fit")
        if self.sharding_offload and jax.default_backend() != "tpu":
            # host memory-kind placement needs the TPU runtime; the virtual
            # CPU backend rejects replicated placement annotations
            logger.warning("sharding_offload requires a TPU backend; "
                           "continuing without offload")
            self.sharding_offload = False
        if self.sharding_offload and self.use_fp16_scaler:
            # the scaler's overflow-revert would compute directly on
            # host-resident state; keep the combinations orthogonal
            logger.warning("sharding_offload is not supported with the fp16 "
                           "scaler; continuing without offload")
            self.sharding_offload = False
        self.pp_degree = int(dist.get("pp_degree") or 1)
        if self.pp_degree > 1:
            # the pipeline consumes the local batch as micro-batches itself
            # (reference train_batch semantics, eager_engine.py:400-410) — the
            # engine must not additionally slice it
            self.accumulate_steps = 1

        glb = dict(self.cfg.get("Global") or {})
        self.seed = int(glb.get("seed", 1234))
        # dropout-mask generation with the default threefry2x32 costs real
        # step time on TPU (counter-based hashing on the VPU); Global.prng_impl
        # lets throughput-focused recipes switch to the hardware-accelerated
        # generators ("rbg"/"unsafe_rbg" — different stream, same statistics)
        prng_impl = glb.get("prng_impl")
        self._base_rng = (jax.random.key(self.seed, impl=str(prng_impl))
                          if prng_impl else jax.random.PRNGKey(self.seed))

        # profiler window (reference Profiler: config block + paddle.profiler
        # integration, eager_engine.py:197-219,329-330,679-738) — state
        # machine owned by observability.trace.ProfilerWindow: re-armed per
        # fit, and stop_trace drains device work first
        self.profiler = ProfilerWindow(self.cfg.get("Profiler"))

        # unified telemetry (docs/observability.md): metrics registry +
        # span tracer + sinks, no-op unless Observability.enable is set
        self.obs = Observability(self.cfg.get("Observability"),
                                 default_output_dir=self.output_dir)
        self._engine_kind = type(self).__name__
        # performance introspection (docs/performance.md): every closed
        # profiler window is decomposed into the MFU-gap report and landed
        # in the perf stream + flight ring automatically
        self.profiler.on_stop = self._on_profiler_stop
        self.mem = None  # HBM monitor — built in prepare (mesh known)
        self._perf_flops_per_step = None
        self._perf_report = None

        self.optimizer = optimizer
        self.lr_schedule = lr_schedule
        self.state: Optional[TrainState] = None
        self.state_shardings = None
        self._train_step = None
        self._eval_step = None
        self._consumed_samples = 0
        self._start_epoch = 0
        # sample position auto-resume REWOUND the stream to (None until it
        # runs); fit compares it with the position the restore actually
        # landed on — an integrity fall-back can land on an older step
        # than the peek predicted, and the stream must follow
        self._resume_expected_consumed = None
        # fault injection for restart/elasticity tests (tools/supervise.py)
        self._fault_step = int(os.environ.get("FLEETX_FAULT_STEP") or 0)

    # ------------------------------------------------------------- contexts
    def _ctx(self):
        """Mesh + logical-rule context for every trace/execute."""
        stack = contextlib.ExitStack()
        stack.enter_context(self.mesh)
        stack.enter_context(nn.logical_axis_rules(self.rules))
        return stack

    # ------------------------------------------------------- state creation
    def _make_state_fn(self, sample_batch: dict):
        module, optimizer = self.module, self.optimizer
        use_scaler, init_scale = self.use_fp16_scaler, self.init_loss_scale

        def make_state(rng):
            params = module.init_variables(rng, sample_batch)
            opt_state = optimizer.init(params) if optimizer is not None else ()
            scaler = None
            if use_scaler:
                scaler = ScalerState(loss_scale=jnp.float32(init_scale),
                                     growth_tracker=jnp.int32(0))
            return TrainState(step=jnp.int32(0), params=params,
                              opt_state=opt_state, scaler=scaler)

        return make_state

    def prepare(self, sample_batch: dict) -> TrainState:
        """Initialise (or lazily re-use) the sharded train state."""
        if self.state is not None:
            return self.state
        sample_batch = _host_batch(sample_batch)
        with self._ctx():
            make_state = self._make_state_fn(sample_batch)
            abstract = jax.eval_shape(make_state, self._base_rng)
            shardings = _named_shardings(abstract, self.mesh, self.rules,
                                         family=self.spec_family,
                                         layout=self.spec_layout)
            if self.sharding_stage in (1, 2) and self.mesh.shape["fsdp"] > 1:
                # ZeRO-1/2: shard optimizer moments over fsdp while params
                # stay replicated (reference group_sharded_parallel
                # level="os_g", eager_engine.py:228-242).
                opt_abs = meta.unbox(abstract.opt_state)
                opt_sh = _tree_of(shardings.opt_state)
                shardings = shardings.replace(opt_state=zero_sharding(
                    opt_abs, self.mesh, param_shardings=opt_sh))
            self._grad_shardings = None
            if self.sharding_stage >= 2 and self.mesh.shape["fsdp"] > 1:
                # ZeRO-2 proper (docs/zero_sharding.md): the grad pytree
                # (and the accumulation carry) is constrained to these
                # specs inside train_step, so GSPMD lowers the dp grad
                # sync to reduce-scatter + sharded update + allgathered
                # params instead of allreduce + replicated update
                params_abs = meta.unbox(abstract.params)
                self._grad_shardings = zero_grad_specs(
                    params_abs, self.mesh,
                    param_shardings=_tree_of(shardings.params))
                if self.obs.enabled:
                    # bytes of grad leaves stage 2 actually distributes
                    # (the per-device saving is this times (1 - 1/fsdp))
                    self.obs.registry.gauge("grad_bytes_sharded").set(
                        _sharded_grad_bytes(params_abs,
                                            self._grad_shardings))
            self._param_gather_shardings = None
            if (self.overlap_update and self._grad_shardings is not None):
                # Overlapped update (docs/bandwidth_levers.md): store params
                # ON the grad shards between steps, so the whole update
                # chain (norm + clip + adam + apply) runs on 1/fsdp-sized
                # operands, and move the param allgather INTO the loss
                # (``gather_params`` in ``_build_step_fns``). XLA then
                # schedules the gather at the head of the next step where it
                # overlaps the forward's first matmuls — instead of a tail
                # allgather that serializes after the optimizer. Same
                # scheme as the tail of "Automatic Cross-Replica Sharding
                # of Weight Update in Data-Parallel Training" (PAPERS.md).
                self._param_gather_shardings = shardings.params
                shardings = shardings.replace(params=self._grad_shardings)
            self._opt_dev_shardings = None
            if self.sharding_offload and self.sharding_stage >= 1:
                # ZeRO offload (reference group_sharded_parallel
                # offload=True): optimizer state LIVES in host memory and is
                # streamed to device memory around the update inside the
                # jitted step (XLA memory kinds over PCIe/DMA)
                self._opt_dev_shardings = shardings.opt_state
                shardings = shardings.replace(opt_state=jax.tree.map(
                    lambda s: s.with_memory_kind("pinned_host"),
                    shardings.opt_state))
            self.state_shardings = shardings
            init_fn = jax.jit(make_state, out_shardings=shardings)
            t0 = time.time()
            self.state = init_fn(self._base_rng)
            jax.block_until_ready(jax.tree.leaves(self.state.params)[:1])
            logger.info("initialized train state in %.1fs (%s params)",
                        time.time() - t0,
                        _fmt_count(_param_count(self.state.params)))
        self._build_step_fns()
        if self.obs.enabled and self.obs.derived is None:
            fpt = None
            if hasattr(self.module, "flops_per_token"):
                fpt = self.module.flops_per_token()
            # mesh.size, not device_count(): the run only uses (and its
            # throughput only reflects) the mesh's devices
            self.obs.init_derived(fpt, self.mesh.size)
            if self.obs.gang_enabled and self.coord.world > 1:
                # straggler skew (docs/observability.md "Multi-host"):
                # every coordination agreement's arrival census feeds the
                # rolling per-rank skew estimate from here on
                self.obs.install_arrival_hook()
        if self.obs.enabled and self.mem is None:
            # HBM attribution (docs/performance.md): sample memory_stats
            # at phase boundaries and score the measured peak against the
            # auto_layout prediction for THIS config (hbm_model_error) —
            # closing the loop on the model that plans offload/stages
            self.mem = MemoryMonitor(
                registry=self.obs.registry,
                predicted_bytes=self._predicted_hbm_bytes())
            self.mem.sample("post_compile")
        if self.ckpt_dir:
            self.load(self.ckpt_dir)
        return self.state

    # ------------------------------------------------------------ step fns
    def _build_step_fns(self):
        module = self.module
        optimizer, lr_schedule = self.optimizer, self.lr_schedule
        if optimizer is not None and not getattr(optimizer, "fused_clip",
                                                 False):
            # update() grows the grad_norm extra arg (single-pass norm,
            # docs/zero_sharding.md); transformations that don't consume it
            # (plain optax, sgd without clip) ignore it
            optimizer = optax.with_extra_args_support(optimizer)
        accum = self.accumulate_steps
        base_rng = self._base_rng
        use_scaler = self.use_fp16_scaler
        # guard skip (docs/resilience.md): generalizes the fp16-scaler's
        # isfinite update-skip to any compute dtype — a non-finite step is
        # dropped on-device so a single bad batch never poisons the params
        guard_skip = self.resilience.guard_skip
        check_finite = use_scaler or guard_skip
        opt_dev_shardings = getattr(self, "_opt_dev_shardings", None)
        opt_host_shardings = (self.state_shardings.opt_state
                              if opt_dev_shardings is not None else None)
        # ZeRO-2 (docs/zero_sharding.md): flat spec list aligned with the
        # grad pytree's leaf order (the boxed grads and the unboxed spec
        # tree flatten identically — unboxing only strips the metadata)
        grad_spec_leaves = None
        if getattr(self, "_grad_shardings", None) is not None:
            grad_spec_leaves = jax.tree.leaves(self._grad_shardings)
        # overlapped update (docs/bandwidth_levers.md): params live on the
        # grad shards between steps; these are the FULL specs the loss
        # gathers them back to
        gather_spec_leaves = None
        if getattr(self, "_param_gather_shardings", None) is not None:
            gather_spec_leaves = jax.tree.leaves(self._param_gather_shardings)
        # grad-accumulation carry dtype (Model.grad_accum_dtype): fp32
        # default, bf16 opt-in halves the live accumulator; None keeps the
        # grads' native dtype
        accum_dtype = getattr(getattr(module, "model_cfg", None),
                              "grad_accum_dtype", None)

        def constrain_grads(grads):
            """Pin the grad pytree to the stage-2 fsdp specs. Applied per
            microbatch AND to the scan carry, so the reduce-scatter of
            microbatch i overlaps microbatch i+1's backward instead of
            serializing at the end of the step."""
            if grad_spec_leaves is None:
                return grads
            leaves, treedef = jax.tree.flatten(grads)
            return jax.tree.unflatten(treedef, [
                jax.lax.with_sharding_constraint(g, s)
                for g, s in zip(leaves, grad_spec_leaves)])

        def gather_params(params):
            """Allgather the fsdp-sharded resident params back to their full
            (tensor-parallel-only) specs — INSIDE the loss, so the gather
            sits at the head of the step where XLA overlaps it with the
            forward's first matmuls, and its transpose (a reduce-scatter)
            delivers the param cotangents already on the grad shards."""
            if gather_spec_leaves is None:
                return params
            leaves, treedef = jax.tree.flatten(params)
            return jax.tree.unflatten(treedef, [
                jax.lax.with_sharding_constraint(p, s)
                for p, s in zip(leaves, gather_spec_leaves)])

        def grads_and_metrics(params, scaler, batch, step):
            def loss_fn(p):
                p = gather_params(p)
                loss, metrics = module.training_loss(p, batch, base_rng, step)
                if use_scaler:
                    loss = loss * scaler.loss_scale.astype(loss.dtype)
                return loss, metrics
            (_, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            if use_scaler:
                inv = 1.0 / scaler.loss_scale
                grads = jax.tree.map(lambda g: g * inv.astype(g.dtype), grads)
            return constrain_grads(grads), metrics

        def update_fn(params, opt_state, grads):
            """The fused update path (docs/zero_sharding.md): ONE global-norm
            reduction shared by the ``grad_norm`` metric and the clip —
            either owned by a ``fused_clip`` optimizer or threaded in as an
            optax extra arg — then update + apply under stage-2 sharded
            grads. Shared verbatim by ``train_step`` and the isolated
            ``measure_update_phase`` timing."""
            with jax.named_scope("optimizer_update"):
                if opt_dev_shardings is not None:  # offload: host -> device
                    opt_state = jax.device_put(opt_state, opt_dev_shardings)
                if getattr(optimizer, "fused_clip", False):
                    updates, new_opt, grad_norm = optimizer.update(
                        grads, opt_state, params)
                else:
                    grad_norm = optax.global_norm(grads)
                    updates, new_opt = optimizer.update(
                        grads, opt_state, params, grad_norm=grad_norm)
                if opt_dev_shardings is not None:  # device -> host
                    new_opt = jax.device_put(new_opt, opt_host_shardings)
                new_params = optax.apply_updates(params, updates)
            return new_params, new_opt, grad_norm

        self._update_fn = update_fn
        self._constrain_grads = constrain_grads
        self._gather_params = gather_params

        def train_step(state: TrainState, batch: dict):
            if accum > 1:
                lead = jax.tree.leaves(batch)[0].shape[0]
                if lead % accum:
                    # a real training batch that does not divide into the
                    # configured microbatches is a config error — reshaping
                    # it anyway would train a different schedule than
                    # configured (VERDICT weak #5)
                    raise ValueError(
                        f"local batch {lead} is not divisible by "
                        f"accumulate_steps {accum} — fix "
                        f"Global.local/micro_batch_size or "
                        f"Engine.accumulate_steps")
                micro = jax.tree.map(
                    lambda x: x.reshape((accum, x.shape[0] // accum) + x.shape[1:]),
                    batch)

                def to_carry(g):
                    if accum_dtype is None:
                        return constrain_grads(g)
                    return constrain_grads(jax.tree.map(
                        lambda l: l.astype(accum_dtype), g))

                def body(carry, mb):
                    g_acc, m_acc = carry
                    g, m = grads_and_metrics(state.params, state.scaler, mb, state.step)
                    g_acc = constrain_grads(jax.tree.map(
                        lambda a, gi: a + gi.astype(a.dtype), g_acc, g))
                    m_acc = jax.tree.map(jnp.add, m_acc, m)
                    return (g_acc, m_acc), None

                first = jax.tree.map(lambda x: x[0], micro)
                g1, m1 = grads_and_metrics(state.params, state.scaler, first, state.step)
                rest = jax.tree.map(lambda x: x[1:], micro)
                (grads, metrics), _ = jax.lax.scan(body, (to_carry(g1), m1), rest)
                # back to the params' dtype for the update (a fp32/bf16
                # carry over fp16-scaled grads must not leak its dtype into
                # the optimizer chain)
                grads = jax.tree.map(lambda g, p: (g / accum).astype(p.dtype),
                                     grads, state.params)
                metrics = jax.tree.map(lambda m: m / accum, metrics)
            else:
                grads, metrics = grads_and_metrics(state.params, state.scaler,
                                                   batch, state.step)

            metrics = dict(metrics)
            if lr_schedule is not None:
                metrics["lr"] = lr_schedule(state.step)

            new_params, new_opt, grad_norm = update_fn(
                state.params, state.opt_state, grads)
            metrics["grad_norm"] = grad_norm

            new_scaler = state.scaler
            new_step = state.step + 1
            if check_finite:
                finite = jnp.isfinite(grad_norm) & jnp.isfinite(
                    metrics["loss"])
                # skip the update on a non-finite step (fp16 overflow, NaN
                # loss): revert params/opt to the pre-step values
                # (reference GradScaler semantics, eager_engine.py:157-164,
                # extended to every dtype by the resilience guard)
                new_params = jax.tree.map(
                    lambda new, old: jnp.where(finite, new, old),
                    new_params, state.params)
                new_opt = jax.tree.map(
                    lambda new, old: jnp.where(finite, new, old) if
                    getattr(new, "shape", None) == getattr(old, "shape", None)
                    else new, new_opt, state.opt_state)
                # a skipped step must not advance the LR schedule /
                # dropout fold-in
                new_step = state.step + jnp.where(finite, 1, 0).astype(
                    state.step.dtype)
                # host-side guard policy reads this at logging windows
                metrics["finite"] = finite
            if use_scaler:
                # grow/backoff the dynamic loss scale
                tracker = jnp.where(finite, state.scaler.growth_tracker + 1, 0)
                grow = tracker >= 1000
                scale = jnp.where(
                    finite,
                    jnp.where(grow, state.scaler.loss_scale * 2.0,
                              state.scaler.loss_scale),
                    state.scaler.loss_scale * 0.5)
                new_scaler = ScalerState(loss_scale=scale,
                                         growth_tracker=jnp.where(grow, 0, tracker))
                metrics["loss_scale"] = scale

            # let the host resync its step mirror at logging points (the
            # fp16 scaler and the resilience guard skip step increments on
            # non-finite updates)
            metrics["opt_step"] = new_step

            return TrainState(step=new_step, params=new_params,
                              opt_state=new_opt, scaler=new_scaler), metrics

        def eval_step(state: TrainState, batch: dict):
            loss, metrics = module.validation_loss(
                gather_params(state.params), batch)
            return dict(metrics)

        bs = batch_sharding(self.mesh)
        with self._ctx():
            if optimizer is not None:
                self._train_step = jax.jit(
                    train_step,
                    in_shardings=(self.state_shardings, bs),
                    out_shardings=(self.state_shardings, None),
                    donate_argnums=(0,))
            self._eval_step = jax.jit(
                eval_step, in_shardings=(self.state_shardings, bs),
                out_shardings=None)
        # SDC sentinel hooks (docs/resilience.md "Integrity"): the raw
        # step fn is kept so a NON-donating twin can be jitted lazily at
        # the first sentinel check — with the sentinel off (cadence 0)
        # neither twin nor fingerprint fn is ever built and the loop is
        # byte-identical to the pre-integrity engine
        self._train_step_raw = train_step if optimizer is not None else None
        self._train_step_nodonate = None
        self._fingerprint_fn = None

    def shard_batch(self, batch: dict) -> dict:
        """Place a host batch onto the mesh, sharded over the data axes."""
        bs = batch_sharding(self.mesh)
        return jax.tree.map(lambda x: jax.device_put(np.asarray(x), bs), batch)

    # ------------------------------------------------- update-phase timing
    def measure_update_phase(self, iters: int = 3) -> float:
        """Time the outside-the-scans update path in isolation
        (docs/zero_sharding.md): global norm + clip + optimizer + apply,
        jitted with the exact closure ``train_step`` uses (``_update_fn``),
        on params-shaped synthetic grads. Each run is recorded as an
        ``optimizer_update`` span/histogram so ``bench.py`` can emit the
        phase mean next to the step time; returns the mean seconds.

        The trace decomposition (BENCHMARKS.md) bounds this phase inside
        the 38.8 ms/step outside-the-scans tail — this measures the
        optimizer slice of it directly, including the stage-2
        reduce-scatter/allgather when ZeRO-2 is on.
        """
        assert self.state is not None and self.optimizer is not None, \
            "call prepare() first"
        update_fn, constrain_grads = self._update_fn, self._constrain_grads

        def update_only(state: TrainState):
            grads = constrain_grads(jax.tree.map(jnp.ones_like, state.params))
            return update_fn(state.params, state.opt_state, grads)

        with self._ctx():
            fn = jax.jit(update_only,
                         in_shardings=(self.state_shardings,),
                         out_shardings=(self.state_shardings.params,
                                        self.state_shardings.opt_state, None))
            jax.block_until_ready(fn(self.state))  # compile + warm
            total = 0.0
            for _ in range(max(iters, 1)):
                with self.obs.timed_span("optimizer_update"):
                    t0 = time.perf_counter()
                    jax.block_until_ready(fn(self.state))
                    total += time.perf_counter() - t0
        return total / max(iters, 1)

    # -------------------------------------------------------- SDC sentinel
    def _ensure_sentinel_fns(self):
        """Lazily build the sentinel's compiled pieces: a NON-donating
        twin of ``train_step`` (the replay must re-execute on the saved
        state, which donation would have invalidated) and the jitted
        param-pytree bit-fingerprint. Built only when the sentinel is
        armed, so cadence 0 compiles nothing extra."""
        if self._train_step_nodonate is not None:
            return
        assert self._train_step_raw is not None, "no optimizer step to replay"
        from fleetx_tpu.resilience.integrity import params_fingerprint

        bs = batch_sharding(self.mesh)
        with self._ctx():
            self._train_step_nodonate = jax.jit(
                self._train_step_raw,
                in_shardings=(self.state_shardings, bs),
                out_shardings=(self.state_shardings, None))
            self._fingerprint_fn = jax.jit(params_fingerprint)

    def _sdc_check(self, prev_state: TrainState, sharded: dict,
                   metrics: dict, step: int, gang: bool) -> None:
        """One SDC sentinel check (docs/resilience.md "Integrity").

        Two probes, both cheap relative to their cadence: (1) REPLAY —
        re-execute the jitted train step on the saved ``(state, batch)``
        pair through the same non-donating executable that produced
        ``metrics`` and compare loss/grad-norm BITWISE (XLA is
        deterministic on fixed hardware, so any difference is a
        hardware/memory fault, not noise); (2) FINGERPRINT — the
        on-device bit-content reduction of the post-step params, compared
        across dp-replicated ranks via the coordination layer (replicas
        are bit-identical by construction; a flipped bit in one rank's
        HBM splits the census). Verdicts are combined collectively on
        gangs so every rank takes the same ``log | quarantine | abort``
        action in the same iteration.
        """
        res = self.resilience
        reg = res.registry
        reg.counter("sdc_checks_total").inc()
        _, replay = self._train_step_nodonate(prev_state, sharded)
        evidence = []
        mismatch = False
        for key in ("loss", "grad_norm"):
            if key not in metrics or key not in replay:
                continue
            a = np.asarray(jax.device_get(metrics[key]))
            b = np.asarray(jax.device_get(replay[key]))
            if a.tobytes() != b.tobytes():
                mismatch = True
                evidence.append(f"replay {key}: {a!r} != {b!r}")
        if mismatch:
            reg.counter("sdc_replay_mismatches").inc()
        if gang:
            # collective verdict BEFORE acting: every rank must mirror
            # the action in the same iteration or its peers wedge in
            # their next collective
            if self.coord.any_flag("sdc_replay", mismatch) and not mismatch:
                evidence.append("replay mismatch on a peer rank")
                mismatch = True
        fp_mismatch = False
        if gang:
            fp = int(jax.device_get(self._fingerprint_fn(self.state.params)))
            census = self.coord.all_gather("sdc_fingerprint", fp)
            if len(set(census.values())) > 1:
                fp_mismatch = True
                reg.counter("sdc_fingerprint_mismatches").inc()
                evidence.append(
                    f"cross-replica param fingerprint diverged: {census} "
                    f"(this rank: {fp})")
        if not (mismatch or fp_mismatch):
            return
        flight.note("sdc", "mismatch", step=int(step), evidence=evidence)
        msg = (f"SDC sentinel tripped at step {step}: "
               + "; ".join(evidence))
        if res.sentinel_action == "abort":
            logger.error("%s — aborting (sentinel_action: abort)", msg)
            raise TrainingAborted(msg)
        if res.sentinel_action == "quarantine":
            reg.counter("sdc_quarantines").inc()
            marker = os.path.join(self.output_dir, "sdc_quarantine.json")
            import json

            from fleetx_tpu.resilience.integrity import atomic_write
            os.makedirs(self.output_dir, exist_ok=True)
            atomic_write(marker, lambda f: json.dump(
                {"step": int(step), "rank": int(self.coord.rank),
                 "evidence": evidence,
                 "quarantines": int(reg.counter("sdc_quarantines").value)},
                f))
            logger.error("%s — host quarantined (marker: %s); training "
                         "continues, schedule this host for replacement",
                         msg, marker)
            return
        logger.error("%s — continuing (sentinel_action: log)", msg)

    def _apply_bitflip(self, state: TrainState) -> TrainState:
        """The ``bitflip_param_at`` drill: flip the lowest bit of the
        first element of the first float param leaf — the minimal silent
        HBM-corruption event, staged deterministically so the sentinel's
        detectors can be rehearsed in tests."""
        leaves, treedef = jax.tree.flatten(state.params)
        for i, leaf in enumerate(leaves):
            if not jnp.issubdtype(leaf.dtype, jnp.floating) or leaf.size < 1:
                continue
            host = np.asarray(jax.device_get(leaf)).copy()
            raw = host.reshape(-1).view(np.uint8)
            raw[0] ^= 0x01
            sharding = getattr(leaf, "sharding", None)
            flipped = (jax.device_put(host, sharding)
                       if sharding is not None else jnp.asarray(host))
            logger.warning("fault injection: flipped one bit in param "
                           "leaf %d", i)
            leaves = list(leaves)
            leaves[i] = flipped
            return state.replace(params=jax.tree.unflatten(treedef, leaves))
        logger.warning("fault injection: no float param leaf to bit-flip")
        return state

    # ----------------------------------------------------------------- fit
    def fit(self, train_data_loader: Iterable, valid_data_loader=None,
            epoch_num: int = 1):
        """Train loop (reference ``fit``/``_train_one_epoch``,
        ``eager_engine.py:250-381``) with the resilience runtime wired at
        step boundaries (docs/resilience.md): auto-resume, graceful
        preemption exit, guard rollback-to-last-good, step watchdog and
        deterministic fault injection. All of it is inert when the
        ``Resilience`` block is absent or disabled.
        """
        res = self.resilience
        if res.auto_resume and self.state is None:
            # locate the latest completed checkpoint and rewind the
            # loader's sampler BEFORE the first batch is drawn, so the
            # stream starts at the checkpoint's consumed_samples position
            self._auto_resume_rewind(train_data_loader)
        it = iter(train_data_loader)
        first = self.module.pretreating_batch(next(it))
        self.prepare(first)
        expected = self._resume_expected_consumed
        self._resume_expected_consumed = None
        if expected is not None and self._consumed_samples != expected:
            # the restore's integrity fall-back landed on an OLDER step
            # than auto-resume peeked (a corruption event between the
            # peek and the load, or a peer rank's corrupt shard moving
            # the voted step): the stream was rewound — and the lead
            # batch drawn — at the peeked position, so following it
            # would silently skip the samples between the two steps
            if _rewind_sampler(train_data_loader, self._consumed_samples):
                logger.warning(
                    "auto-resume fall-back: restore landed at "
                    "consumed_samples=%d, not the peeked %d — re-rewinding "
                    "the sampler and re-drawing the lead batch",
                    self._consumed_samples, expected)
                if hasattr(it, "close"):
                    it.close()
                it = iter(train_data_loader)
                first = self.module.pretreating_batch(next(it))
            else:
                # no sampler to reposition and the already-drawn lead
                # batch may be from the wrong position — the operator
                # must re-position the stream; say so loudly rather than
                # silently skipping the samples between the two steps
                logger.error(
                    "auto-resume fall-back: restore landed at "
                    "consumed_samples=%d but the loader has no "
                    "consumed_samples sampler — the stream MUST be "
                    "positioned at global sample %d or already-trained "
                    "data replays / new data is skipped",
                    self._consumed_samples, self._consumed_samples)

        # consumed_samples counts GLOBAL samples (the sampler's unit): the
        # per-host leading dim times the number of hosts
        global_batch = _leading_dim(first) * jax.process_count()
        # model FLOPs per optimizer step for the trace decomposition's
        # roofline: PER-HOST (leading dim, not global_batch) because the
        # profiler trace only carries this host's devices and mfu_gap
        # divides by that count. None for non-LM modules — the report
        # then ranks raw category costs without an ideal-time floor.
        fpt = (self.module.flops_per_token()
               if hasattr(self.module, "flops_per_token") else None)
        tps = getattr(self.module, "tokens_per_sample", None)
        self._perf_flops_per_step = (
            float(fpt) * int(tps) * _leading_dim(first)
            if fpt and tps else None)
        start_step = int(jax.device_get(self.state.step))
        # sample position at fit entry: rollback rewinds relative to this
        # when the loader has no consumed_samples sampler
        base_consumed = self._consumed_samples
        if start_step >= self.max_steps:
            logger.info("checkpoint already at step %d >= max_steps", start_step)
            # pre-agreed: start_step is the restored checkpoint step, which
            # load() takes from a rank-0 broadcast — uniform across ranks
            return  # fleetx: noqa[FX008] -- resume step is gang-agreed
        if self.run_mode == "epoch" and self._start_epoch >= epoch_num:
            logger.info("checkpoint already at epoch %d >= epoch_num %d",
                        self._start_epoch, epoch_num)
            return

        # epoch accounting: the first pass over the loader is the epoch the
        # checkpoint resumed at (meta "epoch"); each loader re-iteration
        # advances it. In "epoch" run_mode, epoch_num bounds the run; in
        # "step" mode (GPT pretrain) the loader loops until max_steps.
        # The generator yields (epoch, batch) and the CONSUMER below owns
        # self._epoch: with the device prefetcher the generator runs up to
        # `depth` batches ahead on the producer thread, and a mid-window
        # save() must not persist an epoch the training loop has not
        # reached. `final_epoch` carries a cleanly-exhausted generator's
        # boundary value (the "run finished N epochs" checkpoint meta).
        self._epoch = self._start_epoch
        final_epoch = [self._start_epoch]

        from fleetx_tpu.data.prefetch import DevicePrefetcher

        def host_batches(lead=None, lead_iter=None, start_index=start_step):
            """(epoch, batch) stream with the fault-injection hook on every
            batch; ``lead``/``lead_iter`` carry the already-drawn first
            batch + live iterator on the initial pass, while a rollback
            restart re-iterates the loader from scratch. ``start_index``
            is the global step the first yielded batch trains at."""
            epoch = self._start_epoch
            index = start_index
            if lead is not None:
                yield epoch, res.faults.on_batch(index, lead)
                index += 1
                src = lead_iter
            else:
                src = iter(train_data_loader)
            for b in src:
                yield epoch, res.faults.on_batch(
                    index, self.module.pretreating_batch(b))
                index += 1
            while True:  # re-iterate epochs over the same loader
                epoch += 1
                final_epoch[0] = epoch
                if self.run_mode == "epoch" and epoch >= epoch_num:
                    return
                got = False
                for b in train_data_loader:
                    got = True
                    yield epoch, res.faults.on_batch(
                        index, self.module.pretreating_batch(b))
                    index += 1
                if not got:  # one-shot iterator exhausted — stop cleanly
                    return

        # holder so ONE cleanup callback covers every pipeline generation
        # (rollback rebuilds it mid-fit); loader_iter is the raw loader
        # iterator feeding the current host generator, closed explicitly on
        # rollback because fit's own reference keeps it alive past a
        # generator close
        holder: dict = {"prefetcher": None, "host_gen": None,
                        "loader_iter": None}

        def wrap_stream(bi, loader_iter=None):
            """Optionally wrap a host stream in the device prefetcher
            (docs/bandwidth_levers.md): a producer thread shards batch N+1
            while step N is in flight; the consumer-side wait is then pure
            input starvation."""
            pf = None
            if self.prefetch_to_device > 0:
                pf = DevicePrefetcher(
                    bi, lambda eb: (eb[0], self.shard_batch(eb[1])),
                    depth=self.prefetch_to_device, obs=self.obs)
            holder["prefetcher"] = pf
            holder["host_gen"] = bi
            holder["loader_iter"] = loader_iter
            return bi, pf

        def close_stream() -> bool:
            """Tear the current input pipeline down DETERMINISTICALLY, in
            dependency order: prefetcher (joins its producer, leaving the
            host generator suspended), then the host generator (its
            GeneratorExit unwinds any loader iterator it created), then
            the raw loader iterator — whose close joins the DataLoader
            producer thread, so afterwards nothing can touch the
            batch_sampler and a rollback may rewind ``consumed_samples``
            without racing a live producer. Returns False when a producer
            join timed out (hung I/O): the generators are then left to GC
            — closing a generator mid-execution on another thread raises —
            and the no-live-producer guarantee does NOT hold."""
            ok = True
            if holder["prefetcher"] is not None:
                ok = holder["prefetcher"].close()
                holder["prefetcher"] = None
                if not ok:
                    logger.error("prefetch producer did not exit within "
                                 "its join timeout — leaving the input "
                                 "pipeline to GC")
            for key in ("host_gen", "loader_iter"):
                stream = holder[key]
                holder[key] = None
                if ok and stream is not None and hasattr(stream, "close"):
                    try:
                        stream.close()
                    except ValueError:  # generator running on a hung thread
                        logger.error("input stream still executing at "
                                     "close — leaving it to GC")
                        ok = False
            return ok

        with self._ctx(), contextlib.ExitStack() as cleanup:
            cleanup.callback(close_stream)

            def _flight_on_crash(exc_type, exc, tb):
                """Dump the flight ring on any abnormal fit exit — the
                per-rank record of what this process was doing in its
                final seconds (``tools/postmortem.py`` merges them).
                ``SystemExit`` is the graceful preemption path, which
                dumps for itself with an honest reason."""
                if exc_type is not None and \
                        not issubclass(exc_type, SystemExit):
                    flight.note("crash", exc_type.__name__,
                                error=str(exc)[:300])
                    flight.dump(f"crash:{exc_type.__name__}")
                return False  # never suppress the exception

            cleanup.push(_flight_on_crash)
            if res.preemption is not None:
                # scoped install: previous SIGTERM/SIGINT handlers restored
                # on every fit exit path
                cleanup.enter_context(res.preemption.installed())

            def _on_stall():
                """Watchdog stall: durable-ize telemetry AND the flight
                ring — a hung run's last evidence before a possible
                action:abort kill."""
                self.obs.flush()
                self.obs.flight_dump("watchdog_stall")

            watchdog = res.make_watchdog(on_stall=_on_stall)
            if watchdog is not None:
                watchdog.start()
                cleanup.callback(watchdog.stop)
            # distributed watchdog mode: a timed gang barrier every K steps
            # whose timeout names the straggler ranks (None off-gang)
            gang_wd = res.make_gang_watchdog(self.coord)
            # collective loop control: with >1 process, a locally-observed
            # event (a signal, a dry data stream) must NOT change control
            # flow unilaterally — the peers would hang in their next
            # collective; every exit happens on an agreed vote
            gang_loop = res.enabled and self.coord.world > 1
            # gang metric aggregation (docs/observability.md "Multi-host"):
            # window snapshots piggyback on the loop-control vote — no new
            # rendezvous — and rank 0 merges them into gang-scoped records
            gang_obs = (gang_loop and self.obs.enabled
                        and self.obs.gang_enabled)
            self._gang_obs_active = gang_obs

            def wd_quiet():
                """Suspend the stall detector around known-long host phases
                (eval / checkpoint / restore) — they are legitimate
                progress-free time, not hung steps."""
                return (watchdog.suspended() if watchdog is not None
                        else contextlib.nullcontext())
            t_last = time.time()
            window = 0
            losses = []
            step = start_step  # host-side mirror of state.step (no per-step sync)
            last_eval = last_save = -1  # fp16 resync can re-visit a step
            self.profiler.arm()  # each fit gets its own trace window
            batch_iter, prefetcher = wrap_stream(
                iter(host_batches(lead=first, lead_iter=it)), loader_iter=it)

            def preemption_exit():
                """Graceful shutdown at a step boundary: emergency
                checkpoint (finalizing any outstanding async save), flush
                telemetry, exit with the configured code."""
                logger.warning("preemption: checkpoint-and-exit at step %d",
                               step)
                if res.preemption_save and self.state is not None:
                    with wd_quiet():
                        self.save()
                        ckpt_lib.finalize_async_saves()
                res.registry.counter("preemption_exits").inc()
                # the one CLEAN dump: a gang post-mortem needs every
                # rank's flight file, survivors included
                flight.note("preemption", "exit", step=int(step))
                self.obs.flight_dump("preemption")
                self.obs.flush()
                raise SystemExit(res.preemption_exit_code)

            def restart_from_last_good():
                """Guard rollback: restore the newest completed checkpoint,
                rewind the data position, rebuild the input pipeline.
                Returns the restored step.

                Gang form: a barrier on entry (no rank starts restoring
                while a peer is still dispatching the abandoned step), the
                rollback step comes from a rank-0 broadcast (divergent
                local views refuse loudly instead of restoring two
                different steps), and a barrier on exit (no rank re-enters
                the train loop before every peer finished restore+rewind).
                """
                self.coord.barrier("rollback_enter")
                ckpt_lib.finalize_async_saves()
                good_local = ckpt_lib.latest_step(self.output_dir)
                good = self.coord.broadcast("rollback_step", good_local)
                if good is None:
                    raise TrainingAborted(
                        f"rollback requested at step {step} but no "
                        f"completed checkpoint under {self.output_dir}"
                        + ("" if good_local is None else
                           f" on rank 0 (this rank has step {good_local} — "
                           f"divergent views, refusing a split rollback)"))
                if good != good_local and \
                        good not in ckpt_lib.completed_steps(self.output_dir):
                    raise TrainingAborted(
                        f"divergent checkpoint views at rollback: rank 0 "
                        f"restores step {good} but this rank's "
                        f"{self.output_dir} lacks it (local latest: "
                        f"{good_local})")
                # tear the whole input pipeline down BEFORE rewinding: the
                # old DataLoader producer must be joined, or its last
                # sampler advance could stomp the rewound consumed_samples.
                # A wedged producer is a RANK-LOCAL fact — vote it (like
                # the rewind-dry case below) so the refusal aborts every
                # rank together instead of stranding healthy peers in
                # 'rollback_exit' until CoordinationTimeout (lint: FX008)
                pipeline_wedged = not close_stream()
                if self.coord.any_flag("rollback_pipeline_wedged",
                                       pipeline_wedged):
                    # a hung producer still owns the sampler — a rewind
                    # now could be silently overwritten; refuse
                    raise TrainingAborted(
                        "rollback aborted: the input pipeline did not shut "
                        "down cleanly" + ("" if pipeline_wedged
                                          else " on a peer rank")
                        + ", the data position cannot be safely rewound")
                self.load(self.output_dir)
                restored = int(jax.device_get(self.state.step))
                skip = 0
                if not _rewind_sampler(train_data_loader,
                                       self._consumed_samples):
                    # no consumed_samples sampler: re-iterate the loader
                    # and skip forward to the restored position (needs a
                    # re-iterable loader — a one-shot iterator is gone)
                    if iter(train_data_loader) is train_data_loader:
                        raise TrainingAborted(
                            "rollback needs a re-iterable data loader or "
                            "a sampler with consumed_samples")
                    skip = max((self._consumed_samples - base_consumed)
                               // global_batch, 0)
                bi = iter(host_batches(start_index=restored - skip))
                # a dry stream here is a RANK-LOCAL fact (each host owns
                # its shard): raising before the exit barrier would leave
                # the healthy peers wedged in 'rollback_exit' until
                # CoordinationTimeout (lint: FX008), so the failure is
                # voted first and every rank aborts together
                rewind_dry = False
                for _ in range(skip):
                    if next(bi, None) is None:
                        rewind_dry = True
                        break
                if self.coord.any_flag("rollback_rewind_dry", rewind_dry):
                    raise TrainingAborted(
                        "data stream exhausted while rewinding for "
                        "rollback" + ("" if rewind_dry
                                      else " on a peer rank"))
                self._epoch = self._start_epoch
                final_epoch[0] = self._start_epoch
                res.registry.counter("rollbacks_total").inc()
                if res.guard is not None:
                    res.guard.note_rollback()
                flight.note("rollback", "restored", step=int(restored))
                logger.warning("rolled back to checkpoint step %d", restored)
                # no rank re-enters the step loop until every peer has
                # finished restore + rewind — an early rank would dispatch
                # a step its peers' state hasn't reached yet
                self.coord.barrier("rollback_exit")
                return wrap_stream(bi), restored

            def fetch_item():
                """One batch from the active source (device prefetcher when
                armed, else the host iterator) under the ``data_fetch``
                span; ``None`` means this rank's stream ran dry. Reads the
                enclosing ``prefetcher``/``batch_iter`` bindings so a
                rollback's pipeline rebuild is picked up transparently."""
                src = prefetcher if prefetcher is not None else batch_iter
                with self.obs.timed_span("data_fetch"):
                    return next(src, None)

            metrics: dict = {}
            vote_round = 0  # iteration counter for gang collectives: the
            # loop ITERATION count is lockstep across ranks by construction,
            # while `step` can diverge under the in-step non-finite skip
            # (a skipped update doesn't advance one rank's counter) — a
            # step-keyed modulo would desynchronize the gang's collectives
            last_save_round = last_eval_round = 0
            stream_done = False  # this rank's stream ran dry (gang mode:
            # awaiting the agreed exit — never a unilateral break)
            vote_every = res.preemption_sync_every
            # SDC sentinel cadence (docs/resilience.md "Integrity"): 0 =
            # off, and the loop below is then byte-identical to the
            # sentinel-less engine (no twin step fn, no extra collectives)
            sent_every = (res.sentinel_every
                          if self._train_step_raw is not None else 0)
            shared_mesh = gang_loop and any(
                d.process_index != jax.process_index()
                for d in np.asarray(self.mesh.devices).flat)
            if gang_loop and (res.guard is not None or gang_wd is not None
                              or sent_every > 0 or shared_mesh):
                # the guard's window vote, the gang watchdog's call
                # counter and the sentinel's replay/fingerprint
                # collectives stay lockstep only while every rank runs
                # every iteration's full body — the control vote must then
                # run every iteration so a rank's exhaustion is agreed
                # BEFORE any same-iteration collective could diverge. A
                # mesh that spans processes forces the same cadence: every
                # train step is a cross-process computation there, so a
                # locally dry rank idling between votes would strand its
                # peers inside the collective
                vote_every = 1
            while True:
                if gang_loop:
                    # the max_steps exit must ALSO be agreed: a rank whose
                    # step counter reaches the target an iteration ahead
                    # of a lagging peer (in-step skip skew) must not
                    # return unilaterally — it idles as "done" until the
                    # gang votes the run over
                    if step >= self.max_steps:
                        stream_done = True
                elif step >= self.max_steps:
                    # single-process arm: gang mode reaches max_steps via
                    # stream_done + the loop-control vote above, never here
                    break  # fleetx: noqa[FX008] -- off-gang arm (LocalCoordinator)
                res.faults.maybe_sigterm(step, start_step=start_step)
                if gang_loop:
                    # fetch BEFORE the control vote so stream exhaustion
                    # is a flag in the SAME iteration's agreement — a rank
                    # leaving the loop unilaterally would wedge every
                    # later collective its peers issue. An agreed exit
                    # discards any fetched-but-untrained batch, which is
                    # safe: consumed_samples advances only on trained
                    # steps, so a resume re-fetches it.
                    item = None
                    if not stream_done:
                        item = fetch_item()
                        if item is None:
                            stream_done = True
                            self._epoch = final_epoch[0]
                    if vote_round % vote_every == 0:
                        # ONE agreement per round carrying every
                        # loop-control flag: any rank's SIGTERM latches
                        # preemption everywhere (the gang emergency-saves
                        # the same step); any rank's dry stream ends the
                        # run everywhere. Gang aggregation piggybacks the
                        # pending window snapshots on the SAME vote — the
                        # cross-rank metric path adds no rendezvous.
                        payload = {"preempt": bool(res.preempted),
                                   "done": stream_done}
                        if gang_obs:
                            payload["obs"] = self.obs.gang_take_pending()
                        votes = self.coord.all_gather("loop_flags", payload)
                        flags = votes.values()
                        if gang_obs and self.coord.rank == 0:
                            # merge BEFORE acting on the flags so the final
                            # windows are emitted even on the exit vote
                            self.obs.gang_merge_emit(votes)
                        if any(f["preempt"] for f in flags):
                            if res.preemption is not None:
                                res.preemption.latch()
                            preemption_exit()
                        if any(f["done"] for f in flags):
                            break
                    vote_round += 1
                    if item is None:
                        # locally dry between votes (sync_every > 1 with
                        # guard/gang-watchdog off): idle in lockstep; the
                        # vote_round-keyed save rendezvous below must
                        # still be matched or the peers' save would wedge
                        # in the two-phase commit barrier
                        if self.save_steps and \
                                vote_round % self.save_steps == 0 and \
                                vote_round != last_save_round:
                            last_save_round = vote_round
                            with wd_quiet():
                                if step == last_save:
                                    # PR 6's acknowledged wart, fixed: the
                                    # state has not changed since this
                                    # rank's last save — match the peers'
                                    # two-phase commit rendezvous with
                                    # ONLY a healthy vote, skipping the
                                    # redundant state write
                                    ckpt_lib.join_commit_vote()  # fleetx: noqa[FX007] -- both arms join the same ckpt_commit rendezvous
                                else:
                                    last_save = step
                                    self.save()  # fleetx: noqa[FX007] -- both arms join the same ckpt_commit rendezvous
                        # idle in lockstep, never a unilateral exit: every
                        # vote and save rendezvous above was matched, and
                        # vote_every is forced to 1 whenever the loop body
                        # has same-iteration collectives (guard/sentinel/
                        # shared mesh), so peers never outpace this rank
                        continue  # fleetx: noqa[FX008] -- idle path matches every rendezvous; exit is voted
                else:
                    if res.preempted:
                        # single-process arm: gang mode latches preemption
                        # through the loop-control vote, never here
                        preemption_exit()  # fleetx: noqa[FX007] -- off-gang arm (LocalCoordinator)
                    item = fetch_item()
                    if item is None:
                        self._epoch = final_epoch[0]
                        # single-process arm: gang mode turns stream
                        # exhaustion into a voted 'done' flag above
                        break  # fleetx: noqa[FX008] -- off-gang arm (LocalCoordinator)
                self._epoch, payload = item
                self.profiler.maybe_start(step)
                if prefetcher is not None:
                    sharded = payload  # already on-device (producer thread)
                else:
                    with self.obs.timed_span("shard_batch"):
                        sharded = self.shard_batch(payload)
                # the span covers dispatch, not device runtime (the step is
                # async); device time shows up in the XLA trace the
                # TraceAnnotation nests under
                # sentinel steps run through the NON-donating twin so the
                # pre-step state survives for the replay; keyed on the
                # lockstep vote_round in gang mode (every rank must join
                # the replay/fingerprint collectives in the same
                # iteration), on the step counter off-gang
                run_sentinel = bool(sent_every) and (
                    (vote_round if gang_loop else step + 1)
                    % sent_every == 0)
                prev_state = self.state if run_sentinel else None
                with self.obs.span("train_step", step=step):
                    # donate_argnums=(0,) deletes the old state's buffers;
                    # the explicit rebind keeps the donated->rebound
                    # ordering visible (the one-line tuple assign was
                    # equally safe — lint: donated-buffer-reuse docs)
                    if run_sentinel:
                        self._ensure_sentinel_fns()
                        new_state, metrics = self._train_step_nodonate(
                            self.state, sharded)
                    else:
                        new_state, metrics = self._train_step(self.state,
                                                              sharded)
                    self.state = new_state
                window += 1
                self._consumed_samples += global_batch
                step += 1
                if watchdog is not None:
                    watchdog.beat(step)
                if gang_wd is not None:
                    # the gang barrier legitimately blocks for up to
                    # gang_timeout_s waiting on a wedged peer — suspend
                    # the LOCAL stall detector so it cannot kill this
                    # healthy rank before the barrier's straggler census
                    # (the whole point of the distributed mode) can fire
                    with wd_quiet():
                        gang_wd.check(step)
                if run_sentinel:
                    # the sentinel's own cost lands in the sdc_sentinel
                    # span (bench.py reports it next to the step time);
                    # the replay is a full step and the gang census can
                    # block on a wedged peer, so the stall detector is
                    # suspended like every other long host phase
                    with self.obs.timed_span("sdc_sentinel"), wd_quiet():
                        self._sdc_check(prev_state, sharded, metrics,  # fleetx: noqa[FX009] -- gang arm keys on lockstep vote_round; the step arm is single-process
                                        step, gang_loop)
                if res.faults.take_bitflip(step):
                    # the silent-HBM-corruption drill: flips a bit AFTER
                    # this iteration's checks, so the NEXT sentinel round
                    # must catch it (cross-replica fingerprint on gangs)
                    self.state = self._apply_bitflip(self.state)
                if window % self.logging_freq == 0:
                    # ONE device->host sync per logging window: fetch the
                    # whole metrics pytree at once and convert on the host,
                    # instead of per-key float() round-trips (lint:
                    # host-sync-in-traced-code's loop-side cousin).
                    # `metrics` stays a device pytree for the profiler sync.
                    host_metrics = jax.device_get(metrics)
                    # resync with the device step counter: under the fp16
                    # scaler (and the guard's in-step skip), non-finite
                    # steps don't advance state.step
                    step = int(host_metrics.get("opt_step", step))
                    now = time.time()
                    cost = (now - t_last) / self.logging_freq
                    t_last = now
                    loss = float(host_metrics["loss"])
                    losses.append(loss)
                    log_dict = {
                        "global_step": step, "epoch": self._epoch,
                        "batch": window,
                        "loss": loss, "train_cost": cost,
                        "global_batch_size": global_batch,
                        "lr": float(host_metrics.get("lr", 0.0)),
                    }
                    self.module.training_step_end(log_dict)
                    self._emit_train_record(log_dict, host_metrics)
                    if res.guard is not None:
                        fin = host_metrics.get("finite")
                        local_decision = res.guard.observe(
                            step, loss,
                            finite=None if fin is None else bool(fin))
                        # collective verdict: any rank's NaN streak rolls
                        # EVERYONE back, any abort aborts all — no rank
                        # takes a recovery action its peers don't mirror
                        # in the same window. Unconditional (the local
                        # coordinator's gather is a no-op) so the verdict
                        # below is an agreement result, provably
                        # gang-uniform — not a rank-local readback
                        # (lint: FX007 rank-taint sanitizer)
                        decision = coordination.most_severe(
                            self.coord.all_gather(
                                "guard_decision", local_decision).values())
                        if decision is not None:
                            flight.note("guard", str(decision),
                                        step=int(step), loss=loss)
                        if decision == "rollback":
                            with wd_quiet():
                                (batch_iter, prefetcher), step = \
                                    restart_from_last_good()
                            if self.logging_freq == 1:
                                # keep the returned curve consistent with
                                # the rewound step counter (exact only at
                                # one window per step)
                                del losses[max(step - start_step, 0):]
                            window = 0
                            t_last = time.time()
                            # the replayed trajectory must re-save/re-eval
                            # at step numbers the abandoned run already
                            # visited — stale markers would suppress them
                            last_eval = last_save = step
                            continue
                        if decision == "abort":
                            raise TrainingAborted(
                                f"training guard abort at step {step} "
                                f"(loss={loss})")
                # profiler stop drains in-flight device work via the step's
                # loss value so the trace tail isn't truncated
                self.profiler.maybe_stop(step, sync=metrics.get("loss"))
                if self.eval_freq and valid_data_loader is not None:
                    if gang_loop:
                        # keyed on vote_round like the save trigger below
                        # and for the same reason: eval is collective work
                        # on a shared mesh, and a step-keyed trigger would
                        # have a skip-lagged rank sit out an eval its
                        # peers enter
                        eval_due = vote_round % self.eval_freq == 0 and \
                            vote_round != last_eval_round
                    else:
                        eval_due = step % self.eval_freq == 0 and \
                            step != last_eval
                else:
                    eval_due = False
                if eval_due:
                    last_eval = step
                    last_eval_round = vote_round
                    with wd_quiet():
                        self.evaluate(valid_data_loader, global_step=step)
                if gang_loop:
                    # keyed on the lockstep iteration counter, NOT `step`:
                    # under the in-step non-finite skip one rank's step
                    # counter can lag its peers', and a step-keyed trigger
                    # would have that rank skip the save while everyone
                    # else wedges in the two-phase commit barrier
                    save_due = bool(self.save_steps) and \
                        vote_round % self.save_steps == 0 and \
                        vote_round != last_save_round
                else:
                    save_due = bool(self.save_steps) and \
                        step % self.save_steps == 0 and step != last_save
                if save_due:
                    last_save = step
                    last_save_round = vote_round
                    with wd_quiet():
                        self.save()  # fleetx: noqa[FX009] -- gang arm keys save_due on lockstep vote_round; the step-keyed arm is single-process
                if self._fault_step and start_step == 0 and \
                        step >= self._fault_step:
                    # fault injection (tests/tools/supervise.py): die hard on
                    # a FRESH run only — a resumed process sails past, which
                    # is exactly the restart-with-resume behaviour under test
                    logger.error("fault injection: dying at step %d", step)
                    os._exit(17)
            self.profiler.stop(sync=metrics.get("loss")
                               if isinstance(metrics, dict) else None)
            ckpt_lib.finalize_async_saves()
            if self.keep_last:
                ckpt_lib.gc_checkpoints(self.output_dir, self.keep_last,
                                        self.keep_every)
            self.obs.flush()
            return losses

    # ------------------------------------------------------------ telemetry
    def _predicted_hbm_bytes(self):
        """``auto_layout``'s per-device HBM prediction for this config, or
        None for modules its first-order GPT-family model cannot describe
        (the monitor then reports measured peaks without a model error)."""
        if not self.cfg.get("Model") or \
                not hasattr(self.module, "flops_per_token"):
            return None
        try:
            from fleetx_tpu.parallel.auto_layout import (
                advice_inputs, predicted_step_bytes)

            data_world = max(int(self.mesh.shape["data"])
                             * int(self.mesh.shape["fsdp"]), 1)
            mdl, mb, gran = advice_inputs(self.cfg, data_world=data_world)
            return predicted_step_bytes(
                mdl, dict(self.cfg.get("Distributed") or {}), mb, gran)
        except Exception as e:  # noqa: BLE001 — advisory, never fatal
            logger.warning("hbm prediction unavailable: %s: %s",
                           type(e).__name__, e)
            return None

    def _on_profiler_stop(self, trace_dir: str) -> None:
        """Decompose the just-closed profiler window (docs/performance.md).

        Installed as ``ProfilerWindow.on_stop``: parses the Chrome trace
        the window dumped, scores it against the calibrated roofline and
        lands the report in the perf stream (``perf.jsonl``), the gauge
        surface and the flight ring — so every profiled fit window yields
        the BENCHMARKS.md-style decomposition mechanically. Best-effort:
        a parse failure logs and training continues.
        """
        obs = self.obs
        if not obs.perf_enabled:
            return
        try:
            from fleetx_tpu.observability import perf
            from fleetx_tpu.utils.hardware import roofline

            rl = roofline(getattr(jax.devices()[0], "device_kind", ""))
            axis_sizes = {str(a): int(s)
                          for a, s in dict(self.mesh.shape).items()
                          if int(s) > 1}
            report = perf.analyze(
                trace_dir, flops_per_step=self._perf_flops_per_step,
                roofline=rl, axis_sizes=axis_sizes or None,
                top_k=obs.perf_top_k)
            if self.mem is not None:
                self.mem.sample("profile_stop")
                report["hbm"] = self.mem.snapshot()
            self._perf_report = report
            obs.emit_perf(report)
            gap = report.get("mfu_gap") or {}
            top = ", ".join(
                f"{c['name']} {c['ms_per_step']:.1f}ms"
                for c in (gap.get("contributors") or [])[:3])
            logger.info("trace decomposition: step %.1f ms, mfu %s — top "
                        "gap: %s", report["step_ms"], gap.get("mfu"), top)
        except Exception as e:  # noqa: BLE001 — telemetry never kills a run
            logger.warning("trace decomposition failed for %s: %s: %s",
                           trace_dir, type(e).__name__, e)

    def _emit_train_record(self, log_dict: dict, metrics: dict) -> None:
        """One machine-readable record per logging window → the sinks.

        The record always carries the schema's required keys
        (``observability/schema.py``): ``tokens_per_sec``/``mfu`` are null
        rather than absent when underivable (non-LM module, unknown chip).
        """
        obs = self.obs
        if not obs.enabled:
            return
        derived = {}
        if obs.derived is not None:
            derived = obs.derived.update(
                log_dict["train_cost"], log_dict["global_batch_size"],
                tokens_per_sample=getattr(self.module, "tokens_per_sample",
                                          None),
                steps_in_window=self.logging_freq,
                stall_seconds_total=obs.stall_seconds_total())
        record = {
            "ts": time.time(),
            "step": int(log_dict["global_step"]),
            "epoch": int(log_dict.get("epoch", 0)),
            "loss": float(log_dict["loss"]),
            "step_time": float(log_dict["train_cost"]),
            "tokens_per_sec": None,
            "mfu": None,
            "lr": float(log_dict.get("lr", 0.0)),
            "global_batch_size": int(log_dict["global_batch_size"]),
            "engine": self._engine_kind,
        }
        record.update(derived)
        if self.mem is not None:
            # steady-state HBM sample once per window: peak/live gauges +
            # the model error riding every record (docs/performance.md)
            self.mem.sample("steady_state")
            record.update(self.mem.record_keys())
        if "grad_norm" in metrics:
            record["grad_norm"] = float(metrics["grad_norm"])
        if "loss_scale" in metrics:
            record["loss_scale"] = float(metrics["loss_scale"])
        if getattr(self, "_gang_obs_active", False):
            # rolling straggler skew (seconds behind the median arrival at
            # coordination rendezvous points) rides every window record
            skew = obs.own_skew()
            if skew is not None:
                record["rank_skew"] = skew
            # queue the window for the next loop-control vote: rank 0
            # merges every rank's snapshots into the gang-scoped stream
            obs.gang_stash(record)
        obs.registry.gauge("loss").set(record["loss"])
        obs.registry.histogram("step_time").record(record["step_time"])
        obs.emit(record)

    # ---------------------------------------------------------------- eval
    def evaluate(self, valid_data_loader: Iterable, global_step: int = 0):
        """Eval loop (reference ``eager_engine.py:447-520``)."""
        assert self.state is not None, "call prepare()/fit() first"
        total, count = 0.0, 0
        t0 = time.time()
        with self._ctx(), self.obs.timed_span("eval",
                                              global_step=int(global_step)):
            for i, batch in enumerate(valid_data_loader):
                if i >= self.eval_iters:
                    break
                batch = self.module.pretreating_batch(batch)
                metrics = jax.device_get(
                    self._eval_step(self.state, self.shard_batch(batch)))
                total += float(metrics["loss"])
                count += 1
        if self.mem is not None:
            self.mem.sample("eval")
        if count:
            self.module.validation_step_end({
                "global_step": global_step, "batch": count,
                "loss": total / count, "eval_cost": (time.time() - t0) / count,
            })
        return total / max(count, 1)

    # ------------------------------------------------------------- predict
    def predict(self, data_loader: Iterable, max_batches: int = 0):
        """Forward-only loop (reference predict, ``eager_engine.py:523-579``):
        returns host arrays of ``module.predict_step`` per batch."""
        assert self.state is not None, "call prepare()/fit() first"
        if getattr(self, "_predict_step", None) is None:
            with self._ctx():
                self._predict_step = jax.jit(
                    lambda state, batch: self.module.predict_step(
                        state.params, batch),
                    in_shardings=(self.state_shardings,
                                  batch_sharding(self.mesh)),
                    out_shardings=None)
        outputs = []
        with self._ctx():
            for i, batch in enumerate(data_loader):
                if max_batches and i >= max_batches:
                    break
                batch = self.module.pretreating_batch(batch)
                out = self._predict_step(self.state, self.shard_batch(batch))
                outputs.append(jax.device_get(out))
        return outputs

    # ------------------------------------------------------------ inference
    def inference(self, data: list):
        """Delegate to the AOT ``InferenceEngine`` (reference
        ``eager_engine.py:671-677``): first call loads ``Inference.model_dir``."""
        if getattr(self, "_inference_engine", None) is None:
            from fleetx_tpu.core.engine.inference_engine import InferenceEngine

            inf = dict(self.cfg.get("Inference") or {})
            self._inference_engine = InferenceEngine(
                inf.get("model_dir", "./exported"))
        return self._inference_engine.predict(data)

    # ---------------------------------------------------------- checkpoints
    def save(self):
        """Save a resumable checkpoint (reference ``eager_engine.py:581-615``)."""
        assert self.state is not None
        step = int(jax.device_get(self.state.step))
        # store the UNboxed tree: partition metadata lives in code, not in the
        # checkpoint, so restores re-shard freely onto any mesh
        # span only: the duration/bytes histograms live in checkpoint.py
        # (ckpt_save/ckpt_bytes), which also covers non-engine callers
        with self.obs.span("checkpoint_save", step=step):
            path = ckpt_lib.save_checkpoint(
                self.output_dir, step, meta.unbox(self.state),
                meta={"consumed_samples": self._consumed_samples,
                      "epoch": getattr(self, "_epoch", self._start_epoch),
                      "seed": self.seed,
                      # spec provenance (parallel/rules.py): both codecs
                      # stamp the registry that sharded this state, so a
                      # restore under drifted rules is visible in the meta
                      "spec_family": self.spec_family,
                      "spec_registry": rules_lib.registry_fingerprint()},
                async_save=self.async_save)
        if self.mem is not None:
            # checkpoint saves materialize host copies / extra buffers —
            # a phase boundary worth its own HBM sample
            self.mem.sample("checkpoint_save")
        if self.keep_last:
            # retention GC considers only COMPLETED step dirs and never
            # prunes the newest one, so an in-flight async save (meta not
            # yet written) is never touched
            ckpt_lib.gc_checkpoints(self.output_dir, self.keep_last,
                                    self.keep_every)
        return path

    def _auto_resume_rewind(self, loader) -> None:
        """Auto-resume orchestration (docs/resilience.md): find the latest
        completed checkpoint, point ``ckpt_dir`` at it so ``prepare()``
        restores it, and rewind the loader's ``consumed_samples`` sampler
        BEFORE the first batch is drawn so the data stream resumes at the
        checkpoint's exact sample position."""
        target = self.ckpt_dir or self.output_dir
        local_meta = ckpt_lib.peek_meta(target) if target else None
        # the resume decision is rank 0's: every host rewinds to the SAME
        # consumed_samples/epoch regardless of what its own directory scan
        # says — a host whose local view disagrees refuses loudly in
        # load() rather than silently training from a different step
        meta_d = self.coord.broadcast("resume_meta", local_meta)
        if not meta_d:
            if local_meta:
                raise RuntimeError(
                    f"divergent checkpoint views: this rank sees step "
                    f"{local_meta.get('step')} under {target} but rank 0 "
                    f"found no completed checkpoint — refusing to resume "
                    f"from two different steps")
            return
        self.ckpt_dir = target
        consumed = int(meta_d.get("consumed_samples", 0))
        self._resume_expected_consumed = consumed
        if _rewind_sampler(loader, consumed):
            logger.info("auto-resume: sampler rewound to "
                        "consumed_samples=%d", consumed)
        elif consumed:
            # without a consumed_samples sampler the data position cannot
            # be verified — the caller must hand a stream already
            # positioned at `consumed` (tools/train.py does), otherwise
            # already-trained data silently replays
            logger.warning(
                "auto-resume: loader has no consumed_samples sampler — "
                "assuming the stream is already positioned at global "
                "sample %d (pass a GPTBatchSampler-style loader for "
                "automatic rewind)", consumed)
        logger.info("auto-resume: restoring step %s from %s",
                    meta_d.get("step"), target)

    def load(self, directory: Optional[str] = None):
        """Restore the latest checkpoint (reference ``eager_engine.py:617-660``).

        Cross-topology: a checkpoint written under a different pipeline
        layout (layer stacks ``[L]`` vs ``[S, L/S]`` vs ``[V, S, L/(V*S)]``)
        is adapted by reshaping leading dims — train with pp, eval without,
        or re-partition stages between runs.

        Multi-host: the restore step comes from a rank-0 broadcast, never
        from each host's own directory scan — hosts whose local view lacks
        the agreed step refuse loudly (divergent storage is an operator
        problem, not something to paper over with per-host guesses), and a
        host with a NEWER local step defers to rank 0 with an error log.

        Integrity fall-back (docs/resilience.md "Integrity"): a step that
        fails digest verification is refused loudly and the NEWEST OLDER
        completed step is tried instead (``ckpt_verify_fallbacks``
        counter), until one verifies or none remain — a byte-corrupted
        latest checkpoint costs one rollback window, never a run trained
        on garbage. On gangs each attempt's verdict is voted, so one
        rank's corrupt shard makes EVERY rank fall back to the same step.
        """
        ckpt_lib.finalize_async_saves()
        directory = directory or self.output_dir
        gang_vote = self.resilience.enabled and self.coord.world > 1
        abstract = jax.tree.map(
            lambda s, x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            self.state_shardings, meta.unbox(jax.eval_shape(lambda: self.state)))
        local = ckpt_lib.latest_step(directory)
        refused: list = []
        while True:
            step = self.coord.broadcast("resume_step", local)
            if step is None:
                if local is not None:
                    raise RuntimeError(
                        f"divergent checkpoint views: this rank has step "
                        f"{local} under {directory} but rank 0 found no "
                        f"completed checkpoint — refusing to resume from "
                        f"two different steps")
                if refused:
                    raise RuntimeError(
                        f"every checkpoint under {directory} failed "
                        f"integrity verification (refused steps: "
                        f"{refused}) — refusing to restore corrupt state")
                logger.info("no checkpoint found under %s", directory)
                return False
            if step != local:
                if step not in ckpt_lib.completed_steps(directory):
                    raise RuntimeError(
                        f"divergent checkpoint views: rank 0 resumes step "
                        f"{step} but this rank's {directory} lacks it "
                        f"(local latest: {local})")
                logger.error("divergent checkpoint views: local latest %s "
                             "!= rank-0 step %d — resuming from the "
                             "rank-0 step", local, step)
            failed_local = False
            try:
                state, meta_d = ckpt_lib.load_checkpoint(
                    directory, step, abstract, adapt_layout=True)
            except ckpt_lib.CheckpointIntegrityError as e:
                failed_local = True
                logger.error("refusing checkpoint step %d: %s", step, e)
            failed = (self.coord.any_flag("restore_verify", failed_local)
                      if gang_vote else failed_local)
            if not failed:
                break
            self.resilience.registry.counter("ckpt_verify_fallbacks").inc()
            refused.append(step)
            logger.warning("falling back past corrupt checkpoint step %d "
                           "to the newest older completed step", step)
            local = max((s for s in ckpt_lib.completed_steps(directory)
                         if s < step), default=None)
        # re-box: restored leaves are raw arrays; re-attach logical metadata
        self.state = jax.tree.map(
            lambda box, leaf: box.replace_boxed(leaf) if isinstance(box, meta.AxisMetadata) else leaf,
            jax.eval_shape(lambda: self.state), state,
            is_leaf=lambda x: isinstance(x, meta.AxisMetadata))
        # layout-adapted leaves come back replicated — re-place on the mesh
        with self._ctx():
            self.state = jax.device_put(self.state, self.state_shardings)
        self._consumed_samples = int(meta_d.get("consumed_samples", 0))
        self._start_epoch = int(meta_d.get("epoch", 0))
        return True


# ------------------------------------------------------------------ helpers

def _rewind_sampler(loader: Any, consumed: int) -> bool:
    """Point a ``consumed_samples`` sampler (the ``GPTBatchSampler``
    protocol, ``data/sampler/batch_sampler.py``) at an absolute global
    sample position; False when the loader carries no such sampler."""
    sampler = getattr(loader, "batch_sampler", None)
    if sampler is not None and hasattr(sampler, "consumed_samples"):
        sampler.consumed_samples = int(consumed)
        return True
    return False


def _host_batch(batch: dict) -> dict:
    return jax.tree.map(np.asarray, batch)


def _leading_dim(batch: dict) -> int:
    return int(jax.tree.leaves(batch)[0].shape[0])


def _tree_of(tree: Any) -> Any:
    return tree


def _param_count(params: Any) -> int:
    return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(meta.unbox(params)))


def _sharded_grad_bytes(params_abs: Any, grad_shardings: Any) -> int:
    """Bytes of gradient leaves whose ZeRO-2 spec carries the fsdp axis —
    the portion of the grad pytree stage 2 distributes (each device saves
    ``(1 - 1/fsdp)`` of this versus replication)."""
    total = 0
    for leaf, sh in zip(jax.tree.leaves(params_abs),
                        jax.tree.leaves(grad_shardings)):
        axes = set()
        for entry in sh.spec:
            for a in (entry if isinstance(entry, (tuple, list)) else (entry,)):
                if a is not None:
                    axes.add(a)
        if "fsdp" in axes:
            total += int(np.prod(leaf.shape)) * leaf.dtype.itemsize
    return total


def _fmt_count(n: int) -> str:
    if n >= 1e9:
        return f"{n / 1e9:.2f}B"
    if n >= 1e6:
        return f"{n / 1e6:.1f}M"
    return str(n)
