"""AOT inference engine — the ``paddle.inference`` analogue.

Reference: ``ppfleetx/core/engine/inference_engine.py:73-197`` loads a
per-rank exported static program, wires an NCCL ring for mp>1, and runs a
predictor handle-by-handle. The TPU equivalent is radically smaller: the
exported artifact is a serialized StableHLO module (``utils/export.py``)
that XLA AOT-compiles once at load; tensor-parallel inference needs no ring
CSV because the module runs under whatever mesh the caller provides.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import numpy as np

from fleetx_tpu.utils.export import load_exported
from fleetx_tpu.utils.log import logger


class InferenceEngine:
    """Runs an exported model directory (reference ``predict``, l.178-197)."""

    def __init__(self, model_dir: str):
        self.model_dir = model_dir
        self.exported, self.params = load_exported(model_dir)
        self._call = jax.jit(self.exported.call)
        logger.info("loaded exported model from %s", model_dir)

    def predict(self, inputs: Sequence[Any]) -> list[np.ndarray]:
        """numpy in → numpy out (reference keeps the same contract)."""
        arrays = [np.asarray(x) for x in inputs]
        out = self._call(self.params, *arrays)
        leaves = jax.tree.leaves(out)
        return [np.asarray(jax.device_get(l)) for l in leaves]
