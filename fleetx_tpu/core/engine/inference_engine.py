"""AOT inference engine — the ``paddle.inference`` analogue.

Reference: ``ppfleetx/core/engine/inference_engine.py:73-197`` loads a
per-rank exported static program, wires an NCCL ring for mp>1, and runs a
predictor handle-by-handle. The TPU equivalent is radically smaller: the
exported artifact is a serialized StableHLO module (``utils/export.py``)
that XLA AOT-compiles once at load. Data-parallel serving (the reference's
``inference_gpt_345M_dp8`` recipe) needs no launch rendezvous: the
single-device module is ``shard_map``-ped over the mesh's batch axes, each
device running its own batch shard — the exported per-call batch size times
the dp degree is the served batch.
"""

from __future__ import annotations

import time
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from fleetx_tpu.observability.metrics import get_registry
from fleetx_tpu.observability.trace import span
from fleetx_tpu.utils.export import load_exported
from fleetx_tpu.utils.log import logger


def serving_mesh(dist_cfg: dict | None):
    """Mesh for distributed serving, or None for the single-device path.

    Gates on the batch-axis product (``dp_degree`` x ``fsdp/sharding``) and
    the tensor axis (``mp_degree`` — the reference's mp-sharded serving,
    ``inference_engine.py:128-163``), matching the axes ``InferenceEngine``
    shards over. Shared by ``tools/inference.py`` and
    ``tasks/gpt/inference.py``.
    """
    dist = dict(dist_cfg or {})
    dp = int(dist.get("dp_degree") or 1)
    fsdp = int(dist.get("fsdp_degree")
               or (dist.get("sharding") or {}).get("sharding_degree") or 1)
    mp = int(dist.get("mp_degree") or 1)
    if dp * fsdp * mp <= 1:
        return None
    from fleetx_tpu.parallel.mesh import build_mesh

    return build_mesh(dist)


class InferenceEngine:
    """Runs an exported model directory (reference ``predict``, l.178-197).

    ``mesh``: optional ``jax.sharding.Mesh``; when its ``data``/``fsdp``
    axes multiply beyond 1 the engine serves data-parallel as above.
    """

    def __init__(self, model_dir: str, mesh=None):
        self.model_dir = model_dir
        self.exported, self.params = load_exported(model_dir)
        self.mesh = mesh
        self._batch_axes = tuple(
            a for a in ("data", "fsdp")
            if mesh is not None and mesh.shape.get(a, 1) > 1)
        self.dp = 1
        for a in self._batch_axes:
            self.dp *= mesh.shape[a]
        self.mp = mesh.shape.get("tensor", 1) if mesh is not None else 1
        if self.mp > 1:
            self._init_tensor_parallel(model_dir)
        self._plain_call = jax.jit(self.exported.call)
        self._sharded_calls: dict = {}  # in_specs signature → jitted shard_map
        # serving telemetry (docs/observability.md): request latencies land
        # in the process registry; p50/p95/p99 via latency_summary()
        self.metrics = get_registry()
        logger.info("loaded exported model from %s (dp=%d, mp=%d)",
                    model_dir, self.dp, self.mp)

    def _init_tensor_parallel(self, model_dir: str):
        """Tensor-parallel serving (reference mp-sharded exports +
        comm-ring CSV, ``inference_engine.py:128-163``): place the params
        onto the mesh by the export's saved logical specs and let GSPMD
        partition the (inlined) StableHLO body — one artifact serves any
        mp degree, no per-rank files, no ring bootstrap."""
        from flax import linen as nn
        from jax.sharding import NamedSharding

        from fleetx_tpu.parallel.rules import SpecLayout
        from fleetx_tpu.utils.export import load_param_specs

        specs = load_param_specs(model_dir)
        if specs is None:
            raise ValueError(
                f"{model_dir} has no param_specs in meta.json — re-export "
                f"with a current tools/export.py to serve tensor-parallel")
        # the export carries LOGICAL axis names; the registry's canonical
        # layout table (parallel/rules.py) maps them to this mesh
        rules = SpecLayout().axis_rules()
        self._param_shardings = jax.tree.map(
            lambda s: NamedSharding(
                self.mesh, nn.logical_to_mesh_axes(s, rules)),
            specs, is_leaf=lambda x: isinstance(x, P))
        self.params = jax.device_put(self.params, self._param_shardings)

    def _spec_for(self, arr: np.ndarray, pos: int) -> P:
        """Batch-carrying inputs (rank >= 2) shard over the batch axes; rank
        0/1 inputs (rng seeds, scalars) replicate. A rank >= 2 input whose
        leading dim does not divide dp is an error, not a silent replicate —
        replication would gather dp duplicated copies."""
        if arr.ndim >= 2:
            if arr.shape[0] % self.dp:
                raise ValueError(
                    f"input {pos}: leading dim {arr.shape[0]} not divisible "
                    f"by dp={self.dp}; dp serving expects "
                    f"exported_batch * dp rows (build the engine without a "
                    f"mesh for single-device calls)")
            return P(self._batch_axes)
        return P()

    def predict(self, inputs: Sequence[Any]) -> list[np.ndarray]:
        """numpy in → numpy out (reference keeps the same contract).

        Batch contract by mesh shape:

        - dp-only mesh: batch-carrying inputs carry ``exported_batch * dp``
          rows (each device runs the exported program on its shard);
        - mp mesh (with or without dp): inputs match the EXPORTED batch
          exactly — GSPMD partitions the one traced program, splitting the
          batch dim across any dp axes and the weights across ``tensor``.

        Outputs with rank >= 2 come back gathered along the batch dim,
        rank 0/1 outputs are taken from one shard.
        """
        t0 = time.perf_counter()
        try:
            with span("inference_predict"):
                out = self._predict(inputs)
        except BaseException:
            # failures must not pollute latency quantiles or flip the warm
            # flag (a failed first call never compiled anything), but they
            # DO count toward the total (error_rate = failed/total)
            self.metrics.counter("requests_total").inc()
            self.metrics.counter("requests_failed_total").inc()
            raise
        # first-call compile time lands in request_compile_latency so
        # steady-state p99s aren't polluted by the one-off trace/compile
        dt = time.perf_counter() - t0
        name = "request_latency" if self._warm else "request_compile_latency"
        self._warm = True
        self.metrics.histogram(name).record(dt)
        self.metrics.counter("requests_total").inc()
        return out

    _warm = False

    def latency_summary(self) -> dict:
        """p50/p95/p99 etc. of warm request latencies (seconds)."""
        return self.metrics.histogram("request_latency").summary()

    def _predict(self, inputs: Sequence[Any]) -> list[np.ndarray]:
        arrays = [np.asarray(x) for x in inputs]
        if self.mp > 1:
            # GSPMD path: the exported module is inlined into the jit, the
            # params arrive tensor-sharded (see _init_tensor_parallel), and
            # XLA inserts the mp collectives.
            from jax.sharding import NamedSharding

            for i, a in enumerate(arrays):
                if a.ndim >= 2 and self.dp > 1 and a.shape[0] % self.dp:
                    raise ValueError(
                        f"input {i}: leading dim {a.shape[0]} not divisible "
                        f"by the mesh's dp={self.dp} (mp serving partitions "
                        f"the exported batch across the data axes)")
            key = tuple((a.shape, str(a.dtype)) for a in arrays)
            fn = self._sharded_calls.get(key)
            if fn is None:
                in_sh = tuple(
                    NamedSharding(self.mesh,
                                  P(self._batch_axes) if a.ndim >= 2 else P())
                    for a in arrays)
                fn = jax.jit(self.exported.call,
                             in_shardings=(self._param_shardings,) + in_sh)
                self._sharded_calls[key] = fn
            with self.mesh:
                out = fn(self.params, *arrays)
        elif self.dp > 1:
            in_specs = (P(),) + tuple(self._spec_for(a, i)
                                      for i, a in enumerate(arrays))
            fn = self._sharded_calls.get(in_specs)
            if fn is None:
                call = self.exported.call
                # out_specs must mirror the output tree: gather rank >= 2
                # leaves over the batch axes, replicate scalars/vectors.
                # eval_shape sees PER-SHARD inputs (the exported module's
                # own avals), not the gathered batch
                shard_avals = [
                    jax.ShapeDtypeStruct(
                        (a.shape[0] // self.dp,) + a.shape[1:], a.dtype)
                    if spec != P() else
                    jax.ShapeDtypeStruct(a.shape, a.dtype)
                    for a, spec in zip(arrays, in_specs[1:])]
                out_tree = jax.eval_shape(call, self.params, *shard_avals)
                out_specs = jax.tree.map(
                    lambda a: P(self._batch_axes) if a.ndim >= 2 else P(),
                    out_tree)
                fn = jax.jit(jax.shard_map(
                    lambda params, *args: call(params, *args),
                    mesh=self.mesh, in_specs=in_specs,
                    out_specs=out_specs, check_vma=False))
                self._sharded_calls[in_specs] = fn
            with self.mesh:
                out = fn(self.params, *arrays)
        else:
            out = self._plain_call(self.params, *arrays)
        # ONE device_get for the whole output tree: per-leaf fetches in a
        # Python loop serialise the host transfers (and their dispatch
        # round-trips); a single call batches them
        return [np.asarray(l) for l in jax.device_get(jax.tree.leaves(out))]
