"""Module protocol — the Lightning-style task abstraction, made functional.

Re-designs ``ppfleetx/core/module/basic_module.py:226-283`` and the GPT glue in
``ppfleetx/models/language_model/language_module.py``. The reference protocol
is stateful (module owns parameters, ``training_step`` mutates); here a module
is a *recipe*: it builds the flax model, initialises parameters, and exposes
pure loss functions the engine can ``jax.value_and_grad`` + ``jit`` over a
mesh. Host-side hooks (``training_step_end`` logging etc.) stay imperative.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from fleetx_tpu.utils.log import logger


class BasicModule:
    """Task protocol consumed by the engine (reference ``basic_module.py:226``).

    Subclasses implement:

    - ``get_model()``          → a flax module
    - ``training_loss(params, batch, rng, step)`` → ``(loss, metrics)`` pure fn
    - ``validation_loss(params, batch)``          → ``(loss, metrics)`` pure fn

    and may override the host-side hooks. ``batch`` is a dict of arrays whose
    leading dim is the (global) batch.
    """

    #: partition-rule registry family (``parallel/rules.py``): subclasses
    #: declare which PARTITION_RULES table shards their parameter tree;
    #: None = unknown (consumers fall back to flax logical metadata with a
    #: warning, and shardcheck refuses the config)
    spec_family: Any = None

    def __init__(self, cfg: Any):
        self.cfg = cfg
        self.model = self.get_model()
        self.nranks = jax.device_count()

    # -- construction --------------------------------------------------------
    def get_model(self):
        raise NotImplementedError

    def init_variables(self, rng: jax.Array, batch: dict) -> Any:
        """Initialise the (logically-annotated) parameter pytree."""
        raise NotImplementedError

    # -- pure functions ------------------------------------------------------
    def training_loss(self, params: Any, batch: dict, rng: jax.Array,
                      step: jax.Array) -> tuple[jax.Array, dict]:
        raise NotImplementedError

    def validation_loss(self, params: Any, batch: dict) -> tuple[jax.Array, dict]:
        raise NotImplementedError

    def predict_step(self, params: Any, batch: dict) -> Any:
        """Pure forward for ``engine.predict`` (reference ``test_step``)."""
        raise NotImplementedError

    # -- host-side hooks (reference basic_module.py:239-283) -----------------
    def pretreating_batch(self, batch: dict) -> dict:
        return batch

    def training_step_end(self, log_dict: dict) -> None:
        logger.info(
            "[train] epoch: %d, batch: %d, loss: %.9f, avg_batch_cost: %.5f sec",
            log_dict.get("epoch", 0), log_dict["batch"], log_dict["loss"],
            log_dict.get("train_cost", 0.0))

    def validation_step_end(self, log_dict: dict) -> None:
        logger.info(
            "[eval] epoch: %d, batch: %d, loss: %.9f, avg_eval_cost: %.5f sec",
            log_dict.get("epoch", 0), log_dict["batch"], log_dict["loss"],
            log_dict.get("eval_cost", 0.0))

    def input_spec(self) -> Any:
        """Abstract input signature for export/AOT (reference ``input_spec``)."""
        return None


class LanguageModule(BasicModule):
    """Shared GPT-family glue (reference ``language_module.py:31-111``):
    token/ips metric lines and the model-size banner."""

    tokens_per_sample: int = 1024

    def flops_per_token(self) -> float | None:
        """fwd+bwd model FLOPs per trained token (for the MFU line)."""
        from fleetx_tpu.utils.hardware import gpt_flops_per_token

        c = getattr(self, "model_cfg", None)
        if c is None:
            return None
        return gpt_flops_per_token(c.num_layers, c.hidden_size,
                                   self.tokens_per_sample,
                                   vocab_size=c.vocab_size)

    def training_step_end(self, log_dict: dict) -> None:
        speed = 1.0 / max(log_dict.get("train_cost", 1e-9), 1e-9)
        default_global_tokens_num = log_dict.get(
            "global_batch_size", log_dict.get("batch_size", 1)) * self.tokens_per_sample
        mfu = ""
        fpt = self.flops_per_token()
        if fpt:
            from fleetx_tpu.utils.hardware import peak_flops

            peak = peak_flops(jax.devices()[0])
            if peak:
                util = (fpt * default_global_tokens_num * speed
                        / (peak * max(self.nranks, 1)))
                mfu = f", mfu: {util:.1%}"
        logger.info(
            "[train] global step %d, epoch: %d, batch: %d, loss: %.9f, "
            "avg_batch_cost: %.5f sec, speed: %.2f step/s, "
            "ips_total: %.0f tokens/s, ips: %.0f tokens/s, learning rate: %.5e%s",
            log_dict["global_step"], log_dict.get("epoch", 0), log_dict["batch"],
            log_dict["loss"], log_dict.get("train_cost", 0.0), speed,
            default_global_tokens_num * speed,
            default_global_tokens_num * speed / max(self.nranks, 1),
            log_dict.get("lr", 0.0), mfu)

    def validation_step_end(self, log_dict: dict) -> None:
        speed = 1.0 / max(log_dict.get("eval_cost", 1e-9), 1e-9)
        logger.info(
            "[eval] step %d, batch: %d, loss: %.9f, avg_eval_cost: %.5f sec, "
            "speed: %.2f step/s",
            log_dict.get("global_step", 0), log_dict["batch"], log_dict["loss"],
            log_dict.get("eval_cost", 0.0), speed)

    @staticmethod
    def model_size(num_layers: int, hidden_size: int, vocab_size: int) -> float:
        """Parameter-count formula in billions (reference
        ``language_module.py:102-105``)."""
        return (num_layers * (12.0 * hidden_size * hidden_size)
                + vocab_size * hidden_size) / 1e9


class GPTModule(LanguageModule):
    """GPT pretraining task (reference ``language_module.py:112-178``)."""

    @property
    def spec_family(self) -> str:
        """``gpt_moe`` when the MLP stack is mixture-of-experts, ``gpt``
        otherwise — the two families carry different MLP rule tables."""
        return "gpt_moe" if self.model_cfg.moe_num_experts > 0 else "gpt"

    def __init__(self, cfg: Any):
        from fleetx_tpu.models.gpt.model import config_from_dict

        model_cfg = dict(cfg.get("Model", cfg)) if isinstance(cfg, dict) else dict(cfg)
        if isinstance(cfg, dict):
            # pipeline topology flows from the Distributed section (reference
            # pp_degree, utils/config.py:30-65); microbatch count from the
            # engine's accumulate_steps (reference pipeline micro-batching,
            # language_module.py:155-161 + config.py:117)
            dist = dict(cfg.get("Distributed") or {})
            eng = dict(cfg.get("Engine") or {})
            pp = int(dist.get("pp_degree") or 1)
            if pp > 1 and not model_cfg.get("pp_degree"):
                model_cfg["pp_degree"] = pp
            vpp = int(dist.get("virtual_pp_degree") or 0)
            if vpp > 1 and not model_cfg.get("virtual_pp_degree"):
                model_cfg["virtual_pp_degree"] = vpp
            if int(model_cfg.get("pp_degree") or 1) > 1 and \
                    not model_cfg.get("pp_microbatches"):
                model_cfg["pp_microbatches"] = int(eng.get("accumulate_steps") or 0)
            # QAT wrap (reference language_module.py:142-144)
            quant = dict(cfg.get("Quantization") or {})
            if quant.get("enable"):
                model_cfg["use_qat"] = True
                if quant.get("weight_bits"):
                    model_cfg["qat_bits"] = int(quant["weight_bits"])
                # activation width may differ from the weight width
                # (reference paddleslim act quant config)
                if quant.get("activation_bits"):
                    model_cfg["qat_act_bits"] = int(quant["activation_bits"])
        self.model_cfg = config_from_dict(model_cfg)
        self.tokens_per_sample = self.model_cfg.max_position_embeddings
        super().__init__(cfg)
        logger.info(
            "GPT model: layers=%d hidden=%d heads=%d vocab=%d (~%.2fB params)",
            self.model_cfg.num_layers, self.model_cfg.hidden_size,
            self.model_cfg.num_attention_heads, self.model_cfg.vocab_size,
            self.model_size(self.model_cfg.num_layers, self.model_cfg.hidden_size,
                            self.model_cfg.vocab_size))

    def get_model(self):
        from fleetx_tpu.models.gpt.model import GPTForPretraining

        return GPTForPretraining(self.model_cfg)

    def init_variables(self, rng: jax.Array, batch: dict) -> Any:
        variables = self.model.init(
            {"params": rng}, batch["tokens"][:1], batch["position_ids"][:1],
            deterministic=True)
        return variables["params"]

    def training_loss(self, params, batch, rng, step):
        from flax.core import meta
        from fleetx_tpu.models.gpt.model import cross_entropy_loss

        dropout_rng = jax.random.fold_in(rng, step)
        variables = {"params": meta.unbox(params)}
        if self.model_cfg.moe_num_experts > 0:
            kwargs = {}
            if self.model_cfg.vocab_chunk:
                # the chunked LM head composes with the MoE aux collection
                kwargs = dict(labels=batch["labels"],
                              loss_mask=batch["loss_mask"])
            out, aux_vars = self.model.apply(
                variables, batch["tokens"], batch["position_ids"],
                deterministic=False, rngs={"dropout": dropout_rng},
                mutable=["losses"], **kwargs)
            loss = (out if self.model_cfg.vocab_chunk else
                    cross_entropy_loss(out, batch["labels"],
                                       batch["loss_mask"]))
            aux = sum(jnp.sum(l) for l in
                      jax.tree.leaves(aux_vars.get("losses", {})))
            if self.model_cfg.pp_degree > 1:
                # the pipeline sows one (bubble-gated) aux value per
                # microbatch per layer; average back to one batch
                # statistic, using the M pipeline_apply actually ran
                from fleetx_tpu.parallel.pipeline import (
                    effective_microbatches)

                aux = aux / effective_microbatches(
                    self.model_cfg.pp_microbatches
                    or self.model_cfg.pp_degree,
                    batch["tokens"].shape[0])
            return loss + aux, {"loss": loss, "moe_aux": aux}
        if self.model_cfg.vocab_chunk:
            # memory-efficient LM head: the model computes the masked loss
            # itself, never materialising [b, s, vocab] logits
            loss = self.model.apply(
                variables, batch["tokens"], batch["position_ids"],
                deterministic=False, rngs={"dropout": dropout_rng},
                labels=batch["labels"], loss_mask=batch["loss_mask"])
            return loss, {"loss": loss}
        logits = self.model.apply(
            variables, batch["tokens"], batch["position_ids"],
            deterministic=False, rngs={"dropout": dropout_rng})
        loss = cross_entropy_loss(logits, batch["labels"], batch["loss_mask"])
        return loss, {"loss": loss}

    def validation_loss(self, params, batch):
        from flax.core import meta
        from fleetx_tpu.models.gpt.model import cross_entropy_loss

        variables = {"params": meta.unbox(params)}
        if self.model_cfg.vocab_chunk:
            loss = self.model.apply(
                variables, batch["tokens"], batch["position_ids"],
                deterministic=True, labels=batch["labels"],
                loss_mask=batch["loss_mask"])
            return loss, {"loss": loss}
        logits = self.model.apply(
            variables, batch["tokens"], batch["position_ids"],
            deterministic=True)
        loss = cross_entropy_loss(logits, batch["labels"], batch["loss_mask"])
        return loss, {"loss": loss}

    def predict_step(self, params, batch):
        """Forward logits (reference ``test_step``/predict loop)."""
        from flax.core import meta

        return self.model.apply(
            {"params": meta.unbox(params)}, batch["tokens"],
            batch.get("position_ids"), deterministic=True)

    def input_spec(self):
        s = self.model_cfg.max_position_embeddings
        return {
            "tokens": jax.ShapeDtypeStruct((1, s), jnp.int32),
            "position_ids": jax.ShapeDtypeStruct((1, s), jnp.int32),
        }


class GPTEvalModule(GPTModule):
    """Offline eval task: WikiText perplexity / LAMBADA accuracy
    (reference ``GPTEvalModule``, ``language_module.py:277-389``)."""

    def __init__(self, cfg: Any):
        ev = dict(cfg.get("Offline_Eval") or {}) if isinstance(cfg, dict) else {}
        self.eval_type = ev.get("eval_type", "ppl")  # ppl | acc
        super().__init__(cfg)

    def batch_metrics(self, params, batch):
        """Pure per-batch sums the host aggregates (jit-able)."""
        from flax.core import meta
        from fleetx_tpu.models.gpt.model import cross_entropy_per_token

        logits = self.model.apply(
            {"params": meta.unbox(params)}, batch["tokens"],
            batch["position_ids"], deterministic=True)
        losses = cross_entropy_per_token(logits, batch["labels"])
        mask = batch["loss_mask"].astype(jnp.float32)
        preds = jnp.argmax(logits, axis=-1)
        tok_correct = jnp.where(mask > 0, preds == batch["labels"], True)
        row_has_target = mask.sum(axis=1) > 0
        row_correct = jnp.all(tok_correct, axis=1) & row_has_target
        return {
            "loss_sum": (losses * mask).sum(),
            "token_count": mask.sum(),
            "correct": row_correct.sum(),
            "rows": row_has_target.sum(),
        }

    def run_offline_eval(self, params, data_loader) -> dict:
        """Aggregate PPL / accuracy over a loader
        (reference ``validation_epoch_end``, ``language_module.py:352-389``)."""
        import numpy as np

        fn = jax.jit(self.batch_metrics)
        totals = {"loss_sum": 0.0, "token_count": 0.0, "correct": 0.0, "rows": 0.0}
        for batch in data_loader:
            out = jax.device_get(fn(params, batch))
            for k in totals:
                totals[k] += float(out[k])
        results: dict = dict(totals)
        if totals["token_count"]:
            avg = totals["loss_sum"] / totals["token_count"]
            results["loss"] = avg
            results["ppl"] = float(np.exp(min(avg, 30.0)))
        if self.eval_type == "acc" and totals["rows"]:
            results["acc"] = totals["correct"] / totals["rows"]
        logger.info("[eval] offline results: %s",
                    {k: round(v, 6) for k, v in results.items()})
        return results


class GPTGenerationModule(GPTModule):
    """Text-generation task (reference ``GPTGenerationModule``,
    ``language_module.py:179-271``): wraps the jitted sampling loop with
    tokenize / left-pad / detokenize host glue."""

    def __init__(self, cfg: Any):
        from fleetx_tpu.models.gpt.generation import GenerationConfig

        gen = dict(cfg.get("Generation") or {}) if isinstance(cfg, dict) else {}
        # reference decode_strategy: "sampling" | "greedy_search" (the
        # reference raises on greedy; here it is supported); the older
        # use_topp_sampling flag is honoured when no strategy is given
        strategy = gen.get("decode_strategy")
        if strategy is not None:
            assert strategy in ("sampling", "greedy_search", "beam_search"), \
                strategy
            do_sample = strategy == "sampling"
        else:
            do_sample = bool(gen.get("use_topp_sampling", True))
        self.use_beam_search = strategy == "beam_search"
        self.gen_cfg = GenerationConfig(
            max_new_tokens=int(gen.get("max_dec_len", 64)),
            min_new_tokens=int(gen.get("min_dec_len", 0)),
            temperature=float(gen.get("temperature", 1.0)),
            top_k=int(gen.get("top_k", 0)),
            top_p=float(gen.get("top_p", 0.0)),
            repetition_penalty=float(gen.get("repetition_penalty", 1.0)),
            do_sample=do_sample,
            num_return_sequences=int(gen.get("num_return_sequences", 1)),
            eos_token_id=int(gen.get("eos_token_id", 50256)),
            pad_token_id=int(gen.get("pad_token_id", 50256)),
            # diverse beam knobs (reference hybrid_model.py:990-1004)
            num_beams=int(gen.get("num_beams", 1)),
            num_beam_groups=int(gen.get("num_beam_groups", 1)),
            diversity_rate=float(gen.get("diversity_rate", 0.0)),
            length_penalty=float(gen.get("length_penalty", 0.0)),
        )
        if self.use_beam_search:
            assert self.gen_cfg.num_return_sequences <= self.gen_cfg.num_beams
        self.tokenizer = None
        super().__init__(cfg)

    def generate_ids(self, params: Any, prompts: list, rng: jax.Array):
        """prompts: list of token-id lists →
        ``[len(prompts) * num_return_sequences, max_new_tokens]`` numpy,
        prompt-major (rows ``i*n .. i*n+n-1`` continue prompt ``i``)."""
        from flax.core import meta
        from fleetx_tpu.models.gpt import generation as G

        tokens, mask = G.left_pad(prompts, self.gen_cfg.pad_token_id)
        if getattr(self, "use_beam_search", False):
            seqs, _ = G.beam_search(self.model, meta.unbox(params),
                                    self.gen_cfg, jnp.asarray(tokens),
                                    jnp.asarray(mask))
            # beams come back best-first per prompt: keep the top
            # num_return_sequences rows of each prompt's num_beams block
            nb, nr = self.gen_cfg.num_beams, self.gen_cfg.num_return_sequences
            seqs = seqs.reshape(len(prompts), nb, -1)[:, :nr]
            return jax.device_get(seqs.reshape(len(prompts) * nr, -1))
        out = G.generate(self.model, meta.unbox(params), self.gen_cfg,
                         jnp.asarray(tokens), jnp.asarray(mask), rng)
        return jax.device_get(out)

    def generate(self, params: Any, texts: list[str], rng: jax.Array) -> list[str]:
        assert self.tokenizer is not None, "set module.tokenizer first"
        prompts = [self.tokenizer.encode(t) for t in texts]
        out = self.generate_ids(params, prompts, rng)
        eos = self.gen_cfg.eos_token_id
        results = []
        for row in out:
            ids = [int(t) for t in row]
            if eos in ids:
                ids = ids[:ids.index(eos)]
            results.append(self.tokenizer.decode(ids))
        return results
