"""Shared AST analyses: import aliasing, traced-function discovery, taint.

Three facts every tracing rule needs:

1. *which functions are traced* — decorated with ``@jax.jit``/``@pjit`` (bare
   or through ``functools.partial``), passed by name to a ``jax.jit(...)``
   call (the engine idiom: ``self._train_step = jax.jit(train_step, ...,
   donate_argnums=(0,))``), or lexically nested inside such a function;
2. *which names hold traced values* inside one — a fixpoint taint walk from
   the non-static parameters through assignments, where shape/dtype/ndim
   reads and ``len``/``isinstance`` neutralise the taint (branching on a
   shape is static and fine; branching on a value is not);
3. *what a dotted callee resolves to* under the module's imports, so
   ``jr.normal`` / ``from jax import random`` / ``np.asarray`` all normalise
   to canonical ``jax.random.normal`` / ``numpy.asarray`` names.

Scope note (docs/static_analysis.md): analysis is intra-procedural.  A
helper called *from* a jitted function but defined elsewhere is not analysed
— the rules catch the directly-jitted surface, which in this codebase is
where every historical host-sync/branch bug has lived.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator, Optional

#: attribute reads that yield static (trace-time) values, not traced arrays
STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "sharding", "aval",
                "weak_type"}

#: builtins whose result is static regardless of argument taint
NEUTRAL_CALLS = {"len", "isinstance", "type", "getattr", "hasattr", "id",
                 "repr", "str", "format"}

#: dotted names that mean "jit this function"
JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit",
             "jax.experimental.pjit.pjit"}

PARTIAL_NAMES = {"partial", "functools.partial"}


def module_aliases(module) -> dict[str, str]:
    """:func:`import_aliases` cached on the SourceModule (immutable AST)."""
    cached = getattr(module, "_lint_aliases", None)
    if cached is None:
        cached = module._lint_aliases = import_aliases(module.tree)
    return cached


def module_traced(module) -> list["TracedFn"]:
    """:func:`traced_functions` cached on the SourceModule, so FX001/FX005
    (and anything else) share one discovery walk per file."""
    cached = getattr(module, "_lint_traced", None)
    if cached is None:
        cached = module._lint_traced = traced_functions(
            module.tree, module_aliases(module))
    return cached


def fn_taints(tf: "TracedFn") -> set[str]:
    """:func:`tainted_names` cached on the TracedFn (shared across rules)."""
    cached = getattr(tf, "_taints", None)
    if cached is None:
        cached = tf._taints = tainted_names(tf)
    return cached


def import_aliases(tree: ast.AST) -> dict[str, str]:
    """Local name → canonical dotted path for every import in the module."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    aliases[a.asname] = a.name
                else:
                    head = a.name.split(".")[0]
                    aliases[head] = head
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve(node: ast.AST, aliases: dict[str, str]) -> Optional[str]:
    """Dotted path with the leading segment rewritten through the imports."""
    path = dotted(node)
    if path is None:
        return None
    head, _, rest = path.partition(".")
    base = aliases.get(head, head)
    return f"{base}.{rest}" if rest else base


@dataclasses.dataclass
class TracedFn:
    """One function the linter believes XLA traces."""

    node: ast.AST  # FunctionDef | AsyncFunctionDef
    static_params: frozenset = frozenset()
    #: how it became traced: "decorator" | "jit-call" | "nested"
    via: str = "decorator"
    #: for jit-call form: the Assign target expression (e.g. "self._train_step")
    bound_to: Optional[str] = None
    #: donated positional indices from donate_argnums, if any
    donate: tuple = ()

    @property
    def params(self) -> list[str]:
        """All parameter names, in declaration order."""
        a = self.node.args
        return [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]


def _literal_ints(node: ast.AST) -> tuple:
    """A literal int / tuple-of-ints, else ()."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, int)):
                return ()
            out.append(e.value)
        return tuple(out)
    return ()


def _literal_strs(node: ast.AST) -> tuple:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant) and isinstance(e.value, str))
    return ()


def _static_from_kwargs(call: ast.Call, fn: ast.AST) -> frozenset:
    """Parameter names made static by static_argnums/static_argnames."""
    params = [p.arg for p in (*fn.args.posonlyargs, *fn.args.args)]
    static: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for i in _literal_ints(kw.value):
                if 0 <= i < len(params):
                    static.add(params[i])
        elif kw.arg == "static_argnames":
            static.update(_literal_strs(kw.value))
    return frozenset(static)


def _positional_params(fn: ast.AST) -> list[str]:
    """Positional parameter names of a def or lambda."""
    a = fn.args
    return [p.arg for p in (*a.posonlyargs, *a.args)]


def _donate_from_kwargs(call: ast.Call,
                        params: Optional[list] = None) -> tuple:
    """Donated positions from donate_argnums and — when the jitted
    function's signature is visible — donate_argnames."""
    nums: list[int] = []
    names: tuple = ()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            nums.extend(_literal_ints(kw.value))
        elif kw.arg == "donate_argnames":
            names = _literal_strs(kw.value)
    if names and params:
        nums.extend(params.index(n) for n in names if n in params)
    return tuple(sorted(set(nums)))


def _jit_decorator(dec: ast.AST, aliases: dict[str, str],
                   fn: ast.AST) -> Optional[TracedFn]:
    """``@jax.jit`` / ``@partial(jax.jit, static_argnums=...)`` forms."""
    if resolve(dec, aliases) in JIT_NAMES:
        return TracedFn(node=fn, via="decorator")
    if isinstance(dec, ast.Call):
        target = resolve(dec.func, aliases)
        if target in JIT_NAMES:
            return TracedFn(node=fn, via="decorator",
                            static_params=_static_from_kwargs(dec, fn),
                            donate=_donate_from_kwargs(
                                dec, _positional_params(fn)))
        if target in PARTIAL_NAMES and dec.args and \
                resolve(dec.args[0], aliases) in JIT_NAMES:
            return TracedFn(node=fn, via="decorator",
                            static_params=_static_from_kwargs(dec, fn),
                            donate=_donate_from_kwargs(
                                dec, _positional_params(fn)))
    return None


def traced_functions(tree: ast.AST,
                     aliases: dict[str, str]) -> list[TracedFn]:
    """Every function the module traces, with static/donate metadata."""
    defs_by_name: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name[node.name] = node

    traced: dict[int, TracedFn] = {}

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                tf = _jit_decorator(dec, aliases, node)
                if tf is not None:
                    traced[id(node)] = tf
                    break

    # jit-call form: fn passed by name to jax.jit(...), result possibly bound
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and resolve(node.func, aliases) in JIT_NAMES and node.args):
            continue
        head = node.args[0]
        if not isinstance(head, ast.Name) or head.id not in defs_by_name:
            continue
        fn = defs_by_name[head.id]
        traced[id(fn)] = TracedFn(
            node=fn, via="jit-call",
            static_params=_static_from_kwargs(node, fn),
            donate=_donate_from_kwargs(node, _positional_params(fn)))

    # lexically nested defs inherit traced-ness (their params are traced
    # values flowing in from the enclosing trace)
    out = list(traced.values())
    for tf in list(out):
        for inner in ast.walk(tf.node):
            if inner is tf.node or id(inner) in traced:
                continue
            if isinstance(inner, (ast.FunctionDef, ast.AsyncFunctionDef)):
                traced[id(inner)] = TracedFn(node=inner, via="nested")
                out.append(traced[id(inner)])
    return out


def donated_bindings(tree: ast.AST,
                     aliases: dict[str, str]) -> dict[str, tuple]:
    """Callable-expression string → donated positions, for jit-with-donation.

    Covers the two repo idioms::

        self._train_step = jax.jit(train_step, ..., donate_argnums=(0,))
        @partial(jax.jit, donate_argnums=(0,))
        def step(state, batch): ...
    """
    defs_by_name: dict[str, ast.AST] = {
        n.name: n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    bindings: dict[str, tuple] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            if resolve(call.func, aliases) in JIT_NAMES:
                params = None
                if call.args:
                    head = call.args[0]
                    if isinstance(head, ast.Lambda):
                        params = _positional_params(head)
                    elif isinstance(head, ast.Name) and \
                            head.id in defs_by_name:
                        params = _positional_params(defs_by_name[head.id])
                donate = _donate_from_kwargs(call, params)
                if donate and len(node.targets) == 1:
                    key = ast.unparse(node.targets[0])
                    bindings[key] = donate
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                tf = _jit_decorator(dec, aliases, node)
                if tf is not None and tf.donate:
                    bindings[node.name] = tf.donate
    return bindings


# ------------------------------------------------------------------- taint

def own_statements(fn: ast.AST) -> Iterator[ast.stmt]:
    """Statements of ``fn`` recursively, NOT descending into nested defs."""
    yield from own_statements_of_body(fn.body)


def own_statements_of_body(body: list) -> Iterator[ast.stmt]:
    """:func:`own_statements` over a bare statement list (loop bodies)."""
    stack: list[ast.stmt] = list(body)
    while stack:
        stmt = stack.pop(0)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield stmt
        for field in ("body", "orelse", "finalbody"):
            stack.extend(getattr(stmt, field, []) or [])
        for handler in getattr(stmt, "handlers", []) or []:
            stack.extend(handler.body)


def statement_exprs(stmt: ast.stmt) -> Iterator[ast.expr]:
    """Direct expression children of a statement (no nested statements).

    Pairs with :func:`own_statements`: walking each yielded statement's own
    expressions visits every expression of a function exactly once.
    """
    for _, value in ast.iter_fields(stmt):
        if isinstance(value, ast.expr):
            yield value
        elif isinstance(value, list):
            for item in value:
                if isinstance(item, ast.expr):
                    yield item
                elif isinstance(item, (ast.withitem, ast.keyword)):
                    yield from (v for _, v in ast.iter_fields(item)
                                if isinstance(v, ast.expr))


def walk_exprs(expr: ast.expr) -> Iterator[ast.AST]:
    """``ast.walk`` over an expression, not descending into lambda bodies."""
    stack: list[ast.AST] = [expr]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.Lambda):
                continue
            stack.append(child)


def expr_taints(node: ast.AST, tainted: set[str]) -> bool:
    """Does evaluating ``node`` touch a traced *value* (not just metadata)?"""
    if isinstance(node, ast.Name):
        return node.id in tainted
    if isinstance(node, ast.Attribute):
        if node.attr in STATIC_ATTRS:
            return False
        return expr_taints(node.value, tainted)
    if isinstance(node, ast.Call):
        fname = node.func
        if isinstance(fname, ast.Name) and fname.id in NEUTRAL_CALLS:
            return False
        parts = [*node.args, *(kw.value for kw in node.keywords)]
        if isinstance(fname, ast.Attribute):
            parts.append(fname.value)
        return any(expr_taints(p, tainted) for p in parts)
    if isinstance(node, ast.Starred):
        return expr_taints(node.value, tainted)
    if isinstance(node, (ast.Constant, ast.Lambda)):
        return False
    return any(expr_taints(child, tainted)
               for child in ast.iter_child_nodes(node)
               if isinstance(child, ast.expr))


def target_names(target: ast.AST) -> Iterator[str]:
    """Simple names bound by an assignment target (tuples flattened)."""
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for e in target.elts:
            yield from target_names(e)
    elif isinstance(target, ast.Starred):
        yield from target_names(target.value)


def tainted_names(tf: TracedFn) -> set[str]:
    """Fixpoint of names holding traced values inside one traced function."""
    tainted = set(tf.params) - set(tf.static_params)
    changed = True
    while changed:
        changed = False
        for stmt in own_statements(tf.node):
            targets: list[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            elif isinstance(stmt, ast.AugAssign):
                targets, value = [stmt.target], stmt.value
            elif isinstance(stmt, ast.For):
                targets, value = [stmt.target], stmt.iter
            if value is not None and expr_taints(value, tainted):
                for name in target_names(targets[0] if len(targets) == 1
                                         else ast.Tuple(elts=targets)):
                    if name not in tainted:
                        tainted.add(name)
                        changed = True
    return tainted
