"""Content-fingerprint result cache — the grown repo lints in seconds.

Two granularities, matching the two rule scopes in ``core.Rule``:

- **module-scope** rules (FX001-FX005, FX010, FX013, docstrings) depend
  only on one file's text plus a small stable context (FX004's mesh
  axes).  Their findings are cached per
  ``(relpath, sha1(text), rule, context_key)``.
- **project-scope** rules (FX006-FX009, FX011/FX012, FX014-FX016) read
  cross-file state — the config zoo, the call graph over ``fleetx_tpu/``
  + ``tools/`` + ``tasks/``.  Their findings are cached against
  ``Rule.project_digest`` — the whole-project content digest by default
  (any file change re-runs them), or a narrower dependency fingerprint:
  the expensive shardcheck audit keys on registry + models + configs
  (``lint/rules/sharding.py``) so unrelated code edits keep it warm, and
  the thread rules key on the call-graph fingerprint — every scanned /
  context python file, config zoo excluded (``lint/rules/threads.py::
  callgraph_fingerprint``) — so a cross-file edit that moves a helper
  under a lock invalidates correctly while YAML-only edits stay warm.

Cached findings are raw: fingerprints, ``noqa`` suppression and baseline
filtering are recomputed on every run (they read current line text), so a
stale suppression can never hide behind the cache.  The cache file itself
is versioned and silently discarded on any mismatch or decode error —
a corrupt cache costs one cold run, never a wrong result.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path
from typing import List, Optional

from fleetx_tpu.lint.core import Finding

#: bump on FORMAT changes; rule-SEMANTICS changes are handled automatically
#: by :func:`linter_fingerprint` below
CACHE_VERSION = 2


def linter_fingerprint() -> str:
    """Content hash of the linter's own source (``fleetx_tpu/lint/**``).

    Folded into the cache validity check so editing a rule implementation
    invalidates every stored result automatically — without this, a
    module-scope entry keyed only on the TARGET file's sha would keep
    serving pre-edit findings and the whole-repo gate would pass on stale
    results.  Cached on first call (the file set is fixed per process).
    """
    global _LINTER_FP
    if _LINTER_FP is None:
        h = hashlib.sha1()
        pkg = Path(__file__).resolve().parent
        for f in sorted(pkg.rglob("*.py")):
            try:
                payload = f.read_bytes()
            except OSError:
                continue
            h.update(f"{f.relative_to(pkg).as_posix()}\0".encode("utf-8"))
            h.update(hashlib.sha1(payload).digest())
        _LINTER_FP = h.hexdigest()
    return _LINTER_FP


_LINTER_FP: Optional[str] = None

_FIELDS = ("rule", "code", "path", "line", "col", "message")


def _encode(findings: List[Finding]) -> list:
    return [{k: getattr(f, k) for k in _FIELDS} for f in findings]


def _decode(raw: list) -> Optional[List[Finding]]:
    out = []
    try:
        for d in raw:
            out.append(Finding(**{k: d[k] for k in _FIELDS}))
    except (KeyError, TypeError):
        return None
    return out


class ParseCache:
    """JSON-backed finding cache (best-effort: I/O errors degrade to a
    cold run, they never fail the lint)."""

    def __init__(self, path):
        self.path = Path(path)
        self._modules: dict = {}
        self._project: dict = {}
        self._dirty = False
        self.hits = 0
        self.misses = 0
        try:
            data = json.loads(self.path.read_text(encoding="utf-8"))
            if data.get("version") == CACHE_VERSION and \
                    data.get("linter") == linter_fingerprint() and \
                    isinstance(data.get("modules"), dict) and \
                    isinstance(data.get("project"), dict):
                self._modules = data["modules"]
                self._project = data["project"]
        except (OSError, ValueError):
            pass

    # ------------------------------------------------------------- modules
    def get_module(self, relpath: str, sha1: str, rule: str,
                   context_key: str) -> Optional[List[Finding]]:
        """Cached findings of one module-scope rule on one file, or None
        when the content/context fingerprint no longer matches."""
        entry = self._modules.get(f"{relpath}::{rule}")
        if not entry or entry.get("key") != f"{sha1}|{context_key}":
            self.misses += 1
            return None
        decoded = _decode(entry.get("findings", []))
        if decoded is None:
            self.misses += 1
            return None
        self.hits += 1
        return decoded

    def put_module(self, relpath: str, sha1: str, rule: str,
                   context_key: str, findings: List[Finding]) -> None:
        """Store one (file, rule) result under its content fingerprint."""
        self._modules[f"{relpath}::{rule}"] = {
            "key": f"{sha1}|{context_key}", "findings": _encode(findings)}
        self._dirty = True

    # ------------------------------------------------------------- project
    def get_project(self, rule: str,
                    digest: str) -> Optional[List[Finding]]:
        """Cached findings of one project-scope rule, or None when the
        whole-project digest changed."""
        entry = self._project.get(rule)
        if not entry or entry.get("key") != digest:
            self.misses += 1
            return None
        decoded = _decode(entry.get("findings", []))
        if decoded is None:
            self.misses += 1
            return None
        self.hits += 1
        return decoded

    def put_project(self, rule: str, digest: str,
                    findings: List[Finding]) -> None:
        """Store one project-scope rule result under the project digest."""
        self._project[rule] = {"key": digest, "findings": _encode(findings)}
        self._dirty = True

    # --------------------------------------------------------------- flush
    def save(self) -> None:
        if not self._dirty:
            return
        payload = {"version": CACHE_VERSION, "linter": linter_fingerprint(),
                   "modules": self._modules, "project": self._project}
        try:
            tmp = self.path.with_suffix(self.path.suffix + ".tmp")
            tmp.write_text(json.dumps(payload), encoding="utf-8")
            tmp.replace(self.path)
        except OSError:
            pass
