"""Rule registry, project model, suppression and baseline machinery.

Design (mirrors the shape of ``observability/metrics.py``'s registry): rules
are singletons registered by name; a :class:`Project` is built once per run
and carries every cross-file fact a rule may need (mesh axis declarations,
YAML config keys, the code-side consumption set); :func:`run_lint` applies
per-module and project-wide rules, then filters findings through per-line
``# fleetx: noqa[rule]`` suppressions and an optional baseline file.

The baseline exists so a new rule can land with a legacy backlog without
blocking CI: fingerprints are content-based (path + rule + source-line text +
occurrence index), so unrelated edits above a finding do not invalidate it,
while touching the flagged line itself forces a re-triage.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import re
from pathlib import Path
from typing import Any, Iterable, Iterator, Optional

NOQA_RE = re.compile(r"#\s*fleetx:\s*noqa(?:\[(?P<rules>[^\]]*)\])?", re.I)

#: directories (relative to the project root) whose python files define the
#: config-consumption surface even when they are not being linted themselves
CONSUMER_DIRS = ("fleetx_tpu", "tools", "tasks")

#: directories holding the YAML config zoo checked by dead-config-key
CONFIG_DIRS = ("fleetx_tpu/configs", "projects")


def iter_context_files(root: Path) -> Iterator[Path]:
    """Every python file under the cross-file context dirs.

    This is THE shared surface: ``Project.consumer_trees`` (FX006's
    consumption set), ``Project.digest`` (project-rule cache invalidation)
    and the dataflow call graph all iterate exactly this — keeping the
    walks structurally identical is what makes the digest's "covers
    everything the cross-file rules read" claim true by construction.
    """
    for d in CONSUMER_DIRS:
        base = Path(root) / d
        if base.is_dir():
            yield from sorted(base.rglob("*.py"))


@dataclasses.dataclass
class Finding:
    """One diagnostic: a rule, a location, and a message."""

    rule: str
    code: str
    path: str  # posix path relative to the project root
    line: int
    col: int
    message: str
    fingerprint: str = ""

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class Rule:
    """Base rule: override ``check_module`` and/or ``check_project``.

    ``category`` groups rules for selection (``--select docstrings``); the
    six TPU-semantic rules use ``lint``, the docstring rules ``docstrings``.
    """

    name: str = ""
    code: str = ""
    category: str = "lint"
    description: str = ""
    #: True for rules that read the YAML config zoo (affects the file count)
    scans_configs: bool = False
    #: "module" — findings depend on one file (+ ``context_key``), cached
    #: per file; "project" — findings read cross-file state (config zoo,
    #: call graph), cached against the whole-project digest
    scope: str = "module"

    def check_module(self, module: "SourceModule",
                     project: "Project") -> Iterable[Finding]:
        return ()

    def check_project(self, project: "Project") -> Iterable[Finding]:
        return ()

    def context_key(self, project: "Project") -> str:
        """Extra cache discriminator for module-scope rules whose result
        also depends on a stable project fact (FX004: the mesh axes)."""
        return ""

    def project_digest(self, project: "Project") -> str:
        """Cache key for project-scope results. Defaults to the
        whole-project digest (any byte change re-runs); rules whose
        dependency set is narrower and expensive to recompute (the
        shardcheck audit: registry + models + configs) override this so
        unrelated code edits keep their cached result warm."""
        return project.digest()

    def finding(self, path: str, line: int, col: int, message: str) -> Finding:
        return Finding(rule=self.name, code=self.code, path=path,
                       line=max(int(line), 1), col=int(col), message=message)


_REGISTRY: dict[str, Rule] = {}


def register(cls: type) -> type:
    """Class decorator: instantiate and register a rule by its name."""
    rule = cls()
    assert rule.name and rule.code, f"rule {cls.__name__} lacks name/code"
    assert rule.name not in _REGISTRY, f"duplicate rule {rule.name}"
    _REGISTRY[rule.name] = rule
    return cls


def all_rules() -> dict[str, Rule]:
    """Name → rule for every registered rule (imports the rule modules)."""
    import fleetx_tpu.lint.rules  # noqa: F401 — registration side effect

    return dict(sorted(_REGISTRY.items(), key=lambda kv: kv[1].code))


def resolve_rules(select: Iterable[str] | None = None,
                  skip: Iterable[str] | None = None) -> list[Rule]:
    """Resolve ``--select``/``--skip`` tokens (rule name, code, or category)."""
    rules = all_rules()

    def matches(rule: Rule, token: str) -> bool:
        return token in (rule.name, rule.code, rule.category)

    def validate(tokens: list) -> list:
        unknown = [t for t in tokens
                   if not any(matches(r, t) for r in rules.values())]
        if unknown:
            raise KeyError(f"unknown rule/category selector(s): {unknown}")
        return tokens

    out = list(rules.values())
    if select:
        tokens = validate(list(select))
        out = [r for r in out if any(matches(r, t) for t in tokens)]
    if skip:
        tokens = validate(list(skip))
        out = [r for r in out if not any(matches(r, t) for t in tokens)]
    return out


class SourceModule:
    """One parsed python file (path, text, lines, AST)."""

    def __init__(self, path: Path, relpath: str, text: str):
        self.path = path
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text)  # SyntaxError handled by the runner
        self._sha1: Optional[str] = None

    @property
    def sha1(self) -> str:
        """Content fingerprint (drives the parse/result cache)."""
        if self._sha1 is None:
            self._sha1 = hashlib.sha1(
                self.text.encode("utf-8")).hexdigest()
        return self._sha1


class Project:
    """Cross-file context: scanned modules + repo-level facts for rules."""

    def __init__(self, root: Path, scan_paths: list[Path]):
        self.root = root.resolve()
        self.scan_paths = [p.resolve() for p in scan_paths]
        self.modules: list[SourceModule] = []
        self.broken: list[Finding] = []  # syntax errors surfaced as findings
        self.config_paths: list[Path] = []
        self._lines_cache: dict[str, list[str]] = {}
        self._mesh_axes: Optional[tuple] = None
        self._logical_axes: Optional[tuple] = None
        self._digest: Optional[str] = None
        self._collect()

    # ------------------------------------------------------------ collection
    def relpath(self, path: Path) -> str:
        try:
            return path.resolve().relative_to(self.root).as_posix()
        except ValueError:
            return path.as_posix()

    def _collect(self) -> None:
        py_files: list[Path] = []
        yaml_files: list[Path] = []
        for p in self.scan_paths:
            if p.is_dir():
                py_files.extend(sorted(p.rglob("*.py")))
                yaml_files.extend(sorted(p.rglob("*.yaml")))
                yaml_files.extend(sorted(p.rglob("*.yml")))
            elif p.suffix == ".py":
                py_files.append(p)
            elif p.suffix in (".yaml", ".yml"):
                yaml_files.append(p)
        seen = set()
        for f in py_files:
            rel = self.relpath(f)
            if rel in seen:
                continue
            seen.add(rel)
            try:
                text = f.read_text(encoding="utf-8")
                self.modules.append(SourceModule(f, rel, text))
            except SyntaxError as e:
                self.broken.append(Finding(
                    rule="syntax-error", code="FX000", path=rel,
                    line=int(e.lineno or 1), col=int(e.offset or 0),
                    message=f"syntax error: {e.msg}"))
            except UnicodeDecodeError:
                self.broken.append(Finding(
                    rule="syntax-error", code="FX000", path=rel,
                    line=1, col=0, message="file is not valid UTF-8"))
            except ValueError as e:  # e.g. null bytes reach ast.parse
                self.broken.append(Finding(
                    rule="syntax-error", code="FX000", path=rel,
                    line=1, col=0, message=f"unparseable source: {e}"))
            except OSError:
                continue
        self.config_paths = sorted(set(yaml_files))

    # ---------------------------------------------------------- shared facts
    def line(self, relpath: str, lineno: int) -> str:
        """Physical source line (1-indexed) of any file under the root."""
        lines = self._lines_cache.get(relpath)
        if lines is None:
            try:
                lines = (self.root / relpath).read_text(
                    encoding="utf-8").splitlines()
            except (OSError, UnicodeDecodeError):
                lines = []
            self._lines_cache[relpath] = lines
        if 1 <= lineno <= len(lines):
            return lines[lineno - 1]
        return ""

    def _declared_tuple(self, varname: str,
                        relpaths: tuple) -> Optional[tuple]:
        """Statically parse ``VARNAME = ("...", ...)`` from the first of
        ``relpaths`` that declares it — linting never imports jax."""
        for rel in relpaths:
            src = self.root / rel
            if not src.exists():
                continue
            try:
                tree = ast.parse(src.read_text(encoding="utf-8"))
            except (SyntaxError, OSError):
                continue
            for node in tree.body:
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == varname
                        for t in node.targets):
                    val = node.value
                    if isinstance(val, (ast.Tuple, ast.List)):
                        names = [e.value for e in val.elts
                                 if isinstance(e, ast.Constant)
                                 and isinstance(e.value, str)]
                        if names:
                            return tuple(names)
        return None

    def mesh_axes(self) -> tuple:
        """Mesh axis names — ONE source for lint and runtime alike: the
        partition-rule registry's ``MESH_AXES`` literal
        (``fleetx_tpu/parallel/rules.py``; ``parallel/mesh.py`` imports
        it from there, and is kept as a parse fallback for fixture
        projects that predate the registry). Falls back to the canonical
        five axes when neither file is present.
        """
        if self._mesh_axes is not None:
            return self._mesh_axes
        default = ("pipe", "data", "fsdp", "seq", "tensor")
        axes = self._declared_tuple(
            "MESH_AXES", ("fleetx_tpu/parallel/rules.py",
                          "fleetx_tpu/parallel/mesh.py"))
        self._mesh_axes = axes or default
        return self._mesh_axes

    def logical_axes(self) -> tuple:
        """Logical axis vocabulary declared by the registry
        (``parallel/rules.py LOGICAL_AXES``) — FX013 uses it to recognise
        hand-wired rule tables; the canonical vocabulary is the fallback
        for fixture projects (same convention as :meth:`mesh_axes`).
        Memoized like ``mesh_axes`` — FX013 reads it per scanned file."""
        if self._logical_axes is not None:
            return self._logical_axes
        default = ("batch", "vocab", "mlp", "heads", "kv", "layers",
                   "pipe_stage", "pipe_repeat", "act_stage", "norm",
                   "embed", "act_seq", "act_embed", "act_heads", "act_kv",
                   "act_vocab", "expert", "act_expert", "kv_pages",
                   "page_slot")
        self._logical_axes = self._declared_tuple(
            "LOGICAL_AXES", ("fleetx_tpu/parallel/rules.py",)) or default
        return self._logical_axes

    def config_files(self) -> list[Path]:
        """YAML files in scope: the config zoo dirs plus any scanned YAML."""
        out = dict.fromkeys(self.config_paths)
        for d in CONFIG_DIRS:
            base = self.root / d
            if base.is_dir():
                for f in sorted(base.rglob("*.yaml")):
                    out.setdefault(f.resolve())
        return list(out)

    def consumer_trees(self) -> Iterator[ast.AST]:
        """ASTs of every python file that may consume config keys."""
        seen: set[str] = set()
        for m in self.modules:
            seen.add(m.relpath)
            yield m.tree
        for f in iter_context_files(self.root):
            rel = self.relpath(f)
            if rel in seen:
                continue
            seen.add(rel)
            try:
                yield ast.parse(f.read_text(encoding="utf-8"))
            except (SyntaxError, OSError):
                continue

    def digest(self) -> str:
        """Whole-project content fingerprint for project-scope rule caching.

        Covers the scanned modules, every python file a project-scope rule
        may read for cross-file context (``CONSUMER_DIRS`` — the same
        surface the call graph and the config-consumption set are built
        from) and the YAML config zoo; any byte change anywhere in that
        set invalidates every project-scope cache entry.
        """
        if self._digest is not None:
            return self._digest
        h = hashlib.sha1()
        seen: set[str] = set()
        for m in self.modules:
            seen.add(m.relpath)
            h.update(f"{m.relpath}\0{m.sha1}\0".encode("utf-8"))
        extras: list[Path] = list(iter_context_files(self.root))
        extras.extend(self.config_files())
        for f in extras:
            rel = self.relpath(f)
            if rel in seen:
                continue
            seen.add(rel)
            try:
                payload = f.read_bytes()
            except OSError:
                continue
            h.update(f"{rel}\0".encode("utf-8"))
            h.update(hashlib.sha1(payload).digest())
        self._digest = h.hexdigest()
        return self._digest


@dataclasses.dataclass
class LintResult:
    """Outcome of one run: active findings plus suppression accounting."""

    findings: list[Finding]
    suppressed: list[Finding]
    baselined: list[Finding]
    rules: list[str]
    files: int

    @property
    def clean(self) -> bool:
        return not self.findings


# --------------------------------------------------------------- suppression

def _noqa_suppresses(line: str, finding: Finding) -> bool:
    m = NOQA_RE.search(line)
    if not m:
        return False
    rules = m.group("rules")
    if rules is None:
        return True  # bare "fleetx: noqa" silences every rule on the line
    tokens = {t.strip() for t in rules.split(",") if t.strip()}
    return finding.rule in tokens or finding.code in tokens


def fingerprint_findings(findings: list[Finding], project: Project) -> None:
    """Content-based fingerprints: stable under line-number drift."""
    counts: dict[tuple, int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col)):
        text = project.line(f.path, f.line).strip()
        key = (f.path, f.rule, text)
        idx = counts.get(key, 0)
        counts[key] = idx + 1
        raw = f"{f.path}::{f.rule}::{text}::{idx}"
        f.fingerprint = hashlib.sha1(raw.encode("utf-8")).hexdigest()[:16]


def load_baseline(path: Path) -> set[str]:
    """Fingerprints accepted by a baseline file (missing file → empty)."""
    if not path.exists():
        return set()
    with open(path) as fh:
        data = json.load(fh)
    return {str(fp) for fp in data.get("findings", {})}


def write_baseline(path: Path, findings: list[Finding]) -> None:
    """Persist current findings as the accepted backlog."""
    payload = {
        "version": 1,
        "comment": "accepted legacy findings — regenerate with "
                   "`python tools/lint.py --write-baseline`",
        "findings": {
            f.fingerprint: {"rule": f.rule, "path": f.path, "line": f.line,
                            "message": f.message}
            for f in sorted(findings, key=lambda f: (f.path, f.line, f.col))
        },
    }
    with open(path, "w") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
        fh.write("\n")


# --------------------------------------------------------------------- runner

def run_lint(paths: Iterable[Any], root: Any = None,
             select: Iterable[str] | None = None,
             skip: Iterable[str] | None = None,
             baseline_path: Any = None,
             cache_path: Any = None,
             only_paths: Iterable[str] | None = None) -> LintResult:
    """Lint ``paths`` and return the filtered result.

    ``root`` anchors cross-file facts (mesh axes, config zoo, consumption
    set); it defaults to the common parent of ``paths`` so fixture projects
    in a tmp dir are self-contained.  ``cache_path`` enables the
    content-fingerprint result cache (``lint/cache.py``).  ``only_paths``
    restricts *reported* findings to those relpaths while the full scan
    still provides cross-file context (the ``--changed-only`` contract).
    """
    path_objs = [Path(p) for p in paths]
    if root is None:
        root = _common_root(path_objs)
    project = Project(Path(root), path_objs)
    rules = resolve_rules(select, skip)

    cache = None
    if cache_path is not None:
        from fleetx_tpu.lint.cache import ParseCache

        cache = ParseCache(cache_path)

    findings: list[Finding] = list(project.broken)
    for rule in rules:
        findings.extend(_run_rule(rule, project, cache))
    if cache is not None:
        cache.save()
    fingerprint_findings(findings, project)

    accepted = load_baseline(Path(baseline_path)) if baseline_path else set()
    active: list[Finding] = []
    suppressed: list[Finding] = []
    baselined: list[Finding] = []
    for f in findings:
        if _noqa_suppresses(project.line(f.path, f.line), f):
            suppressed.append(f)
        elif f.fingerprint in accepted:
            baselined.append(f)
        else:
            active.append(f)
    if only_paths is not None:
        keep = set(only_paths)
        active = [f for f in active if f.path in keep]
        suppressed = [f for f in suppressed if f.path in keep]
        baselined = [f for f in baselined if f.path in keep]
    active.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    # config files count as "checked" only when a config-reading rule ran
    n_configs = (len(project.config_files())
                 if any(r.scans_configs for r in rules) else 0)
    return LintResult(findings=active, suppressed=suppressed,
                      baselined=baselined, rules=[r.name for r in rules],
                      files=len(project.modules) + n_configs)


def _run_rule(rule: Rule, project: Project, cache) -> list[Finding]:
    """One rule over the project, through the result cache when enabled."""
    if cache is None:
        out = list(rule.check_project(project))
        for module in project.modules:
            out.extend(rule.check_module(module, project))
        return out
    if rule.scope == "project":
        digest = f"{rule.project_digest(project)}|{rule.context_key(project)}"
        cached = cache.get_project(rule.name, digest)
        if cached is not None:
            return cached
        out = list(rule.check_project(project))
        for module in project.modules:
            out.extend(rule.check_module(module, project))
        cache.put_project(rule.name, digest, out)
        return out
    out = list(rule.check_project(project))
    ctx = rule.context_key(project)
    for module in project.modules:
        cached = cache.get_module(module.relpath, module.sha1,
                                  rule.name, ctx)
        if cached is not None:
            out.extend(cached)
            continue
        got = list(rule.check_module(module, project))
        cache.put_module(module.relpath, module.sha1, rule.name, ctx, got)
        out.extend(got)
    return out


def _common_root(paths: list[Path]) -> Path:
    resolved = [p.resolve() for p in paths] or [Path.cwd()]
    common = resolved[0] if resolved[0].is_dir() else resolved[0].parent
    for p in resolved[1:]:
        p = p if p.is_dir() else p.parent
        while common not in (p, *p.parents):
            common = common.parent
    # a run over fleetx_tpu/ should still see tools/ + configs at the repo
    # root: hop up while the chosen root looks like a package subdir
    if (common / "__init__.py").exists():
        while (common / "__init__.py").exists():
            common = common.parent
    return common
