"""Tracing-semantics rules: host syncs and Python control flow under jit.

Both rules share the traced-function discovery and taint analysis in
``lint/analysis.py``.  The failure modes they target are the two that the
pjit scaling papers (PAPERS.md) call the dominant silent-slowdown class:

- a ``.item()``/``float()``/``print`` on a traced value either fails at
  trace time (``ConcretizationTypeError``) or — worse, on a re-trace path —
  forces a device→host transfer every step;
- a Python ``if``/``while`` on a traced value triggers per-branch re-tracing
  (or a trace error), where ``jnp.where``/``lax.cond``/``lax.while_loop``
  keeps control flow on-device.
"""

from __future__ import annotations

import ast
from typing import Iterable

from fleetx_tpu.lint import analysis
from fleetx_tpu.lint.core import Finding, Project, Rule, SourceModule, register

#: numpy call names that materialise a host array from a traced value
_NUMPY_MATERIALIZERS = {"asarray", "array", "copy"}

#: python builtins that force a concrete scalar
_SCALAR_BUILTINS = {"float", "int", "bool", "complex"}


def _own_calls(tf: analysis.TracedFn) -> Iterable[ast.Call]:
    for stmt in analysis.own_statements(tf.node):
        for expr in analysis.statement_exprs(stmt):
            for node in analysis.walk_exprs(expr):
                if isinstance(node, ast.Call):
                    yield node


@register
class HostSyncInTracedCode(Rule):
    """Device→host syncs inside jitted/pjitted functions."""

    name = "host-sync-in-traced-code"
    code = "FX001"
    description = (".item()/float()/np.asarray/jax.device_get/print on a "
                   "traced value inside a jitted function")

    def check_module(self, module: SourceModule,
                     project: Project) -> Iterable[Finding]:
        aliases = analysis.module_aliases(module)
        out: list[Finding] = []
        for tf in analysis.module_traced(module):
            tainted = analysis.fn_taints(tf)
            for call in _own_calls(tf):
                msg = self._diagnose(call, tainted, aliases)
                if msg:
                    out.append(self.finding(module.relpath, call.lineno,
                                            call.col_offset, msg))
        return out

    def _diagnose(self, call: ast.Call, tainted: set,
                  aliases: dict) -> str | None:
        func = call.func
        if isinstance(func, ast.Attribute) and func.attr in ("item", "tolist"):
            if not call.args and analysis.expr_taints(func.value, tainted):
                return (f"'.{func.attr}()' on a traced value forces a "
                        "device->host sync inside a jitted function")
        if isinstance(func, ast.Name) and func.id in _SCALAR_BUILTINS:
            if len(call.args) == 1 and \
                    analysis.expr_taints(call.args[0], tainted):
                return (f"'{func.id}()' concretises a traced value (host "
                        "sync / ConcretizationTypeError) — keep it a jnp "
                        "array or move the conversion outside jit")
        resolved = analysis.resolve(func, aliases)
        if resolved and resolved.startswith("numpy."):
            tail = resolved.rsplit(".", 1)[-1]
            if tail in _NUMPY_MATERIALIZERS and any(
                    analysis.expr_taints(a, tainted) for a in call.args):
                return (f"'{resolved}' materialises a traced value on the "
                        "host inside a jitted function — use jnp instead")
        if resolved == "jax.device_get" and any(
                analysis.expr_taints(a, tainted) for a in call.args):
            return ("'jax.device_get' on a traced value inside a jitted "
                    "function is a host sync — return the value instead")
        if isinstance(func, ast.Name) and func.id == "print":
            if any(analysis.expr_taints(a, tainted) for a in call.args):
                return ("'print' of a traced value prints a tracer (and "
                        "pins a host sync on concrete re-runs) — use "
                        "jax.debug.print")
        return None


@register
class TracedPythonBranch(Rule):
    """Python ``if``/``while`` on values derived from traced parameters."""

    name = "traced-python-branch"
    code = "FX005"
    description = ("Python control flow on a traced value re-traces per "
                   "branch — use jnp.where/lax.cond/lax.while_loop")

    def check_module(self, module: SourceModule,
                     project: Project) -> Iterable[Finding]:
        out: list[Finding] = []
        for tf in analysis.module_traced(module):
            tainted = analysis.fn_taints(tf)
            for stmt in analysis.own_statements(tf.node):
                if isinstance(stmt, (ast.If, ast.While)) and \
                        analysis.expr_taints(stmt.test, tainted):
                    kind = "if" if isinstance(stmt, ast.If) else "while"
                    fix = ("jnp.where/jax.lax.cond" if kind == "if"
                           else "jax.lax.while_loop/jax.lax.fori_loop")
                    out.append(self.finding(
                        module.relpath, stmt.lineno, stmt.col_offset,
                        f"Python '{kind}' on a traced value inside a jitted "
                        f"function (re-traces per branch, or fails on "
                        f"abstract values) — use {fix}"))
        return out
