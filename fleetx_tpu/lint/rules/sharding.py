"""Shardcheck rules: the partition-rule registry audited statically.

Three rules close the loop the registry (``parallel/rules.py``) opened —
sharding specs are data, so a static pass can verify them on CPU CI
instead of a jit bind discovering drift minutes into a pod compile:

- **FX011 shard-rule-coverage** (project scope): derives every YAML-zoo
  config's abstract parameter tree with ``jax.eval_shape`` (shape-level,
  no FLOPs — ``parallel/shardcheck.py``) and flags leaves no rule
  matches, leaves matched by conflicting rules, rule templates that
  cannot apply (rank mismatch / unknown logical axis), oversized
  fully-replicated leaves (the forgotten-spec hazard) and configs that
  cannot be audited at all.
- **FX012 shard-rule-health** (project scope): dead rules (no audited
  config of the family ever matches them — anchored to the pattern's
  line in ``parallel/rules.py``), families no zoo config exercises, and
  sharded dims not divisible by their mesh degree for a config's
  declared layout.
- **FX013 hand-wired-spec-table** (module scope, pure AST): a partition
  rule table (name→spec pairs) or a ``PartitionSpec`` built from literal
  mesh-axis names OUTSIDE ``parallel/rules.py`` — the drift the registry
  exists to end. Zero-baseline enforced like every other rule.

FX011/FX012 are the only rules that import jax (lazily, inside
``check_project``); their result cache is keyed on the registry + model +
config fingerprints (:func:`audit_fingerprint`, stdlib-only), so a warm
``tools/lint.py`` run with an unrelated code edit never pays the jax
import, while editing the registry, a model, or a config re-audits.
Projects without ``fleetx_tpu/parallel/rules.py`` (lint fixture trees)
are skipped entirely.
"""

from __future__ import annotations

import ast
import hashlib
import os
from typing import Iterable, Optional

from fleetx_tpu.lint import analysis
from fleetx_tpu.lint.core import Finding, Project, Rule, SourceModule, register

_RULES_RELPATH = "fleetx_tpu/parallel/rules.py"

#: what the audit's result depends on — mirrored by the audit driver's
#: imports (parallel/shardcheck.py reads the registry, builds the models
#: via core/module.py + models/** [+ ops/** QAT wrappers], derives the
#: serving pool shapes from serving/paged_cache.init_pool, and loads the
#: zoo through utils/config.parse_config); kept HERE because the
#: fingerprint must be computable without importing jax (a warm cache hit
#: must stay instant)
_FINGERPRINT_FILES = (_RULES_RELPATH,
                      "fleetx_tpu/parallel/shardcheck.py",
                      "fleetx_tpu/core/module.py",
                      "fleetx_tpu/serving/paged_cache.py",
                      "fleetx_tpu/utils/config.py")
_FINGERPRINT_DIRS = ("fleetx_tpu/models", "fleetx_tpu/ops",
                     "fleetx_tpu/configs", "projects")

_PSPEC_NAMES = {"jax.sharding.PartitionSpec",
                "jax.interpreters.pxla.PartitionSpec",
                "jax.experimental.pjit.PartitionSpec",
                "PartitionSpec"}

#: tools/shardcheck.py's positional-config restriction — lives HERE (not
#: in parallel/shardcheck.py) so reading it never imports jax; folded
#: into the FX011/FX012 cache keys via context_key. Dead-rule accounting
#: is skipped under a filter (a partial zoo cannot prove a rule dead).
_config_filter: Optional[tuple] = None


def set_config_filter(paths: Optional[Iterable[str]]) -> None:
    """Restrict FX011/FX012 to specific config files (None = whole zoo)."""
    global _config_filter
    _config_filter = tuple(sorted(paths)) if paths else None


def get_config_filter() -> Optional[tuple]:
    """The active config restriction (see :func:`set_config_filter`)."""
    return _config_filter


def audit_fingerprint(root) -> str:
    """Content hash of the shardcheck dependency set (stdlib walk)."""
    h = hashlib.sha1()

    def feed(relpath: str) -> None:
        try:
            with open(os.path.join(str(root), relpath), "rb") as f:
                payload = f.read()
        except OSError:
            return
        h.update(relpath.encode("utf-8") + b"\0")
        h.update(hashlib.sha1(payload).digest())

    for rel in _FINGERPRINT_FILES:
        feed(rel)
    for d in _FINGERPRINT_DIRS:
        base = os.path.join(str(root), d)
        for dirpath, _, names in sorted(os.walk(base)):
            for name in sorted(names):
                if name.endswith((".py", ".yaml", ".yml")):
                    feed(os.path.relpath(os.path.join(dirpath, name),
                                         str(root)).replace(os.sep, "/"))
    return h.hexdigest()


def _zoo_report(project: Project) -> Optional[dict]:
    """The shared zoo audit, computed once per Project (FX011 and FX012
    both read it). None when this tree carries no registry (fixtures) or
    the audit stack cannot import; import failure is reported by FX011."""
    cached = getattr(project, "_shardcheck_report", False)
    if cached is not False:
        return cached
    report: Optional[dict] = None
    if (project.root / _RULES_RELPATH).exists():
        try:
            from fleetx_tpu.parallel import shardcheck

            report = shardcheck.audit_zoo(str(project.root),
                                          only=_config_filter)
        except Exception as e:  # noqa: BLE001 — surfaced as a finding
            report = {"issues": [{
                "kind": "audit-error", "family": "?", "leaf": "",
                "config": _RULES_RELPATH,
                "message": f"shardcheck audit could not run: "
                           f"{type(e).__name__}: {e}"}],
                "dead_rules": [], "configs": 0, "families": {}}
    project._shardcheck_report = report
    return report


def _pattern_line(project: Project, pattern: str,
                  family: str = "") -> int:
    """Line of a rule's regex literal inside parallel/rules.py (1 when it
    cannot be located — e.g. a pattern built at runtime).

    A pattern literal can occur more than once (a rule inlined in one
    family's ``PARTITION_RULES`` entry and repeated in a shared
    ``_GPT_*`` table), so occurrences INSIDE the family's own
    ``"family": (...)`` span win; rules the family pulls in from a shared
    table fall back to the first (shared-table) occurrence — which is
    where that rule actually lives."""
    if not pattern:
        return 1
    try:
        text = (project.root / _RULES_RELPATH).read_text(encoding="utf-8")
    except OSError:
        return 1
    lines = text.splitlines()
    hits = [i for i, line in enumerate(lines, start=1) if pattern in line]
    if not hits:
        return 1
    if family and len(hits) > 1:
        start = next((i for i, line in enumerate(lines, start=1)
                      if f'"{family}":' in line), None)
        if start is not None:
            end = next((i for i, line in enumerate(
                lines[start:], start=start + 1)
                if line.strip().startswith('"') and '": ' in line),
                len(lines) + 1)
            in_span = [h for h in hits if start <= h < end]
            if in_span:
                return in_span[0]
    return hits[0]


@register
class ShardRuleCoverage(Rule):
    """Every zoo config's param tree fully + unambiguously matched."""

    name = "shard-rule-coverage"
    code = "FX011"
    category = "shardcheck"
    description = ("model leaf unmatched/ambiguous/oversized-replicated "
                   "under the partition-rule registry (parallel/rules.py) "
                   "for a YAML-zoo config")
    scope = "project"
    scans_configs = True

    KINDS = ("unmatched", "ambiguous", "rank-mismatch", "unknown-axis",
             "replicated-large", "audit-error")

    def context_key(self, project: Project) -> str:
        return repr(_config_filter)

    def project_digest(self, project: Project) -> str:
        return audit_fingerprint(project.root)

    def check_project(self, project: Project) -> Iterable[Finding]:
        report = _zoo_report(project)
        if report is None:
            return
        for issue in report["issues"]:
            if issue["kind"] not in self.KINDS:
                continue
            yield self.finding(
                issue.get("config", _RULES_RELPATH), 1, 0,
                f"[{issue['kind']}] {issue['message']} (consumers: "
                f"engine prepare, zero_grad_specs, both checkpoint "
                f"codecs, auto_layout resolve this leaf through the "
                f"registry)")


@register
class ShardRuleHealth(Rule):
    """No dead rules; sharded dims divide their mesh degrees."""

    name = "shard-rule-health"
    code = "FX012"
    category = "shardcheck"
    description = ("dead partition rule, unexercised family, or sharded "
                   "dim not divisible by its mesh degree for a config's "
                   "layout")
    scope = "project"
    scans_configs = True

    def context_key(self, project: Project) -> str:
        return repr(_config_filter)

    def project_digest(self, project: Project) -> str:
        return audit_fingerprint(project.root)

    def check_project(self, project: Project) -> Iterable[Finding]:
        report = _zoo_report(project)
        if report is None:
            return
        for issue in report["issues"]:
            if issue["kind"] != "indivisible":
                continue
            yield self.finding(issue.get("config", _RULES_RELPATH), 1, 0,
                               f"[indivisible] {issue['message']}")
        for dead in report["dead_rules"]:
            yield self.finding(
                _RULES_RELPATH,
                _pattern_line(project, dead["pattern"],
                              family=dead.get("family", "")), 0,
                f"[dead-rule] {dead['message']}")


@register
class HandWiredSpecTable(Rule):
    """Partition tables / literal-axis PartitionSpecs outside rules.py."""

    name = "hand-wired-spec-table"
    code = "FX013"
    description = ("hand-wired partition table or PartitionSpec with "
                   "literal mesh axes outside parallel/rules.py — the "
                   "registry is the single spec source")

    def context_key(self, project: Project) -> str:
        return ",".join(project.mesh_axes()) + "|" + \
            ",".join(project.logical_axes())

    # ---------------------------------------------------------------- helpers
    @staticmethod
    def _axis_strings(node: ast.AST) -> Iterable[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node.value
        elif isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                yield from HandWiredSpecTable._axis_strings(e)

    def _is_rule_pair(self, node: ast.AST, axes: set, aliases) -> bool:
        """A ``("name-ish", spec-ish)`` 2-tuple: the shape of one rule."""
        if not isinstance(node, (ast.Tuple, ast.List)) or len(node.elts) != 2:
            return False
        first, second = node.elts
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            return False
        if isinstance(second, ast.Call) and \
                analysis.resolve(second.func, aliases) in _PSPEC_NAMES:
            return True
        return any(s in axes for s in self._axis_strings(second))

    def check_module(self, module: SourceModule,
                     project: Project) -> Iterable[Finding]:
        if module.relpath.replace(os.sep, "/").endswith(
                "parallel/rules.py"):
            return ()
        aliases = analysis.module_aliases(module)
        mesh_axes = set(project.mesh_axes())
        axes = mesh_axes | set(project.logical_axes())
        out: list[Finding] = []
        table_lines: set[int] = set()
        for node in ast.walk(module.tree):
            # (a) a rule TABLE: >= 2 (name, spec) pairs in one literal
            if isinstance(node, (ast.Tuple, ast.List)) and \
                    len(node.elts) >= 2 and all(
                        self._is_rule_pair(e, axes, aliases)
                        for e in node.elts):
                table_lines.add(node.lineno)
                out.append(self.finding(
                    module.relpath, node.lineno, node.col_offset,
                    "hand-wired partition-rule table — spec tables live "
                    "in parallel/rules.py PARTITION_RULES (one source for "
                    "engine, ZeRO, checkpoints, auto_layout and "
                    "shardcheck); matching by name here WILL drift"))
        for node in ast.walk(module.tree):
            # (b) a PartitionSpec built from literal MESH axis names —
            # activation constraints go through logical names + the
            # registry layout table, params through PARTITION_RULES
            if not isinstance(node, ast.Call):
                continue
            if analysis.resolve(node.func, aliases) not in _PSPEC_NAMES:
                continue
            if node.lineno in table_lines:
                continue  # already reported as part of the table
            args = list(node.args) + [kw.value for kw in node.keywords]
            literal = [s for a in args for s in self._axis_strings(a)
                       if s in mesh_axes]
            if literal:
                out.append(self.finding(
                    module.relpath, node.lineno, node.col_offset,
                    f"PartitionSpec with literal mesh axes {literal} "
                    f"outside parallel/rules.py — resolve through the "
                    f"registry (registry_specs/kv_pool_spec/batch_spec) "
                    f"so shardcheck can audit it"))
        return out
