"""dead-config-key: YAML keys no code consumes, and code sections no YAML
provides.

The config zoo (``fleetx_tpu/configs/``) outlives the code that reads it:
a renamed engine knob leaves the old YAML key silently ignored — the recipe
*looks* tuned but the value never lands (the classic "why did my
save_steps stop working" failure).  Because ``AttrDict`` supports
``cfg.get("k")``, ``cfg["k"]`` and ``cfg.k`` access, the consumption set is
built from every python file under ``fleetx_tpu/``, ``tools/`` and
``tasks/``: string keys of ``get/pop/setdefault``/subscript/``in`` tests,
attribute names, keyword-argument names and function parameter names (YAML
sub-dicts are routinely splatted ``**cfg`` into constructors), and
class-body field names (dataclass configs).  A YAML leaf key matching none
of those is dead.

The reverse direction flags code reading a *section* no config ever
defines: ``cfg.get("TitleCase")``/``cfg["TitleCase"]`` on a receiver named
like a config (``cfg``/``config``/``self.cfg``...) where no YAML in the
repo has that top-level key — the stale-rename caught from the code side.
"""

from __future__ import annotations

import ast
import re
from typing import Any, Iterable, Optional

from fleetx_tpu.lint.core import Finding, Project, Rule, register

try:
    import yaml
except ImportError:  # pragma: no cover — pyyaml ships with the repo
    yaml = None

#: YAML structural keys that are config-system syntax, not config data
_STRUCTURAL = {"_base_", "_inherited_"}

#: receivers that look like a config object for the reverse check
_CFG_RECEIVERS = re.compile(
    r"(^|\.)(cfg|config|configs|conf)$|_(cfg|config)$")

_TITLECASE = re.compile(r"^[A-Z][A-Za-z0-9]+$")


def _flatten_yaml(node: Any, path: str = "") -> Iterable[tuple[str, str, int]]:
    """(dotted_path, leaf_key, line) for every mapping key in a YAML doc,
    including mappings nested inside sequences (transform-op lists)."""
    if isinstance(node, yaml.nodes.SequenceNode):
        for item in node.value:
            yield from _flatten_yaml(item, f"{path}[]" if path else "[]")
        return
    if not isinstance(node, yaml.nodes.MappingNode):
        return
    for key_node, value_node in node.value:
        if not isinstance(key_node, yaml.nodes.ScalarNode):
            continue
        key = str(key_node.value)
        dotted = f"{path}.{key}" if path else key
        yield dotted, key, key_node.start_mark.line + 1
        yield from _flatten_yaml(value_node, dotted)


def _consumed_names(project: Project) -> set[str]:
    """Every identifier the code could use to consume a config key."""
    consumed: set[str] = set()
    for tree in project.consumer_trees():
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute):
                consumed.add(node.attr)
            elif isinstance(node, ast.Call):
                func = node.func
                if isinstance(func, ast.Attribute) and \
                        func.attr in ("get", "pop", "setdefault", "getattr"):
                    if node.args and isinstance(node.args[0], ast.Constant) \
                            and isinstance(node.args[0].value, str):
                        consumed.add(node.args[0].value)
                if isinstance(func, ast.Attribute) and \
                        func.attr == "setdefault_tree" and node.args and \
                        isinstance(node.args[0], ast.Constant):
                    consumed.update(str(node.args[0].value).split("."))
                if isinstance(func, ast.Name) and func.id == "getattr" and \
                        len(node.args) >= 2 and \
                        isinstance(node.args[1], ast.Constant):
                    consumed.add(str(node.args[1].value))
                for kw in node.keywords:
                    if kw.arg:
                        consumed.add(kw.arg)
            elif isinstance(node, ast.Subscript):
                sl = node.slice
                if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                    consumed.add(sl.value)
            elif isinstance(node, ast.Compare):
                # "key" in cfg  — membership tests consume the key
                if isinstance(node.left, ast.Constant) and \
                        isinstance(node.left.value, str) and any(
                            isinstance(op, (ast.In, ast.NotIn))
                            for op in node.ops):
                    consumed.add(node.left.value)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # configs name transforms/datasets/optimizers by the
                # def/class they resolve to in a registry
                consumed.add(node.name)
                a = node.args
                for p in (*a.posonlyargs, *a.args, *a.kwonlyargs):
                    consumed.add(p.arg)
            elif isinstance(node, ast.ClassDef):
                consumed.add(node.name)
                for stmt in node.body:
                    if isinstance(stmt, ast.AnnAssign) and \
                            isinstance(stmt.target, ast.Name):
                        consumed.add(stmt.target.id)
                    elif isinstance(stmt, ast.Assign):
                        for t in stmt.targets:
                            if isinstance(t, ast.Name):
                                consumed.add(t.id)
            elif isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and _TITLECASE.match(
                        str(node.value)):
                # TitleCase literals (section names in f-strings/dict keys)
                consumed.add(node.value)
    return consumed


def _yaml_sections(project: Project) -> set[str]:
    """Mapping keys (any depth) present in any YAML config in the repo.

    All depths, because code reads nested sections through intermediate
    dicts (``data_cfg.get("Eval")`` for ``Data.Eval``).
    """
    sections: set[str] = set()
    for path in project.config_files():
        try:
            doc = yaml.compose(path.read_text(encoding="utf-8"))
        except (yaml.YAMLError, OSError):
            continue
        for _, key, _line in _flatten_yaml(doc):
            sections.add(key)
    return sections


@register
class DeadConfigKey(Rule):
    """Config keys and code-side sections that point at nothing."""

    name = "dead-config-key"
    code = "FX006"
    scans_configs = True
    scope = "project"
    description = ("YAML config key no code consumes / code reads a config "
                   "section no YAML provides")

    def check_project(self, project: Project) -> Iterable[Finding]:
        if yaml is None:
            return ()
        out: list[Finding] = []
        consumed = _consumed_names(project)

        for path in project.config_files():
            rel = project.relpath(path)
            try:
                doc = yaml.compose(path.read_text(encoding="utf-8"))
            except (yaml.YAMLError, OSError):
                continue
            if doc is None:
                continue
            for dotted_path, key, line in _flatten_yaml(doc):
                if key in _STRUCTURAL or key in consumed:
                    continue
                out.append(self.finding(
                    rel, line, 0,
                    f"config key '{dotted_path}' is never consumed by any "
                    f"get()/[]/attribute access under fleetx_tpu/, tools/ "
                    f"or tasks/ — dead key (or a renamed knob)"))

        out.extend(self._unprovided_sections(project))
        return out

    # ------------------------------------------------- reverse direction
    def _unprovided_sections(self, project: Project) -> Iterable[Finding]:
        sections = _yaml_sections(project)
        if not sections:  # no configs in scope — nothing to cross-check
            return
        for module in project.modules:
            for node in ast.walk(module.tree):
                section, site = self._section_read(node)
                if section and section not in sections:
                    yield self.finding(
                        module.relpath, site.lineno, site.col_offset,
                        f"code reads config section '{section}' but no YAML "
                        f"config in the repo defines it — stale rename?")

    @staticmethod
    def _section_read(node: ast.AST) -> tuple[Optional[str], Any]:
        """``cfg.get("X")`` / ``cfg["X"]`` with a TitleCase literal key."""
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "get" and node.args and \
                isinstance(node.args[0], ast.Constant):
            receiver = node.func.value
            key = node.args[0].value
        elif isinstance(node, ast.Subscript) and \
                isinstance(node.slice, ast.Constant):
            receiver = node.value
            key = node.slice.value
        else:
            return None, None
        if not isinstance(key, str) or not _TITLECASE.match(key):
            return None, None
        try:
            rec_str = ast.unparse(receiver)
        except Exception:  # pragma: no cover — malformed receivers
            return None, None
        if _CFG_RECEIVERS.search(rec_str):
            return key, node
        return None, None
