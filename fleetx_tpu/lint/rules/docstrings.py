"""Docstring rules — ``codestyle/check_docstrings.py`` under the registry.

Same policy as the original checker (itself a pragmatic subset of the
reference's 349-LoC pylint plugin): public modules, classes and
functions/methods carry docstrings; protocol hooks documented once on the
base class and one-statement accessors are exempt.  Moving the policy here
gives the docstring checks the shared driver, the ``# fleetx:
noqa[docstring-missing]`` suppression syntax and the shared exit-code
convention; ``codestyle/check_docstrings.py`` remains as a thin
pre-commit-compatible wrapper.
"""

from __future__ import annotations

import ast
from typing import Iterable

from fleetx_tpu.lint.core import Finding, Project, Rule, SourceModule, register

#: module/engine protocol hooks — documented once on the base protocol
#: (core/module.py BasicModule, core/engine/basic_engine.py)
SKIP_NAMES = {
    "__init__", "setup", "main",
    "get_model", "init_variables", "training_loss", "validation_loss",
    "predict_step", "training_step_end", "validation_step_end",
    "pretreating_batch", "input_spec", "fit", "evaluate", "predict",
    "save", "load", "inference", "generate",
    # lint rule protocol hooks — documented once on lint/core.py Rule
    "check_module", "check_project",
}


def _public_nodes(tree: ast.Module) -> Iterable[ast.AST]:
    """Module-level defs and their direct methods — nested closures are
    implementation detail (same stance as the reference checker)."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            yield node
            if isinstance(node, ast.ClassDef):
                yield from (n for n in node.body
                            if isinstance(n, (ast.FunctionDef,
                                              ast.AsyncFunctionDef)))


def _trivial(node: ast.AST) -> bool:
    """One-statement accessors are self-describing."""
    if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    body = node.body
    if body and isinstance(body[0], ast.Expr) and \
            isinstance(body[0].value, ast.Constant):
        body = body[1:]  # strip docstring
    return len(body) <= 1


@register
class DocstringMissing(Rule):
    """Public module/class/function without a docstring."""

    name = "docstring-missing"
    code = "FX101"
    category = "docstrings"
    description = ("public module, class, or function lacks a docstring "
                   "(protocol hooks and one-liners exempt)")

    def check_module(self, module: SourceModule,
                     project: Project) -> Iterable[Finding]:
        out: list[Finding] = []
        if not ast.get_docstring(module.tree) and \
                module.path.name != "__init__.py":
            out.append(self.finding(module.relpath, 1, 0,
                                    "missing module docstring"))
        for node in _public_nodes(module.tree):
            name = node.name
            if name.startswith("_") or name in SKIP_NAMES or _trivial(node):
                continue
            if ast.get_docstring(node) is None:
                kind = "class" if isinstance(node, ast.ClassDef) \
                    else "function"
                out.append(self.finding(
                    module.relpath, node.lineno, node.col_offset,
                    f"missing docstring on {kind} {name}"))
        return out


@register
class DocstringEmpty(Rule):
    """Docstring present but blank."""

    name = "docstring-empty"
    code = "FX102"
    category = "docstrings"
    description = "docstring exists but contains only whitespace"

    def check_module(self, module: SourceModule,
                     project: Project) -> Iterable[Finding]:
        out: list[Finding] = []
        for node in _public_nodes(module.tree):
            name = node.name
            if name.startswith("_") or name in SKIP_NAMES or _trivial(node):
                continue
            doc = ast.get_docstring(node)
            if doc is not None and not doc.strip():
                kind = "class" if isinstance(node, ast.ClassDef) \
                    else "function"
                out.append(self.finding(
                    module.relpath, node.lineno, node.col_offset,
                    f"empty docstring on {kind} {name}"))
        return out
