"""Thread-safety rules (FX014-FX016) over the thread/lock lattice.

The serving fleet is the first genuinely concurrent subsystem in the tree:
the engine loop, the replica accept/handler threads and the router's
accept/poll/per-connection threads all share mutable state behind ad-hoc
``threading.Lock`` discipline.  The bug class that takes such a fleet down
is never a crash in review — it is a counter bumped off-lock from a
per-connection handler, two locks taken in opposite orders on the drain
path, or a socket ``recv`` sitting inside a ``with self._lock:`` so one
stuck peer stalls every thread contending on the lock.  These rules make
that class a lint failure, built on :class:`~fleetx_tpu.lint.dataflow.
ThreadModel` (thread contexts from ``threading.Thread(target=...)`` sites,
guarded-attribute sets from ``with self._lock`` discipline, both propagated
over the interprocedural call graph):

- **FX014** ``unguarded-shared-state`` — an attribute written on one thread
  context and read/written on another with no common lock on some path.
  FP guards: thread-safe containers (``queue.Queue``, ``deque``, ``Event``
  &c.), ``__init__`` writes, thread-confined state (all accesses on one
  single-instance context), writes ordered before the spawn in the same
  function, and helpers only ever called under the lock (caller-entry lock
  intersection).
- **FX015** ``lock-order-inversion`` — lock A acquired under B on one
  reachable path and B under A on another (lexically or through a call
  made under a lock), the classic ABBA deadlock.
- **FX016** ``blocking-call-under-lock`` — socket recv/accept, zero-arg
  ``.get()``/``.join()``, subprocess waits, ``time.sleep`` or a jax device
  sync reachable while a lock is held: the drain-stall shape.

All three are *may* analyses (see docs/static_analysis.md "Scope and
limits"); deliberate lock-free protocols are silenced inline with
``# fleetx: noqa[rule] -- reason``, never baselined.  The runtime half of
the contract is ``fleetx_tpu/observability/tsan.py`` (``FLEETX_TSAN=1``).
"""

from __future__ import annotations

import hashlib
from typing import Dict, Iterable, List, Tuple

from fleetx_tpu.lint import dataflow
from fleetx_tpu.lint.core import (Finding, Project, Rule,
                                  iter_context_files, register)


def callgraph_fingerprint(project: Project) -> str:
    """Content fingerprint of the thread rules' input surface: the scanned
    modules plus every ``CONSUMER_DIRS`` python file (the call graph the
    lattice propagates over) — and nothing else.  Unlike
    :meth:`Project.digest` this excludes the YAML config zoo, so config-only
    edits keep the thread-rule cache warm while ANY cross-file python edit
    (a new spawn site, a helper moved under a lock) invalidates it.
    """
    cached = getattr(project, "_lint_callgraph_fp", None)
    if cached is not None:
        return cached
    h = hashlib.sha1()
    seen: set = set()
    for m in project.modules:
        seen.add(m.relpath)
        h.update(f"{m.relpath}\0{m.sha1}\0".encode("utf-8"))
    for f in iter_context_files(project.root):
        rel = project.relpath(f)
        if rel in seen:
            continue
        seen.add(rel)
        try:
            payload = f.read_bytes()
        except OSError:
            continue
        h.update(f"{rel}\0".encode("utf-8"))
        h.update(hashlib.sha1(payload).digest())
    project._lint_callgraph_fp = h.hexdigest()
    return project._lint_callgraph_fp


class _ThreadRule(Rule):
    """Shared plumbing: project scope, lattice access, call-graph cache key."""

    scope = "project"
    category = "threads"

    def project_digest(self, project: Project) -> str:
        return callgraph_fingerprint(project)


def _ctx_text(tm: dataflow.ThreadModel, fid: int) -> str:
    parts = []
    for label, multi in sorted(tm.contexts_of(fid).items()):
        parts.append(f"'{label}' (xN)" if multi else f"'{label}'")
    return "/".join(parts)


@register
class UnguardedSharedState(_ThreadRule):
    """Cross-thread attribute traffic with no common lock."""

    name = "unguarded-shared-state"
    code = "FX014"
    description = ("attribute written on one thread context and read/"
                   "written on another with no common lock held — guard "
                   "both sides with one lock or make the state "
                   "thread-confined")

    def check_project(self, project: Project) -> Iterable[Finding]:
        tm = dataflow.get_thread_model(project)
        out: List[Finding] = []
        for owner, attrs in sorted(tm.accesses.items()):
            relpath, cls = owner
            safe = tm.safe_attrs.get(owner, set()) | \
                tm.lock_attrs.get(owner, set())
            for attr, accesses in sorted(attrs.items()):
                if attr in safe:
                    continue
                hit = self._conflict_for(tm, accesses)
                if hit is None:
                    continue
                write, other, ctx_w, ctx_o = hit
                anchor = self._anchor(tm, accesses, write)
                if not anchor.func.in_scope:
                    continue
                counterpart = other if anchor is write else write
                where = (f"line {counterpart.lineno}"
                         if counterpart.func.relpath == anchor.func.relpath
                         else f"{counterpart.func.relpath}:"
                              f"{counterpart.lineno}")
                out.append(self.finding(
                    anchor.func.relpath, anchor.lineno, anchor.col,
                    f"'{cls}.{attr}' is written on thread context "
                    f"{_ctx_text(tm, id(write.func.node))} "
                    f"({write.func.node.name}, line {write.lineno}) and "
                    f"{'written' if other.kind == 'write' else 'read'} on "
                    f"{_ctx_text(tm, id(other.func.node))} "
                    f"({other.func.node.name}, {where}) with no common "
                    f"lock held — interleavings lose updates or observe "
                    f"torn state; guard both sides with one lock (e.g. "
                    f"'with self._lock:') or make the attribute "
                    f"thread-confined"))
        return out

    @staticmethod
    def _conflict_for(tm, accesses):
        """First (write, other-access) pair that can interleave cross-thread
        unlocked — one finding per (class, attr) keeps triage tractable."""
        writes = [a for a in accesses
                  if a.kind == "write" and not tm.is_init_access(a)]
        for w in writes:
            for o in accesses:
                if tm.is_init_access(o):
                    continue
                hit = tm.conflict(w, o)
                if hit is not None:
                    return w, o, hit[0], hit[1]
        return None

    @staticmethod
    def _anchor(tm, accesses, write):
        """Prefer anchoring on an in-scope unlocked write (the fix site)."""
        if write.func.in_scope:
            return write
        for a in accesses:
            if a.func.in_scope and a.kind == "write" and \
                    not tm.locks_at(a) and not tm.is_init_access(a):
                return a
        for a in accesses:
            if a.func.in_scope and not tm.is_init_access(a):
                return a
        return write


@register
class LockOrderInversion(_ThreadRule):
    """Two locks acquired in opposite orders on reachable paths."""

    name = "lock-order-inversion"
    code = "FX015"
    description = ("locks acquired in opposite orders on two reachable "
                   "paths (lexically or through calls made under a lock) "
                   "— ABBA deadlock under contention; pick one global "
                   "acquisition order")

    def check_project(self, project: Project) -> Iterable[Finding]:
        tm = dataflow.get_thread_model(project)
        by_pair: Dict[Tuple[dataflow.LockId, dataflow.LockId],
                      List[dataflow.LockPair]] = {}
        for p in tm.lock_pairs:
            by_pair.setdefault((p.first, p.second), []).append(p)
        out: List[Finding] = []
        seen: set = set()
        for (a, b), sites in sorted(
                by_pair.items(), key=lambda kv: (kv[0][0].label,
                                                 kv[0][1].label)):
            rev = by_pair.get((b, a))
            if not rev:
                continue
            for site in sites:
                if not site.in_scope:
                    continue
                key = (a, b, site.relpath, site.lineno)
                if key in seen:
                    continue
                seen.add(key)
                opp = rev[0]
                via = f" (via {site.via})" if site.via else ""
                opp_via = f" via {opp.via}" if opp.via else ""
                out.append(self.finding(
                    site.relpath, site.lineno, 0,
                    f"lock '{b.label}' acquired while '{a.label}' is "
                    f"held{via}, but the opposite order is taken at "
                    f"{opp.relpath}:{opp.lineno}{opp_via} — two threads "
                    f"taking the orders concurrently deadlock; pick one "
                    f"global acquisition order and restructure the later "
                    f"site"))
                break  # one finding per ordered pair
        return out


@register
class BlockingCallUnderLock(_ThreadRule):
    """(May-)blocking calls reachable while a lock is held."""

    name = "blocking-call-under-lock"
    code = "FX016"
    description = ("socket recv/accept, queue get/join, subprocess wait, "
                   "sleep or device sync reachable while a lock is held — "
                   "every thread contending on the lock stalls behind the "
                   "call (the drain-stall shape); move it outside the lock")

    def check_project(self, project: Project) -> Iterable[Finding]:
        tm = dataflow.get_thread_model(project)
        out: List[Finding] = []
        seen: set = set()
        for site in tm.blocking_sites:
            if not site.in_scope:
                continue
            key = (site.relpath, site.lineno, site.lock)
            if key in seen:
                continue
            seen.add(key)
            out.append(self.finding(
                site.relpath, site.lineno, site.col,
                f"{site.desc} can block while lock '{site.lock.label}' is "
                f"held — every thread contending on '{site.lock.label}' "
                f"stalls behind this call until it returns (drain-stall "
                f"shape); move the blocking call outside the lock, or use "
                f"a non-blocking variant with a timeout"))
        return out
