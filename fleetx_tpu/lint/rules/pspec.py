"""pspec-mesh-mismatch: PartitionSpec axis literals the mesh never declares.

A ``PartitionSpec("modle")`` typo does not fail at construction — GSPMD
only rejects it when the jit actually binds the spec to a mesh, which for a
cold-start 175B config is minutes into compilation (and under
``shard_map`` it can silently mean "replicated").  The mesh's axis
vocabulary is a closed set declared once by the partition-rule registry
(``fleetx_tpu/parallel/rules.py``: ``MESH_AXES`` — the same source the
runtime mesh and shardcheck consume), so the check is purely static:
every string literal inside a ``PartitionSpec(...)`` / ``P(...)`` call
(including nested tuples like ``P(("data", "fsdp"))``) must be a declared
axis name.

Logical axis names (``nn.with_logical_partitioning``) are out of scope —
they pass through the rule table in ``parallel/sharding.py`` and never
reach a ``PartitionSpec`` literal directly.
"""

from __future__ import annotations

import ast
from typing import Iterable

from fleetx_tpu.lint import analysis
from fleetx_tpu.lint.core import Finding, Project, Rule, SourceModule, register

_PSPEC_NAMES = {"jax.sharding.PartitionSpec",
                "jax.interpreters.pxla.PartitionSpec",
                "jax.experimental.pjit.PartitionSpec",
                "PartitionSpec"}


def _axis_literals(node: ast.AST) -> Iterable[tuple[str, ast.AST]]:
    """String constants inside a PartitionSpec argument (tuples flattened)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        yield node.value, node
    elif isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            yield from _axis_literals(e)


@register
class PSpecMeshMismatch(Rule):
    """PartitionSpec axis-name literals cross-checked against MESH_AXES."""

    name = "pspec-mesh-mismatch"
    code = "FX004"
    description = ("PartitionSpec axis literal not declared in "
                   "parallel/rules.py MESH_AXES — fails at jit bind time")

    def context_key(self, project: Project) -> str:
        """Findings depend on the declared mesh axes, not just the file."""
        return ",".join(project.mesh_axes())

    def check_module(self, module: SourceModule,
                     project: Project) -> Iterable[Finding]:
        aliases = analysis.module_aliases(module)
        axes = set(project.mesh_axes())
        out: list[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = analysis.resolve(node.func, aliases)
            if resolved not in _PSPEC_NAMES:
                continue
            args = list(node.args) + [kw.value for kw in node.keywords]
            for arg in args:
                for name, lit in _axis_literals(arg):
                    if name not in axes:
                        out.append(self.finding(
                            module.relpath, lit.lineno, lit.col_offset,
                            f"PartitionSpec axis '{name}' is not a mesh "
                            f"axis — declared axes are "
                            f"{tuple(project.mesh_axes())} "
                            f"(parallel/rules.py MESH_AXES)"))
        return out
