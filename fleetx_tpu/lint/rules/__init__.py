"""Rule modules — importing this package registers every rule."""

from fleetx_tpu.lint.rules import (  # noqa: F401
    collectives,
    config_keys,
    docstrings,
    donation,
    prng,
    pspec,
    retrace,
    sharding,
    threads,
    tracing,
)
