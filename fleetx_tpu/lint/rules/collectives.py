"""Gang-collective lockstep rules (FX007-FX009) over the dataflow engine.

The contract these rules enforce is the one ``resilience/coordination.py``
states and docs/resilience.md's collective-decision table catalogues: every
rank must invoke the same agreement primitives in the same order, so ANY
control flow that reaches a collective on some ranks but not others wedges
the whole gang until ``CoordinationTimeout``.  The PR 6-8 review history is
one instance of this class after another — a unilateral stream-dry loop
exit, a step-keyed save trigger under the in-step skip, an early raise
between the rollback barriers — and each named bug is now a regression
fixture in ``tests/test_zz_lint_v2.py``.

- **FX007** ``collective-under-rank-guard`` — a gang primitive (or a call
  that transitively performs one, via the project call graph) lexically
  dominated by an ``if``/``while`` whose test is rank-tainted, or inside a
  rank-local I/O exception handler.
- **FX008** ``unmatched-agreement-pairing`` — two patterns: (a) a paired
  protocol (``X_enter``/``X_exit`` and friends, see
  :data:`PAIRED_SUFFIXES`/:data:`EXTRA_PAIRS`) whose CFG admits a
  rank-divergent escape path between the pair; (b) a rank-tainted early
  ``return``/``raise``/``break``/``continue`` that skips collectives its
  peers still execute.
- **FX009** ``step-keyed-gang-trigger`` — the FX007 shape where the guard
  is specifically a modulo over a rank-local counter (``step %
  save_steps``-style): the exact PR 6/7 desync, reported separately so the
  fix ("key on the lockstep iteration counter") is in the message.
  Lockstep counters (unconditionally advanced, e.g. ``vote_round``) do not
  taint, so vote-round-keyed triggers pass.

Divergence that is provably pre-agreed (single-process branches, arms that
match the same rendezvous either way) is silenced inline with
``# fleetx: noqa[rule] -- reason``, never baselined.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, List, Optional

from fleetx_tpu.lint import analysis, dataflow
from fleetx_tpu.lint.core import Finding, Project, Rule, register

#: paired-protocol registry, suffix convention: an agreement named
#: ``<base><opener>`` must be matched by ``<base><closer>`` on every path
#: to function exit (docs/static_analysis.md "Declaring a paired primitive")
PAIRED_SUFFIXES = (
    ("_enter", "_exit"),
    ("_begin", "_end"),
    ("_prepare", "_commit"),
)

#: explicit pairs for protocols that don't follow the suffix convention
#: (opener agreement name -> required closer agreement name)
EXTRA_PAIRS: dict = {}


def _closer_for(name: str) -> Optional[str]:
    """The agreement name that must close ``name``, or None."""
    if name in EXTRA_PAIRS:
        return EXTRA_PAIRS[name]
    for opener, closer in PAIRED_SUFFIXES:
        if name.endswith(opener):
            return name[: -len(opener)] + closer
    return None


@dataclasses.dataclass
class _CollectiveSite:
    """One (transitively) collective call and its control context."""

    stmt: ast.stmt
    call: ast.Call
    desc: str
    guard: Optional[dataflow.GuardFrame]   # innermost tainted guard
    loops: List[ast.stmt]
    agreement: Optional[str] = None        # literal name arg, if any


@dataclasses.dataclass
class _ExitSite:
    """One return/raise/break/continue and its control context."""

    stmt: ast.stmt
    guard: Optional[dataflow.GuardFrame]
    loops: List[ast.stmt]


@dataclasses.dataclass
class _FunctionFacts:
    info: dataflow.FuncInfo
    collectives: List[_CollectiveSite]
    exits: List[_ExitSite]


def _innermost_tainted(guards) -> Optional[dataflow.GuardFrame]:
    for g in reversed(guards):
        if g.taint is not None:
            return g
    return None


def _agreement_name(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Constant) and \
            isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def function_facts(project: Project) -> List[_FunctionFacts]:
    """Collective/exit sites with guard context for every in-scope
    function, computed once per project and shared by FX007-FX009."""
    cached = getattr(project, "_lint_gang_facts", None)
    if cached is not None:
        return cached
    df = dataflow.get_dataflow(project)
    out: List[_FunctionFacts] = []
    for info in df.scope_functions():
        env = df.taints(info)
        collectives: List[_CollectiveSite] = []
        exits: List[_ExitSite] = []
        for stmt, guards, loops in dataflow.guarded_statements(
                info.node, lambda e: df.expr_taint(e, env, info)):
            if isinstance(stmt, (ast.Return, ast.Raise, ast.Break,
                                 ast.Continue)):
                exits.append(_ExitSite(stmt, _innermost_tainted(guards),
                                       list(loops)))
            for expr in analysis.statement_exprs(stmt):
                for node in analysis.walk_exprs(expr):
                    if not isinstance(node, ast.Call):
                        continue
                    desc = df.call_collective(node, info)
                    if desc is None:
                        continue
                    collectives.append(_CollectiveSite(
                        stmt, node, desc, _innermost_tainted(guards),
                        list(loops), agreement=_agreement_name(node)))
        out.append(_FunctionFacts(info, collectives, exits))
    project._lint_gang_facts = out
    return out


def _arm_ids(guard_stmt: ast.stmt, exit_stmt: ast.stmt) -> set:
    """Node ids of the guard arm (if-body/orelse/except-body) that contains
    ``exit_stmt`` — the code the exiting rank itself runs."""
    arms: List[list] = []
    if isinstance(guard_stmt, (ast.If, ast.While)):
        arms = [guard_stmt.body, guard_stmt.orelse]
    elif isinstance(guard_stmt, ast.Try):
        arms = [h.body for h in guard_stmt.handlers]
    for arm in arms:
        ids = {id(n) for s in arm for n in ast.walk(s)}
        if id(exit_stmt) in ids:
            return ids
    return set()


def _guard_text(guard: dataflow.GuardFrame) -> str:
    stmt = guard.stmt
    if isinstance(stmt, (ast.If, ast.While)):
        try:
            return f"'{ast.unparse(stmt.test)}' (line {stmt.lineno})"
        except Exception:  # noqa: BLE001 — unparse is best-effort detail
            return f"the guard at line {stmt.lineno}"
    return f"the handler at line {stmt.lineno}"


@register
class CollectiveUnderRankGuard(Rule):
    """Gang collectives reachable only under rank-divergent control flow."""

    name = "collective-under-rank-guard"
    code = "FX007"
    scope = "project"
    description = ("gang collective (coordinator primitive / ckpt commit / "
                   "lax collective) dominated by a rank-divergent branch — "
                   "ranks that skip it wedge the gang")

    def check_project(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for facts in function_facts(project):
            for site in facts.collectives:
                if site.guard is None or site.guard.taint.kind != "rank":
                    continue
                out.append(self.finding(
                    facts.info.relpath, site.call.lineno,
                    site.call.col_offset,
                    f"{site.desc} runs only under {_guard_text(site.guard)}, "
                    f"which is rank-divergent ({site.guard.taint.reason}) — "
                    f"ranks that skip the call strand their peers until "
                    f"CoordinationTimeout; agree on the condition first "
                    f"(broadcast/any_flag) or hoist the collective out of "
                    f"the guard"))
        return out


@register
class UnmatchedAgreementPairing(Rule):
    """Early exits that break a paired protocol or skip peers' collectives."""

    name = "unmatched-agreement-pairing"
    code = "FX008"
    scope = "project"
    description = ("a rank-divergent early return/raise/break escapes "
                   "between paired agreement calls (X_enter without X_exit, "
                   "vote without barrier) or out of a collective loop")

    def check_project(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        df = dataflow.get_dataflow(project)
        for facts in function_facts(project):
            reported: set = set()
            out.extend(self._check_pairs(df, facts, reported))
            out.extend(self._check_exits(df, facts, reported))
        return out

    # -- pattern A: registered pairs + CFG escape enumeration ---------------
    def _check_pairs(self, df, facts: _FunctionFacts,
                     reported: set) -> Iterable[Finding]:
        openers = [s for s in facts.collectives
                   if s.agreement and _closer_for(s.agreement)]
        if not openers:
            return
        cfg = df.cfg(facts.info)
        exits_by_id = {id(e.stmt): e for e in facts.exits}
        for opener in openers:
            closer_name = _closer_for(opener.agreement)
            closers = {id(s.stmt) for s in facts.collectives
                       if s.agreement == closer_name}
            if not closers:
                yield self.finding(
                    facts.info.relpath, opener.call.lineno,
                    opener.call.col_offset,
                    f"agreement '{opener.agreement}' opens a paired "
                    f"protocol but no matching '{closer_name}' call exists "
                    f"in this function — peers reaching the closer will "
                    f"wedge (paired protocols must close in the function "
                    f"that opens them)")
                continue
            reach = cfg.reachable(id(opener.stmt), blocked=closers)
            if dataflow.EXIT not in reach:
                continue
            for key in reach:
                site = exits_by_id.get(key)
                if site is None or site.guard is None:
                    continue
                if dataflow.EXIT not in cfg.succ.get(key, ()):
                    continue   # e.g. a raise absorbed by a local handler
                if id(site.stmt) in reported:
                    continue
                reported.add(id(site.stmt))
                kind = type(site.stmt).__name__.lower()
                yield self.finding(
                    facts.info.relpath, site.stmt.lineno,
                    site.stmt.col_offset,
                    f"this '{kind}' escapes between '{opener.agreement}' "
                    f"(line {opener.call.lineno}) and its paired "
                    f"'{closer_name}' under {_guard_text(site.guard)} "
                    f"({site.guard.taint.reason}) — peers block in the "
                    f"closing rendezvous; vote the failure through "
                    f"any_flag/all_gather and exit uniformly")

    # -- pattern B: rank-divergent exits that skip peers' collectives -------
    def _check_exits(self, df, facts: _FunctionFacts,
                     reported: set) -> Iterable[Finding]:
        if not facts.collectives:
            return
        cfg = None
        for site in facts.exits:
            if site.guard is None or id(site.stmt) in reported:
                continue
            stmt = site.stmt
            if isinstance(stmt, (ast.Break, ast.Continue)):
                if not site.loops:
                    continue
                loop = site.loops[-1]
                pending = [c for c in facts.collectives
                           if loop in c.loops]
                if not pending:
                    continue
                reported.add(id(stmt))
                kind = "break" if isinstance(stmt, ast.Break) else "continue"
                yield self.finding(
                    facts.info.relpath, stmt.lineno, stmt.col_offset,
                    f"rank-divergent '{kind}' ({site.guard.taint.reason}) "
                    f"in a loop whose body issues {pending[0].desc} (line "
                    f"{pending[0].call.lineno}) — peers still looping "
                    f"wedge in their next rendezvous; make the exit a "
                    f"gang decision (vote the flag via any_flag)")
                continue
            # return / raise: only when it actually leaves the function
            if cfg is None:
                cfg = df.cfg(facts.info)
            if dataflow.EXIT not in cfg.succ.get(id(stmt), ()):
                continue
            # "what peers go on to run" = reachable from the guard MINUS
            # the guard arm the exit itself sits on (a collective on the
            # exiting rank's own path is FX007's business; counting it
            # here would invert the diagnosis: `if rank == 0:
            # barrier(); return` does not strand peers in that barrier)
            own_arm = _arm_ids(site.guard.stmt, stmt)
            reach = cfg.reachable(id(site.guard.stmt))
            pending = [c for c in facts.collectives
                       if id(c.stmt) in reach and c.stmt is not stmt
                       and id(c.stmt) not in own_arm]
            if not pending:
                continue
            reported.add(id(stmt))
            kind = type(stmt).__name__.lower()
            yield self.finding(
                facts.info.relpath, stmt.lineno, stmt.col_offset,
                f"rank-divergent '{kind}' ({site.guard.taint.reason}) "
                f"exits while peers go on to {pending[0].desc} (line "
                f"{pending[0].call.lineno}) — they wedge until "
                f"CoordinationTimeout; agree on the exit first "
                f"(any_flag/all_gather), then return/raise on every rank")


@register
class StepKeyedGangTrigger(Rule):
    """Modulo-on-a-rank-local-counter guards around gang collectives."""

    name = "step-keyed-gang-trigger"
    code = "FX009"
    scope = "project"
    description = ("a '% save_steps'-style modulo over a rank-local step "
                   "counter triggers a collective — counters skew under "
                   "the in-step skip; key on a lockstep round counter")

    def check_project(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for facts in function_facts(project):
            for site in facts.collectives:
                if site.guard is None or site.guard.taint.kind != "mod":
                    continue
                out.append(self.finding(
                    facts.info.relpath, site.call.lineno,
                    site.call.col_offset,
                    f"{site.desc} is triggered by {_guard_text(site.guard)} "
                    f"— {site.guard.taint.reason}; per-rank step counters "
                    f"skew (fp16/guard in-step skip), so some ranks sit "
                    f"out the rendezvous while peers wedge in it — key "
                    f"the trigger on a lockstep iteration counter "
                    f"(vote_round) instead"))
        return out
