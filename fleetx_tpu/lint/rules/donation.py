"""donated-buffer-reuse: reads of an argument after jit donated its buffer.

``jax.jit(..., donate_argnums=(0,))`` lets XLA alias the input buffer into
the output (the in-place update the train loop depends on for memory), but
the Python reference still points at the now-deleted buffer: any later read
raises ``RuntimeError: Array has been deleted`` — or worse, on CPU test
backends where donation is a no-op, silently reads stale values that then
explode only on TPU.  The motivating case is the engine's
``self._train_step = jax.jit(train_step, ..., donate_argnums=(0,))`` with
``self.state`` threaded through the fit loop
(``fleetx_tpu/core/engine/eager_engine.py``).

Detection: for every binding of a jit-with-donation callable (assignment or
``@partial(jax.jit, donate_argnums=...)`` decorator; ``donate_argnames``
resolved to positions when the jitted function's signature is visible), find
calls through that binding, take the donated positional argument expressions
(simple names / attribute chains like ``self.state``), and flag

- any *load* of the same expression after the call and before a rebind, and
- a call inside a loop whose donated argument is never rebound in the loop
  body (the second iteration passes a deleted buffer).

A rebinding that happens in the same statement as the call (``state, m =
step(state, b)``) is the idiomatic safe form and is not flagged.  The
after-call scan is branch-aware: each statement contributes its *own*
expressions in source order (compound statements only their headers),
statements in a mutually exclusive ``if`` arm are skipped, and a store only
silences later reads it dominates — a rebind inside ``if cond:`` does not
excuse an unconditional read after the ``if``.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from fleetx_tpu.lint import analysis
from fleetx_tpu.lint.core import Finding, Project, Rule, SourceModule, register


def _own_nodes(stmt: ast.stmt, expr_str: str, ctxs: tuple) -> list[ast.AST]:
    """Name/Attribute nodes matching ``expr_str`` in the statement's OWN
    expressions (headers only for compound statements)."""
    out = []
    for expr in analysis.statement_exprs(stmt):
        for node in analysis.walk_exprs(expr):
            if isinstance(node, (ast.Name, ast.Attribute)) and \
                    isinstance(getattr(node, "ctx", None), ctxs) and \
                    ast.unparse(node) == expr_str:
                out.append(node)
    return out


def _own_loads(stmt: ast.stmt, expr_str: str) -> list[ast.AST]:
    return _own_nodes(stmt, expr_str, (ast.Load,))


def _own_stores(stmt: ast.stmt, expr_str: str) -> bool:
    return bool(_own_nodes(stmt, expr_str, (ast.Store, ast.Del)))


def _functions(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _ordered_statements(fn: ast.AST) -> list[ast.stmt]:
    """The function's own statements in source order (compound statements
    appear before their children)."""
    return sorted(analysis.own_statements(fn),
                  key=lambda s: (s.lineno, s.col_offset))


def _branch_paths(fn: ast.AST) -> dict[int, tuple]:
    """id(stmt) → tuple of ``(id(if_stmt), arm)`` ancestors.

    Statements in different arms of the same ``if`` are mutually exclusive
    — a read there never follows the donating call at runtime.  Loops,
    ``with`` and ``try`` blocks are transparent (treated as always
    executing), which errs toward flagging.
    """
    paths: dict[int, tuple] = {}

    def visit(stmts, path):
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            paths[id(s)] = path
            if isinstance(s, ast.If):
                visit(s.body, path + ((id(s), "body"),))
                visit(s.orelse, path + ((id(s), "orelse"),))
            elif isinstance(s, (ast.For, ast.AsyncFor, ast.While)):
                visit(s.body, path)
                visit(s.orelse, path)
            elif isinstance(s, (ast.With, ast.AsyncWith)):
                visit(s.body, path)
            elif isinstance(s, ast.Try):
                visit(s.body, path)
                for h in s.handlers:
                    visit(h.body, path)
                visit(s.orelse, path)
                visit(s.finalbody, path)

    visit(fn.body, ())
    return paths


def _compatible(p1: tuple, p2: tuple) -> bool:
    """Can both statements execute in one run (no conflicting if-arms)?"""
    arms = dict(p1)
    return all(arms.get(if_id, arm) == arm for if_id, arm in p2)


def _enclosing_loop(call_stmt: ast.stmt, fn: ast.AST) -> Optional[ast.stmt]:
    """Innermost For/While containing ``call_stmt`` (lexically)."""
    best = None
    for loop in analysis.own_statements(fn):
        if not isinstance(loop, (ast.For, ast.While, ast.AsyncFor)):
            continue
        if loop.lineno <= call_stmt.lineno and \
                (loop.end_lineno or loop.lineno) >= (call_stmt.end_lineno or
                                                     call_stmt.lineno):
            if best is None or loop.lineno >= best.lineno:
                best = loop
    return best


@register
class DonatedBufferReuse(Rule):
    """Reads of a donated argument after the donating call."""

    name = "donated-buffer-reuse"
    code = "FX002"
    description = ("argument read after being passed to a donate_argnums "
                   "jit call — the buffer is deleted (or stale on CPU)")

    def check_module(self, module: SourceModule,
                     project: Project) -> Iterable[Finding]:
        aliases = analysis.module_aliases(module)
        bindings = analysis.donated_bindings(module.tree, aliases)
        if not bindings:
            return ()
        out: list[Finding] = []
        for fn in _functions(module.tree):
            stmts = _ordered_statements(fn)
            paths = _branch_paths(fn)
            for stmt in stmts:
                for expr in analysis.statement_exprs(stmt):
                    for node in analysis.walk_exprs(expr):
                        if isinstance(node, ast.Call):
                            key = ast.unparse(node.func)
                            donate = bindings.get(key)
                            if donate:
                                out.extend(self._check_call(
                                    module, fn, stmts, paths, stmt, node,
                                    donate))
        return out

    def _check_call(self, module: SourceModule, fn: ast.AST,
                    stmts: list[ast.stmt], paths: dict, call_stmt: ast.stmt,
                    call: ast.Call, donate: tuple) -> Iterable[Finding]:
        for pos in donate:
            if pos >= len(call.args):
                continue
            arg = call.args[pos]
            if not isinstance(arg, (ast.Name, ast.Attribute)):
                continue
            expr_str = ast.unparse(arg)
            # reads later in the SAME statement: Python evaluates the RHS
            # left to right, so `out = f(state, b) + state.sum()` reads the
            # deleted buffer — and the tuple-target store happens only
            # after the whole RHS, so it is no excuse
            later = [n for n in _own_loads(call_stmt, expr_str)
                     if (n.lineno, n.col_offset) > (call.end_lineno or
                                                    call.lineno,
                                                    call.end_col_offset or 0)]
            if later:
                node = later[0]
                yield self.finding(
                    module.relpath, node.lineno, node.col_offset,
                    f"'{expr_str}' was donated to "
                    f"'{ast.unparse(call.func)}' earlier in this statement "
                    f"and read again after the call — the buffer is "
                    f"deleted after donation")
                continue
            # the call statement's own stores: `state, m = step(state, b)`
            rebound_here = _own_stores(call_stmt, expr_str)
            loop = _enclosing_loop(call_stmt, fn)

            if loop is not None and not rebound_here:
                loop_stmts = [s for s in stmts
                              if loop.lineno < s.lineno and
                              (s.end_lineno or s.lineno) <=
                              (loop.end_lineno or loop.lineno)]
                if not any(_own_stores(s, expr_str) for s in loop_stmts):
                    yield self.finding(
                        module.relpath, call.lineno, call.col_offset,
                        f"'{expr_str}' is donated here but never rebound in "
                        f"the enclosing loop — the next iteration passes a "
                        f"deleted buffer (rebind '{expr_str}' from the "
                        f"call's result)")
                    continue

            if rebound_here:
                continue
            # branch-aware linear scan in source order over each
            # statement's own expressions: a read is a hazard when it can
            # execute after the call (compatible if-arms) and no store
            # that DOMINATES it (executes on every path to it) intervened
            call_path = paths.get(id(call_stmt), ())
            store_paths: list[tuple] = []
            for stmt in stmts:
                if (stmt.lineno, stmt.col_offset) <= (call_stmt.lineno,
                                                      call_stmt.col_offset):
                    continue
                p = paths.get(id(stmt))
                if p is None or not _compatible(call_path, p):
                    continue  # mutually exclusive with the call
                loads = _own_loads(stmt, expr_str)
                # `x = f(x)`: the RHS load happens before the target store,
                # so loads are checked first
                if loads and not any(set(sp) <= set(p)
                                     for sp in store_paths):
                    node = loads[0]
                    yield self.finding(
                        module.relpath, node.lineno, node.col_offset,
                        f"'{expr_str}' was donated to '"
                        f"{ast.unparse(call.func)}' on line {call.lineno} "
                        f"and read here before being rebound — the buffer "
                        f"is deleted after donation")
                    break
                if _own_stores(stmt, expr_str):
                    if set(p) <= set(call_path):
                        break  # unconditional rebind: everything after is safe
                    store_paths.append(p)  # conditional rebind: keep scanning
