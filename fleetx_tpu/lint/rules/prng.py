"""prng-key-reuse: one key consumed by two sampling calls without a split.

JAX keys are pure values — sampling twice with the same key yields the
*same* bits, which in a training loop means correlated dropout masks or
identical noise across what should be independent draws.  The repo idiom
(``models/imagen/modeling.py``, ``models/gpt/generation.py``) is
``rng, sub = jax.random.split(rng)`` before every consumption; this rule
flags the paths that skip it.

Detection is a per-function walk that tracks, for each simple name, the
last sampling call that consumed it; any second consumption before the name
is reassigned (by ``split``/``fold_in`` or anything else) is flagged.
Branches of an ``if`` are walked with independent copies of the state and
merged conservatively; loop bodies are walked twice so a consumption that
survives an iteration (key never re-split in the loop) is caught.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from fleetx_tpu.lint import analysis
from fleetx_tpu.lint.core import Finding, Project, Rule, SourceModule, register

#: jax.random functions that do NOT consume a key's stream
_NON_CONSUMING = {"split", "fold_in", "PRNGKey", "key", "key_data",
                  "wrap_key_data", "key_impl", "clone"}


def _consumed_key(call: ast.Call, aliases: dict) -> Optional[str]:
    """Name of the key a ``jax.random.*`` sampling call consumes, if any."""
    resolved = analysis.resolve(call.func, aliases)
    if not resolved or not resolved.startswith("jax.random."):
        return None
    fn_name = resolved[len("jax.random."):]
    if "." in fn_name or fn_name in _NON_CONSUMING:
        return None
    key_arg = call.args[0] if call.args else None
    for kw in call.keywords:
        if kw.arg == "key":
            key_arg = kw.value
    if isinstance(key_arg, ast.Name):
        return key_arg.id
    return None


@register
class PrngKeyReuse(Rule):
    """The same PRNG key consumed twice without an interleaved split."""

    name = "prng-key-reuse"
    code = "FX003"
    description = ("a jax.random key consumed by two sampling calls without "
                   "jax.random.split/fold_in in between — identical bits")

    def check_module(self, module: SourceModule,
                     project: Project) -> Iterable[Finding]:
        aliases = analysis.module_aliases(module)
        out: list[Finding] = []
        flagged: set[int] = set()  # call node ids (loop bodies walk twice)
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_block(node.body, {}, aliases, module, out, flagged)
        return out

    # ------------------------------------------------------------ the walk
    def _walk_block(self, stmts: list[ast.stmt], state: dict,
                    aliases: dict, module: SourceModule,
                    out: list[Finding], flagged: set[int]) -> dict:
        """``state``: key name → lineno of its last consumption."""
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # separate scope, walked by check_module
            # consumptions in this statement's own expressions, in order
            for expr in analysis.statement_exprs(stmt):
                for node in analysis.walk_exprs(expr):
                    if isinstance(node, ast.Call):
                        key = _consumed_key(node, aliases)
                        if key is None:
                            continue
                        if key in state and id(node) not in flagged:
                            flagged.add(id(node))
                            out.append(self.finding(
                                module.relpath, node.lineno, node.col_offset,
                                f"key '{key}' was already consumed by a "
                                f"sampling call on line {state[key]} — "
                                f"split it first (rng, sub = jax.random."
                                f"split(rng)) or the two draws return "
                                f"identical bits"))
                        state[key] = node.lineno
            # rebinds reset the key's stream
            for name in _stmt_stores(stmt):
                state.pop(name, None)
            # control flow
            if isinstance(stmt, ast.If):
                s_body = self._walk_block(stmt.body, dict(state), aliases,
                                          module, out, flagged)
                s_else = self._walk_block(stmt.orelse, dict(state), aliases,
                                          module, out, flagged)
                state = _merge(state, s_body, s_else)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                # walk twice: a key consumed in iteration 1 and not re-split
                # is reused in iteration 2
                state = self._walk_block(stmt.body, state, aliases, module,
                                         out, flagged)
                state = self._walk_block(stmt.body, state, aliases, module,
                                         out, flagged)
                state = self._walk_block(stmt.orelse, state, aliases, module,
                                         out, flagged)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                state = self._walk_block(stmt.body, state, aliases, module,
                                         out, flagged)
            elif isinstance(stmt, ast.Try):
                for block in (stmt.body, *(h.body for h in stmt.handlers),
                              stmt.orelse, stmt.finalbody):
                    state = self._walk_block(block, state, aliases, module,
                                             out, flagged)
        return state


def _stmt_stores(stmt: ast.stmt) -> list[str]:
    """Simple names this statement's own targets (re)bind."""
    targets: list[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    out: list[str] = []
    for t in targets:
        out.extend(analysis.target_names(t))
    return out


def _merge(before: dict, s_body: dict, s_else: dict) -> dict:
    """Post-``if`` state: the union of both arms' final states.

    If either arm's final state leaves the key consumed, the path through
    that arm reaches any later consumption with the key already spent — so
    the later draw is a real reuse on that path and must flag.  Refreshes
    are already applied inside each arm's walk (assignment pops the key),
    so a key re-split in an arm simply drops out of that arm's state.
    """
    merged = dict(s_else)
    for key, line in s_body.items():
        merged[key] = max(line, merged.get(key, line))
    return merged
