"""FX010 retrace-hazard: jitted callables fed loop-varying shapes/statics.

The serving runtime's core invariant is "two static-shape jitted programs
that never retrace" (docs/serving.md) — today that is pinned only by the
jit-cache-size assertions in ``tests/test_zz_serving.py``.  This rule moves
the invariant into lint: a callable the module provably jits (decorated,
or bound via ``x = jax.jit(fn, ...)``) that is invoked inside a Python
loop with an argument whose SHAPE (or static value) varies across
iterations compiles a fresh executable per distinct shape/value — the
classic silent-slowdown where step N is fast and step N+1 stalls in XLA.

Three shapes are flagged, each with a named fixture:

1. a **static argument** (``static_argnums``/``static_argnames`` position)
   whose expression involves a loop-varying name — one compile per value;
2. a **sliced operand** whose slice length is not syntactically constant
   and whose bounds involve a loop-varying name (``buf[:len(active)]``) —
   one compile per length.  Constant-length windows (``x[p:p + K]`` with
   the same base expression and a constant offset) pass: that is the
   engine's chunked-prefill idiom;
3. an **array constructor** (``np.zeros``/``jnp.ones``/...) whose shape
   argument involves a loop-varying name.

Loop-varying names are computed per loop by fixpoint: ``for`` targets,
augmented-assignment targets, self-updates (``x = f(x)``), and anything
assigned from them.  The analysis is intra-procedural and name-granular
(attributes like ``self.pool_k`` are not tracked) — the documented
trade-off of the whole linter.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, List, Optional, Set

from fleetx_tpu.lint import analysis
from fleetx_tpu.lint.core import Finding, Project, Rule, SourceModule, register

#: resolved constructors whose first argument is a shape
_SHAPE_CTORS = {
    f"{mod}.{fn}"
    for mod in ("numpy", "jax.numpy")
    for fn in ("zeros", "ones", "full", "empty", "arange")
}


@dataclasses.dataclass
class _JitBinding:
    """One callable the module jits, with its static-argument metadata."""

    params: List[str]            # positional param names ([] when unknown)
    static_names: Set[str]       # static params by name
    static_positions: Set[int]   # static params by call position

    def static_at(self, index: int) -> bool:
        """Is the call-site positional argument at ``index`` static?"""
        if index in self.static_positions:
            return True
        return index < len(self.params) and \
            self.params[index] in self.static_names


def _static_meta(call: ast.Call, params: List[str]) -> _JitBinding:
    """Decode static_argnums/static_argnames off a ``jax.jit(...)`` call."""
    names: Set[str] = set()
    positions: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            positions.update(analysis._literal_ints(kw.value))
        elif kw.arg == "static_argnames":
            names.update(analysis._literal_strs(kw.value))
    return _JitBinding(params=params, static_names=names,
                       static_positions=positions)


def jit_bindings(module: SourceModule) -> dict:
    """Callable-expression string -> :class:`_JitBinding` for everything
    this module jits: decorated defs and ``target = jax.jit(fn, ...)``
    assignments (including ``self._step = ...``)."""
    aliases = analysis.module_aliases(module)
    defs_by_name = {
        n.name: n for n in ast.walk(module.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    bindings: dict = {}
    for tf in analysis.module_traced(module):
        if tf.via != "decorator":
            continue
        params = analysis._positional_params(tf.node)
        bindings[tf.node.name] = _JitBinding(
            params=params, static_names=set(tf.static_params),
            static_positions=set())
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and analysis.resolve(node.value.func, aliases)
                in analysis.JIT_NAMES and len(node.targets) == 1):
            continue
        call = node.value
        params: List[str] = []
        if call.args:
            head = call.args[0]
            if isinstance(head, ast.Lambda):
                params = analysis._positional_params(head)
            elif isinstance(head, ast.Name) and head.id in defs_by_name:
                params = analysis._positional_params(defs_by_name[head.id])
        try:
            key = ast.unparse(node.targets[0])
        except Exception:  # noqa: BLE001 — exotic target, skip
            continue
        bindings[key] = _static_meta(call, params)
    return bindings


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _loop_varying(loop: ast.stmt) -> Set[str]:
    """Names whose value varies across iterations of ``loop``."""
    varying: Set[str] = set()
    if isinstance(loop, (ast.For, ast.AsyncFor)):
        varying.update(analysis.target_names(loop.target))
    stmts = list(analysis.own_statements_of_body(loop.body))
    for stmt in stmts:   # seeds: self-updates + augmented assignments
        if isinstance(stmt, ast.AugAssign):
            varying.update(analysis.target_names(stmt.target))
        elif isinstance(stmt, ast.Assign):
            targets = {n for t in stmt.targets
                       for n in analysis.target_names(t)}
            if targets & _names_in(stmt.value):
                varying.update(targets)
    changed = True
    while changed:
        changed = False
        for stmt in stmts:
            targets: List[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                targets, value = [stmt.target], stmt.iter
            if value is None or not (_names_in(value) & varying):
                continue
            for t in targets:
                for name in analysis.target_names(t):
                    if name not in varying:
                        varying.add(name)
                        changed = True
    return varying


def _const_length_slice(sl: ast.Slice) -> bool:
    """True when the slice length is syntactically constant (both bounds
    constant, or ``x : x + K`` / ``x : x - K`` over the same base)."""
    lower, upper = sl.lower, sl.upper
    if sl.step is not None:
        return False
    consts = [b is None or isinstance(b, ast.Constant)
              for b in (lower, upper)]
    if all(consts):
        return True
    if lower is not None and upper is not None and \
            isinstance(upper, ast.BinOp) and \
            isinstance(upper.op, (ast.Add, ast.Sub)) and \
            isinstance(upper.right, ast.Constant):
        try:
            return ast.unparse(upper.left) == ast.unparse(lower)
        except Exception:  # noqa: BLE001 — unparse is best-effort
            return False
    return False


@register
class RetraceHazard(Rule):
    """Jit re-compiles per iteration from varying shapes/static values."""

    name = "retrace-hazard"
    code = "FX010"
    description = ("a jitted callable is invoked in a loop with a "
                   "Python-varying shape or static argument — one XLA "
                   "compile per distinct value; pin the shape (pad/mask) "
                   "like the serving runtime's static-shape programs")

    def check_module(self, module: SourceModule,
                     project: Project) -> Iterable[Finding]:
        bindings = jit_bindings(module)
        if not bindings:
            return ()
        aliases = analysis.module_aliases(module)
        out: List[Finding] = []
        for loop in ast.walk(module.tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            varying = _loop_varying(loop)
            if not varying:
                continue
            for stmt in analysis.own_statements_of_body(loop.body):
                for expr in analysis.statement_exprs(stmt):
                    for node in analysis.walk_exprs(expr):
                        if isinstance(node, ast.Call):
                            out.extend(self._check_call(
                                node, bindings, varying, aliases,
                                module.relpath))
        return out

    def _check_call(self, call: ast.Call, bindings: dict, varying: Set[str],
                    aliases: dict, relpath: str) -> Iterable[Finding]:
        try:
            key = ast.unparse(call.func)
        except Exception:  # noqa: BLE001 — exotic callee
            return
        binding = bindings.get(key)
        if binding is None:
            return
        for idx, arg in enumerate(call.args):
            yield from self._check_arg(
                call, key, arg, binding.static_at(idx), varying, aliases,
                relpath)
        for kw in call.keywords:
            if kw.arg is None:
                continue
            yield from self._check_arg(
                call, key, kw.value, kw.arg in binding.static_names,
                varying, aliases, relpath)

    def _check_arg(self, call: ast.Call, key: str, arg: ast.AST,
                   is_static: bool, varying: Set[str], aliases: dict,
                   relpath: str) -> Iterable[Finding]:
        names = _names_in(arg) & varying
        if not names:
            return
        what = sorted(names)[0]
        if is_static:
            yield self.finding(
                relpath, call.lineno, call.col_offset,
                f"static argument '{ast.unparse(arg)}' of jitted '{key}' "
                f"involves loop-varying '{what}' — jax compiles a fresh "
                f"executable per distinct static value; make it a traced "
                f"array argument or hoist it out of the loop")
            return
        for node in ast.walk(arg) if not isinstance(arg, ast.Subscript) \
                else [arg]:
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.slice, ast.Slice) and \
                    not _const_length_slice(node.slice) and \
                    (_names_in(node.slice) & varying):
                yield self.finding(
                    relpath, call.lineno, call.col_offset,
                    f"operand '{ast.unparse(node)}' of jitted '{key}' is a "
                    f"slice whose length varies with loop-local '{what}' — "
                    f"every new length retraces; pad to a static shape and "
                    f"mask (the serving runtime's static-batch idiom)")
                return
        if isinstance(arg, ast.Call):
            ctor = analysis.resolve(arg.func, aliases)
            if ctor in _SHAPE_CTORS and arg.args and \
                    (_names_in(arg.args[0]) & varying):
                yield self.finding(
                    relpath, call.lineno, call.col_offset,
                    f"operand '{ast.unparse(arg)}' of jitted '{key}' is "
                    f"constructed with a shape that varies with "
                    f"loop-local '{what}' — one retrace per shape; "
                    f"allocate at the static maximum and mask")
