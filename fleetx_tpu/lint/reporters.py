"""Text, JSON and SARIF rendering of a :class:`LintResult`.

Text mimics the compiler convention (``path:line:col: CODE[rule] message``)
so editors and CI annotations pick locations up; JSON follows the
``tools/metrics_report.py --json`` spirit — a single machine-readable object
a gating script can consume without scraping stdout; SARIF 2.1.0 is the
interchange format CI forges ingest to annotate findings inline on the
diff (``tools/lint.py --sarif``).
"""

from __future__ import annotations

from fleetx_tpu.lint.core import LintResult, all_rules


def render_text(result: LintResult, verbose: bool = False) -> str:
    """Human-readable report, one finding per line plus a summary."""
    out = [f"{f.location()}: {f.code}[{f.rule}] {f.message}"
           for f in result.findings]
    summary = (f"checked {result.files} files: {len(result.findings)} "
               f"finding(s)")
    extras = []
    if result.suppressed:
        extras.append(f"{len(result.suppressed)} noqa-suppressed")
    if result.baselined:
        extras.append(f"{len(result.baselined)} baselined")
    if extras:
        summary += f" ({', '.join(extras)})"
    out.append(summary)
    if verbose and result.suppressed:
        out.append("suppressed:")
        out.extend(f"  {f.location()}: {f.code}[{f.rule}] {f.message}"
                   for f in result.suppressed)
    return "\n".join(out)


def render_json(result: LintResult) -> dict:
    """Machine-readable payload (schema_version pins the contract)."""
    return {
        "schema_version": 1,
        "rules": result.rules,
        "files": result.files,
        "counts": {
            "findings": len(result.findings),
            "suppressed": len(result.suppressed),
            "baselined": len(result.baselined),
        },
        "findings": [f.to_dict() for f in result.findings],
        "suppressed": [f.to_dict() for f in result.suppressed],
        "baselined": [f.to_dict() for f in result.baselined],
        "clean": result.clean,
    }


def render_sarif(result: LintResult) -> dict:
    """SARIF 2.1.0 log: one run, one result per active finding.

    ``partialFingerprints`` carries the content-based fingerprint the
    baseline machinery already computes, so a SARIF consumer's "new since
    last scan" diffing agrees with ``tools/lint_baseline.json``.  Only
    active findings are emitted — suppressed/baselined ones are resolved
    by definition and would re-open as annotations otherwise.
    """
    registered = {r.name: r for r in all_rules().values()}
    rule_names = [n for n in result.rules if n in registered]
    rule_index = {n: i for i, n in enumerate(rule_names)}
    sarif_rules = []
    for name in rule_names:
        rule = registered[name]
        sarif_rules.append({
            "id": rule.code,
            "name": rule.name,
            "shortDescription": {"text": rule.description or rule.name},
            "helpUri": "docs/static_analysis.md",
            "properties": {"category": rule.category},
        })
    results = []
    for f in result.findings:
        entry = {
            "ruleId": f.code,
            "level": "error",   # the gate treats any finding as failing
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(f.line, 1),
                               "startColumn": max(f.col + 1, 1)},
                },
            }],
        }
        if f.fingerprint:
            entry["partialFingerprints"] = {"fleetxLint/v1": f.fingerprint}
        if f.rule in rule_index:
            entry["ruleIndex"] = rule_index[f.rule]
        results.append(entry)
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {"name": "fleetx-lint",
                                "informationUri":
                                    "docs/static_analysis.md",
                                "rules": sarif_rules}},
            "columnKind": "unicodeCodePoints",
            "results": results,
        }],
    }
