"""Text and JSON rendering of a :class:`LintResult`.

Text mimics the compiler convention (``path:line:col: CODE[rule] message``)
so editors and CI annotations pick locations up; JSON follows the
``tools/metrics_report.py --json`` spirit — a single machine-readable object
a gating script can consume without scraping stdout.
"""

from __future__ import annotations

from fleetx_tpu.lint.core import LintResult


def render_text(result: LintResult, verbose: bool = False) -> str:
    """Human-readable report, one finding per line plus a summary."""
    out = [f"{f.location()}: {f.code}[{f.rule}] {f.message}"
           for f in result.findings]
    summary = (f"checked {result.files} files: {len(result.findings)} "
               f"finding(s)")
    extras = []
    if result.suppressed:
        extras.append(f"{len(result.suppressed)} noqa-suppressed")
    if result.baselined:
        extras.append(f"{len(result.baselined)} baselined")
    if extras:
        summary += f" ({', '.join(extras)})"
    out.append(summary)
    if verbose and result.suppressed:
        out.append("suppressed:")
        out.extend(f"  {f.location()}: {f.code}[{f.rule}] {f.message}"
                   for f in result.suppressed)
    return "\n".join(out)


def render_json(result: LintResult) -> dict:
    """Machine-readable payload (schema_version pins the contract)."""
    return {
        "schema_version": 1,
        "rules": result.rules,
        "files": result.files,
        "counts": {
            "findings": len(result.findings),
            "suppressed": len(result.suppressed),
            "baselined": len(result.baselined),
        },
        "findings": [f.to_dict() for f in result.findings],
        "suppressed": [f.to_dict() for f in result.suppressed],
        "baselined": [f.to_dict() for f in result.baselined],
        "clean": result.clean,
    }
