"""Project-level dataflow: call graph, per-function CFGs, rank-taint lattice.

PRs 6-8 added gang-collective lockstep contracts (every rank must issue the
same agreement primitives in the same order — ``resilience/coordination.py``)
and their review history was dominated by ONE bug class: a collective
reachable under control flow keyed on rank-local state.  Catching that class
needs more than the per-file AST walks in ``lint/analysis.py``:

1. a **call graph** over the scanned modules plus the ``tools/``/``tasks/``
   driver surface, with a transitive *may-perform-collective* summary per
   function (``self.save()`` is a gang rendezvous three calls down);
2. an intra-procedural **CFG** per function (statement granularity), so the
   pairing rule can enumerate paths between paired agreement calls and name
   the early ``return``/``raise``/``break`` that escapes between them;
3. a **rank-taint lattice** per function: which names (may) hold values
   that differ across ranks.  Sources: ``process_index``/``.rank`` reads,
   rank-keyed environment lookups, device readbacks (``jax.device_get`` /
   ``.item()`` — per-rank under the in-step non-finite skip), per-rank
   stream reads (``next()``), counters incremented under a rank-divergent
   guard, and rank-local I/O exception handlers.  Sanitizers: the agreement
   primitives themselves — a ``broadcast``/``all_gather``/``majority``/
   ``any_flag`` result is gang-uniform by construction.

Everything here is a *may* analysis: taint joins are unions, call edges are
name-resolved through the module's imports (no inheritance walk), and the
CFG adds exceptional edges only for explicit ``raise`` statements.  The
rules built on top (FX007-FX009 in ``rules/collectives.py``) therefore
over-approximate; provably pre-agreed divergence is silenced inline with
``# fleetx: noqa[rule] -- reason`` per docs/static_analysis.md.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Set, Tuple

from fleetx_tpu.lint import analysis

#: coordinator agreement methods — calling one IS a gang collective
COORD_METHODS = {"barrier", "broadcast", "any_flag", "all_gather", "majority"}

#: agreement methods whose RESULT is gang-uniform (taint sanitizers);
#: ``barrier`` returns None so it never launders a value
SANITIZER_METHODS = {"broadcast", "any_flag", "all_gather", "majority"}

#: resolved dotted names of in-program (XLA) collectives — a rank-divergent
#: guard around one of these wedges the mesh exactly like a KV-store one
LAX_COLLECTIVES = {
    "jax.lax.psum", "jax.lax.pmean", "jax.lax.pmax", "jax.lax.pmin",
    "jax.lax.all_gather", "jax.lax.all_to_all", "jax.lax.ppermute",
    "jax.lax.psum_scatter", "jax.lax.pswapaxes",
}

#: every function under this prefix is a cross-process rendezvous
MULTIHOST_PREFIX = "jax.experimental.multihost_utils."

#: attribute reads that yield a rank-local value
RANK_SOURCE_ATTRS = {"rank", "process_index", "preempted"}

#: attribute reads that are gang-uniform even off a rank-local receiver
#: (every rank sees the same world size — ``coord.world == 1`` guards are
#: the canonical "no peers to strand" branch)
UNIFORM_ATTRS = {"world"}

#: resolved call targets that yield a rank-local value
RANK_SOURCE_CALLS = {"jax.process_index"}

#: resolved call targets that read a per-rank device value back to the host
READBACK_CALLS = {"jax.device_get"}
READBACK_ATTRS = {"item", "tolist"}

#: environment keys that identify the process (rank-local by definition)
RANK_ENV_KEYS = {"PROCESS_ID", "RANK", "LOCAL_RANK", "NODE_RANK",
                 "PROCESS_INDEX", "TPU_WORKER_ID", "CLOUD_TPU_TASK_ID"}

#: exception types whose handler body runs only on the rank that hit the
#: (rank-local) I/O fault — control flow inside is rank-divergent
IO_EXCEPTIONS = {"OSError", "IOError", "FileNotFoundError", "NotADirectoryError",
                 "PermissionError", "TimeoutError", "ConnectionError",
                 "BlockingIOError", "InterruptedError", "StopIteration",
                 "EOFError"}

# the call graph parses the same cross-file surface as FX006's consumption
# set and the project digest (core.iter_context_files) — files there are
# *context* even when out of lint scope: a guarded ``self.save()`` in the
# engine is only known collective because checkpoint.py's vote is visible


@dataclasses.dataclass
class Taint:
    """One lattice element: ``kind`` selects the reporting rule.

    ``kind == "rank"`` — plain rank-divergent value (FX007 shapes);
    ``kind == "mod"``  — a modulo over a rank-local counter (the FX009
    step-keyed trigger shape; it stays "mod" through comparisons and
    boolean algebra so ``step % k == 0 and step != last`` keeps the
    specific diagnosis).
    """

    kind: str
    reason: str


@dataclasses.dataclass
class FuncInfo:
    """One function in the project call graph."""

    qualname: str           # e.g. "fleetx_tpu/core/checkpoint.py::save_checkpoint"
    relpath: str
    node: ast.AST           # FunctionDef | AsyncFunctionDef
    aliases: dict
    cls: Optional[str] = None   # enclosing class name, if a method
    in_scope: bool = True       # False for context-only (tools/tasks) modules


# --------------------------------------------------------------------- CFG

ENTRY = "<entry>"
EXIT = "<exit>"


class CFG:
    """Statement-granularity control-flow graph of one function body.

    Nodes are ``id(stmt)`` keys (plus the ``ENTRY``/``EXIT`` sentinels);
    edges follow structured control flow, ``break``/``continue`` jump to
    their loop's follow/head, ``return`` goes to ``EXIT`` and ``raise``
    goes to the nearest enclosing handler set (or ``EXIT`` when none).
    Only explicit ``raise`` statements get exceptional edges — implicit
    exception paths out of arbitrary calls are out of scope (documented
    in docs/static_analysis.md "Scope and limits").
    """

    def __init__(self, fn: ast.AST):
        self.succ: Dict[object, Set[object]] = {ENTRY: set(), EXIT: set()}
        self.stmts: Dict[object, ast.stmt] = {}
        entry = self._seq(fn.body, EXIT, loops=[], tries=[], finals=[])
        self.succ[ENTRY].add(entry)

    # -- construction -------------------------------------------------------
    def _key(self, stmt: ast.stmt) -> object:
        self.stmts[id(stmt)] = stmt
        self.succ.setdefault(id(stmt), set())
        return id(stmt)

    def _seq(self, stmts: List[ast.stmt], follow: object,
             loops: list, tries: list, finals: list) -> object:
        """Wire a statement sequence; returns the entry key (or ``follow``)."""
        entry = follow
        for stmt in reversed(stmts):
            entry = self._stmt(stmt, entry, loops, tries, finals)
        return entry

    def _stmt(self, stmt: ast.stmt, follow: object,
              loops: list, tries: list, finals: list) -> object:
        key = self._key(stmt)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            self.succ[key].add(follow)   # a def is one opaque statement
        elif isinstance(stmt, ast.If):
            self.succ[key].add(self._seq(stmt.body, follow, loops, tries,
                                         finals))
            self.succ[key].add(self._seq(stmt.orelse, follow, loops, tries,
                                         finals) if stmt.orelse else follow)
        elif isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            # loop head evaluates the test/iter each round
            body = self._seq(stmt.body, key, loops + [(key, follow)], tries,
                             finals)
            self.succ[key].add(body)
            exit_edge = (self._seq(stmt.orelse, follow, loops, tries, finals)
                         if stmt.orelse else follow)
            self.succ[key].add(exit_edge)
        elif isinstance(stmt, ast.Break):
            # an abrupt exit runs every enclosing finally first — routing
            # through the innermost finalbody (not straight to the target)
            # is what lets `try: ... finally: barrier("x_exit")` CLOSE a
            # pairing; the over-approximation (flow continues after the
            # finally) trades a narrow false negative for never flagging
            # the canonical cleanup idiom
            self.succ[key].add(finals[-1] if finals
                               else (loops[-1][1] if loops else follow))
        elif isinstance(stmt, ast.Continue):
            self.succ[key].add(finals[-1] if finals
                               else (loops[-1][0] if loops else follow))
        elif isinstance(stmt, ast.Return):
            self.succ[key].add(finals[-1] if finals else EXIT)
        elif isinstance(stmt, ast.Raise):
            # nearest enclosing try WITH handlers: a handler-less frame
            # (try/finally) must not shadow an outer except
            handlers = next((hs for hs in reversed(tries) if hs), None)
            if handlers:
                for h in handlers:
                    self.succ[key].add(h)
            elif finals:
                self.succ[key].add(finals[-1])
            else:
                self.succ[key].add(EXIT)
        elif isinstance(stmt, ast.Try):
            final_entry = (self._seq(stmt.finalbody, follow, loops, tries,
                                     finals)
                           if stmt.finalbody else follow)
            inner_finals = (finals + [final_entry] if stmt.finalbody
                            else finals)
            handler_entries = [self._seq(h.body, final_entry, loops, tries,
                                         inner_finals)
                               for h in stmt.handlers]
            after_body = (self._seq(stmt.orelse, final_entry, loops, tries,
                                    inner_finals)
                          if stmt.orelse else final_entry)
            body = self._seq(stmt.body, after_body, loops,
                             tries + [handler_entries], inner_finals)
            self.succ[key].add(body)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            self.succ[key].add(self._seq(stmt.body, follow, loops, tries,
                                         finals))
        elif isinstance(stmt, ast.Match):
            matched = False
            for case in stmt.cases:
                self.succ[key].add(self._seq(case.body, follow, loops,
                                             tries, finals))
                matched = True
            if not matched:
                self.succ[key].add(follow)
            self.succ[key].add(follow)  # no case may match
        else:
            self.succ[key].add(follow)
        return key

    # -- queries ------------------------------------------------------------
    def reachable(self, start: object,
                  blocked: Optional[Set[object]] = None) -> Set[object]:
        """Keys reachable from ``start`` (exclusive) without passing
        through a ``blocked`` node."""
        blocked = blocked or set()
        seen: Set[object] = set()
        stack = [s for s in self.succ.get(start, ()) if s not in blocked]
        while stack:
            node = stack.pop()
            if node in seen:
                continue
            seen.add(node)
            if node in blocked:
                continue
            stack.extend(self.succ.get(node, ()))
        return seen


# ------------------------------------------------------------ guarded walk

@dataclasses.dataclass
class GuardFrame:
    """One enclosing guard on the walk: the guarding statement and the
    taint (None for uniform guards) of its test."""

    stmt: ast.stmt
    taint: Optional[Taint]


def guarded_statements(fn: ast.AST, taint_of) -> Iterator[
        Tuple[ast.stmt, List[GuardFrame], List[ast.stmt]]]:
    """Yield ``(stmt, guard_stack, loop_stack)`` for every own statement.

    ``taint_of(expr)`` evaluates guard tests; ``guard_stack`` carries every
    enclosing ``if``/``while`` frame (tainted or not, innermost last) plus
    synthetic frames for rank-local I/O exception handlers; ``loop_stack``
    is the enclosing ``for``/``while`` statements.
    """

    def walk(stmts, guards, loops):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            yield stmt, guards, loops
            if isinstance(stmt, ast.If):
                frame = GuardFrame(stmt, taint_of(stmt.test))
                yield from walk(stmt.body, guards + [frame], loops)
                yield from walk(stmt.orelse, guards + [frame], loops)
            elif isinstance(stmt, ast.While):
                frame = GuardFrame(stmt, taint_of(stmt.test))
                yield from walk(stmt.body, guards + [frame], loops + [stmt])
                yield from walk(stmt.orelse, guards, loops)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                yield from walk(stmt.body, guards, loops + [stmt])
                yield from walk(stmt.orelse, guards, loops)
            elif isinstance(stmt, ast.Try):
                yield from walk(stmt.body, guards, loops)
                for h in stmt.handlers:
                    frame = _handler_frame(stmt, h)
                    hg = guards + [frame] if frame else guards
                    yield from walk(h.body, hg, loops)
                yield from walk(stmt.orelse, guards, loops)
                yield from walk(stmt.finalbody, guards, loops)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                yield from walk(stmt.body, guards, loops)
            elif isinstance(stmt, ast.Match):
                for case in stmt.cases:
                    yield from walk(case.body, guards, loops)

    yield from walk(fn.body, [], [])


def _handler_names(handler: ast.ExceptHandler) -> List[str]:
    t = handler.type
    elts = t.elts if isinstance(t, ast.Tuple) else ([] if t is None else [t])
    out = []
    for e in elts:
        path = analysis.dotted(e)
        if path:
            out.append(path.rsplit(".", 1)[-1])
    return out


def _handler_frame(try_stmt: ast.Try,
                   handler: ast.ExceptHandler) -> Optional[GuardFrame]:
    """A synthetic rank-taint frame for rank-local I/O exception handlers."""
    names = _handler_names(handler)
    hits = [n for n in names if n in IO_EXCEPTIONS]
    if hits:
        return GuardFrame(try_stmt, Taint(
            "rank", f"inside a rank-local I/O handler (except {hits[0]})"))
    return GuardFrame(try_stmt, None)


# ------------------------------------------------------------- the engine

class Dataflow:
    """All cross-function facts the FX007-FX009 rules consume, built once
    per :class:`~fleetx_tpu.lint.core.Project` and cached on it."""

    def __init__(self, project):
        self.project = project
        self.functions: Dict[int, FuncInfo] = {}
        self._local_defs: Dict[str, Dict[str, FuncInfo]] = {}
        self._methods: Dict[Tuple[str, str, str], FuncInfo] = {}
        self._by_global: Dict[str, FuncInfo] = {}
        self._reexports: Dict[str, str] = {}
        self._taints: Dict[int, Dict[str, Taint]] = {}
        self._cfgs: Dict[int, CFG] = {}
        self._returns_rank: Dict[int, Optional[str]] = {}
        self.collective_chain: Dict[int, List[str]] = {}
        self._collect()
        self._summarize()

    # -- collection ---------------------------------------------------------
    def _module_dotted(self, relpath: str) -> str:
        dotted = relpath[:-3].replace("/", ".")
        if dotted.endswith(".__init__"):
            dotted = dotted[:-len(".__init__")]
        return dotted

    def _iter_sources(self):
        """(relpath, tree, aliases, in_scope) for scope + context modules."""
        from fleetx_tpu.lint.core import iter_context_files

        seen = set()
        for m in self.project.modules:
            seen.add(m.relpath)
            yield m.relpath, m.tree, analysis.module_aliases(m), True
        for f in iter_context_files(self.project.root):
            rel = self.project.relpath(f)
            if rel in seen:
                continue
            seen.add(rel)
            try:
                tree = ast.parse(f.read_text(encoding="utf-8"))
            except (SyntaxError, OSError, UnicodeDecodeError, ValueError):
                continue
            yield rel, tree, analysis.import_aliases(tree), False

    def _collect(self) -> None:
        for relpath, tree, aliases, in_scope in self._iter_sources():
            dotted = self._module_dotted(relpath)
            local: Dict[str, FuncInfo] = {}
            self._local_defs[relpath] = local

            def visit(node, cls, prefix):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        qual = f"{relpath}::{prefix}{child.name}"
                        info = FuncInfo(qual, relpath, child, aliases,
                                        cls=cls, in_scope=in_scope)
                        self.functions[id(child)] = info
                        local[child.name] = info
                        if cls is None and not prefix:
                            self._by_global[f"{dotted}.{child.name}"] = info
                        if cls is not None:
                            self._methods[(relpath, cls, child.name)] = info
                        visit(child, cls, f"{prefix}{child.name}.")
                    elif isinstance(child, ast.ClassDef):
                        visit(child, child.name, f"{prefix}{child.name}.")
                    else:
                        visit(child, cls, prefix)

            visit(tree, None, "")
            # re-exports: `from x import f` at module top level makes
            # `<this module>.f` an alias for `x.f`
            for node in tree.body:
                if isinstance(node, ast.ImportFrom) and node.module \
                        and not node.level:
                    for a in node.names:
                        self._reexports[f"{dotted}.{a.asname or a.name}"] = \
                            f"{node.module}.{a.name}"

    def _deref(self, dotted: Optional[str]) -> Optional[FuncInfo]:
        for _ in range(6):  # bounded re-export chase
            if dotted is None:
                return None
            hit = self._by_global.get(dotted)
            if hit is not None:
                return hit
            nxt = self._reexports.get(dotted)
            if nxt == dotted:
                return None
            dotted = nxt
        return None

    # -- call resolution ----------------------------------------------------
    def resolve_call(self, call: ast.Call,
                     finfo: FuncInfo) -> Optional[FuncInfo]:
        """The project function a call resolves to, through local scope,
        ``self.``-method dispatch and the module's imports — or None."""
        func = call.func
        if isinstance(func, ast.Name):
            local = self._local_defs.get(finfo.relpath, {}).get(func.id)
            if local is not None:
                return local
            return self._deref(finfo.aliases.get(func.id))
        if isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name) and \
                    func.value.id in ("self", "cls") and finfo.cls:
                return self._methods.get(
                    (finfo.relpath, finfo.cls, func.attr))
            return self._deref(analysis.resolve(func, finfo.aliases))
        return None

    # -- collective summaries ----------------------------------------------
    def direct_collective(self, call: ast.Call,
                          aliases: dict) -> Optional[str]:
        """Why this call IS a gang collective, or None."""
        func = call.func
        if isinstance(func, ast.Attribute) and func.attr in COORD_METHODS:
            return f"gang primitive '.{func.attr}()'"
        resolved = analysis.resolve(func, aliases)
        if resolved in LAX_COLLECTIVES:
            return f"device collective '{resolved}'"
        if resolved and resolved.startswith(MULTIHOST_PREFIX):
            return f"multihost rendezvous '{resolved}'"
        return None

    def _own_calls(self, fn: ast.AST) -> Iterator[ast.Call]:
        for stmt in analysis.own_statements(fn):
            for expr in analysis.statement_exprs(stmt):
                for node in analysis.walk_exprs(expr):
                    if isinstance(node, ast.Call):
                        yield node

    def _summarize(self) -> None:
        """Fixpoints: may-perform-collective chains + rank-local returns."""
        edges: Dict[int, Set[int]] = {}
        for fid, info in self.functions.items():
            callees: Set[int] = set()
            for call in self._own_calls(info.node):
                desc = self.direct_collective(call, info.aliases)
                if desc and fid not in self.collective_chain:
                    self.collective_chain[fid] = [desc]
                target = self.resolve_call(call, info)
                if target is not None:
                    callees.add(id(target.node))
            edges[fid] = callees
        changed = True
        while changed:
            changed = False
            for fid, callees in edges.items():
                if fid in self.collective_chain:
                    continue
                for cid in callees:
                    chain = self.collective_chain.get(cid)
                    if chain is None:
                        continue
                    name = self.functions[cid].node.name
                    new = [f"{name}()"] + chain
                    if len(new) > 6:
                        # cap the DISPLAYED chain only — propagation must
                        # never stop, or deep engine call chains (fit ->
                        # rollback -> save -> commit vote is already 6)
                        # would silently fall out of coverage
                        new = new[:2] + ["..."] + new[-1:]
                    self.collective_chain[fid] = new
                    changed = True
                    break
        # rank-local return summaries, to fixpoint: each pass may add
        # summaries that retaint other functions' environments, so the
        # per-function taint cache is dropped between passes (and after
        # the last one — rule-time queries must see the final summaries)
        for _ in range(3):
            changed = False
            for fid, info in self.functions.items():
                if self._returns_rank.get(fid):
                    continue
                reason = self._returns_rank_local(info)
                if reason and self._returns_rank.get(fid) != reason:
                    self._returns_rank[fid] = reason
                    changed = True
            self._taints.clear()
            if not changed:
                break

    def collective_of(self, fn: ast.AST) -> Optional[str]:
        """Human chain for a may-collective function ('save() -> ...')."""
        chain = self.collective_chain.get(id(fn))
        if chain is None:
            return None
        return " -> ".join(chain)

    def call_collective(self, call: ast.Call,
                        finfo: FuncInfo) -> Optional[str]:
        """Why evaluating this call (transitively) runs a collective."""
        direct = self.direct_collective(call, finfo.aliases)
        if direct:
            return direct
        target = self.resolve_call(call, finfo)
        if target is not None:
            chain = self.collective_of(target.node)
            if chain:
                return f"'{ast.unparse(call.func)}()' -> {chain}"
        return None

    def _returns_rank_local(self, info: FuncInfo) -> Optional[str]:
        env = self.taints(info)
        for stmt in analysis.own_statements(info.node):
            if isinstance(stmt, ast.Return) and stmt.value is not None:
                t = self.expr_taint(stmt.value, env, info)
                if t is not None:
                    return (f"'{info.node.name}()' returns a rank-local "
                            f"value ({t.reason})")
        return None

    # -- taint --------------------------------------------------------------
    def expr_taint(self, node: ast.AST, env: Dict[str, Taint],
                   finfo: FuncInfo) -> Optional[Taint]:
        """May-taint of one expression under the name environment ``env``."""
        if isinstance(node, ast.Name):
            return env.get(node.id)
        if isinstance(node, ast.Attribute):
            if node.attr in RANK_SOURCE_ATTRS:
                return Taint("rank", f"reads rank-local '.{node.attr}'")
            if node.attr in UNIFORM_ATTRS:
                return None
            return self.expr_taint(node.value, env, finfo)
        if isinstance(node, ast.Call):
            return self._call_taint(node, env, finfo)
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
            left = self.expr_taint(node.left, env, finfo)
            if left is not None:
                counter = ast.unparse(node.left)
                return Taint("mod", f"modulo over rank-local counter "
                                    f"'{counter}' ({left.reason})")
            return self.expr_taint(node.right, env, finfo)
        if isinstance(node, ast.Subscript):
            if self._is_rank_env_subscript(node, finfo):
                return Taint("rank", "rank-keyed environment lookup")
            # fall through to the generic child walk
        if isinstance(node, (ast.Constant, ast.Lambda)):
            return None
        out: Optional[Taint] = None
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.expr, ast.comprehension, ast.keyword)):
                t = self.expr_taint(child, env, finfo)
                if t is not None:
                    if t.kind == "mod":
                        return t      # the specific diagnosis wins
                    out = out or t
        return out

    def _is_rank_env_key(self, node: ast.AST) -> bool:
        return isinstance(node, ast.Constant) and \
            isinstance(node.value, str) and node.value in RANK_ENV_KEYS

    def _is_rank_env_subscript(self, node: ast.Subscript,
                               finfo: FuncInfo) -> bool:
        target = analysis.resolve(node.value, finfo.aliases)
        return target == "os.environ" and self._is_rank_env_key(node.slice)

    def _call_taint(self, call: ast.Call, env: Dict[str, Taint],
                    finfo: FuncInfo) -> Optional[Taint]:
        func = call.func
        if isinstance(func, ast.Attribute):
            if func.attr in SANITIZER_METHODS:
                return None   # an agreement result is gang-uniform
            if func.attr in READBACK_ATTRS and not call.args:
                return Taint("rank", f"device readback '.{func.attr}()' "
                                     "(per-rank under the in-step skip)")
        resolved = analysis.resolve(func, finfo.aliases)
        if resolved in RANK_SOURCE_CALLS:
            return Taint("rank", f"'{resolved}()' is rank-local")
        if resolved in READBACK_CALLS:
            return Taint("rank", f"'{resolved}' reads a per-rank device "
                                 "value (diverges under the in-step skip)")
        if resolved in ("os.getenv", "os.environ.get") and call.args and \
                self._is_rank_env_key(call.args[0]):
            return Taint("rank", "rank-keyed environment lookup")
        if isinstance(func, ast.Name) and func.id == "next":
            return Taint("rank", "per-rank stream read (next())")
        target = self.resolve_call(call, finfo)
        if target is not None:
            reason = self._returns_rank.get(id(target.node))
            if reason:
                return Taint("rank", reason)
        parts = [*call.args, *(kw.value for kw in call.keywords)]
        if isinstance(func, ast.Attribute):
            parts.append(func.value)
        out: Optional[Taint] = None
        for p in parts:
            t = self.expr_taint(p, env, finfo)
            if t is not None:
                if t.kind == "mod":
                    return t
                out = out or t
        return out

    def taints(self, finfo: FuncInfo) -> Dict[str, Taint]:
        """Fixpoint of rank-tainted names inside one function."""
        fid = id(finfo.node)
        cached = self._taints.get(fid)
        if cached is not None:
            return cached
        env: Dict[str, Taint] = {}
        self._taints[fid] = env   # pre-publish: recursion-safe
        for p in (*finfo.node.args.posonlyargs, *finfo.node.args.args,
                  *finfo.node.args.kwonlyargs):
            if p.arg in ("rank", "process_index"):
                env[p.arg] = Taint("rank", f"parameter '{p.arg}' carries "
                                           "the process identity")
        for _ in range(20):   # bounded fixpoint
            if not self._taint_pass(finfo, env):
                break
        return env

    def _taint_pass(self, finfo: FuncInfo, env: Dict[str, Taint]) -> bool:
        changed = False

        def bind(target, taint):
            nonlocal changed
            for name in analysis.target_names(target):
                if name not in env:
                    env[name] = taint
                    changed = True

        def guard_taint(guards):
            for g in reversed(guards):
                if g.taint is not None:
                    return g.taint
            return None

        for stmt, guards, _loops in guarded_statements(
                finfo.node, lambda e: self.expr_taint(e, env, finfo)):
            value = None
            targets: List[ast.AST] = []
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            elif isinstance(stmt, ast.AugAssign):
                targets, value = [stmt.target], stmt.value
                gt = guard_taint(guards)
                if gt is not None and isinstance(stmt.target, ast.Name):
                    # implicit flow, counters only: an increment that only
                    # SOME ranks execute makes the counter itself rank-local
                    # (the exact in-step-skip desync shape)
                    bind(stmt.target, Taint(
                        "rank", f"counter '{stmt.target.id}' advanced under "
                                f"a rank-divergent guard ({gt.reason})"))
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                targets, value = [stmt.target], stmt.iter
            elif isinstance(stmt, ast.Try):
                for h in stmt.handlers:
                    if h.name and any(n in IO_EXCEPTIONS
                                      for n in _handler_names(h)):
                        if h.name not in env:
                            env[h.name] = Taint(
                                "rank", "caught a rank-local I/O exception")
                            changed = True
            if value is not None and targets:
                t = self.expr_taint(value, env, finfo)
                if t is not None:
                    for target in targets:
                        bind(target, t)
        return changed

    # -- CFG ---------------------------------------------------------------
    def cfg(self, finfo: FuncInfo) -> CFG:
        """The function's control-flow graph (built once, cached)."""
        fid = id(finfo.node)
        got = self._cfgs.get(fid)
        if got is None:
            got = self._cfgs[fid] = CFG(finfo.node)
        return got

    # -- scope helpers ------------------------------------------------------
    def scope_functions(self) -> Iterator[FuncInfo]:
        """Functions defined in the linted modules (findings surface here;
        context-only modules feed the call graph silently)."""
        for info in self.functions.values():
            if info.in_scope:
                yield info


def get_dataflow(project) -> Dataflow:
    """The project's dataflow engine, built once and cached (rules share
    the call graph, taint environments and CFGs)."""
    cached = getattr(project, "_lint_dataflow", None)
    if cached is None:
        cached = project._lint_dataflow = Dataflow(project)
    return cached
