"""Project-level dataflow: call graph, per-function CFGs, rank-taint lattice.

PRs 6-8 added gang-collective lockstep contracts (every rank must issue the
same agreement primitives in the same order — ``resilience/coordination.py``)
and their review history was dominated by ONE bug class: a collective
reachable under control flow keyed on rank-local state.  Catching that class
needs more than the per-file AST walks in ``lint/analysis.py``:

1. a **call graph** over the scanned modules plus the ``tools/``/``tasks/``
   driver surface, with a transitive *may-perform-collective* summary per
   function (``self.save()`` is a gang rendezvous three calls down);
2. an intra-procedural **CFG** per function (statement granularity), so the
   pairing rule can enumerate paths between paired agreement calls and name
   the early ``return``/``raise``/``break`` that escapes between them;
3. a **rank-taint lattice** per function: which names (may) hold values
   that differ across ranks.  Sources: ``process_index``/``.rank`` reads,
   rank-keyed environment lookups, device readbacks (``jax.device_get`` /
   ``.item()`` — per-rank under the in-step non-finite skip), per-rank
   stream reads (``next()``), counters incremented under a rank-divergent
   guard, and rank-local I/O exception handlers.  Sanitizers: the agreement
   primitives themselves — a ``broadcast``/``all_gather``/``majority``/
   ``any_flag`` result is gang-uniform by construction.

Everything here is a *may* analysis: taint joins are unions, call edges are
name-resolved through the module's imports (no inheritance walk), and the
CFG adds exceptional edges only for explicit ``raise`` statements.  The
rules built on top (FX007-FX009 in ``rules/collectives.py``) therefore
over-approximate; provably pre-agreed divergence is silenced inline with
``# fleetx: noqa[rule] -- reason`` per docs/static_analysis.md.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Set, Tuple

from fleetx_tpu.lint import analysis

#: coordinator agreement methods — calling one IS a gang collective
COORD_METHODS = {"barrier", "broadcast", "any_flag", "all_gather", "majority"}

#: agreement methods whose RESULT is gang-uniform (taint sanitizers);
#: ``barrier`` returns None so it never launders a value
SANITIZER_METHODS = {"broadcast", "any_flag", "all_gather", "majority"}

#: resolved dotted names of in-program (XLA) collectives — a rank-divergent
#: guard around one of these wedges the mesh exactly like a KV-store one
LAX_COLLECTIVES = {
    "jax.lax.psum", "jax.lax.pmean", "jax.lax.pmax", "jax.lax.pmin",
    "jax.lax.all_gather", "jax.lax.all_to_all", "jax.lax.ppermute",
    "jax.lax.psum_scatter", "jax.lax.pswapaxes",
}

#: every function under this prefix is a cross-process rendezvous
MULTIHOST_PREFIX = "jax.experimental.multihost_utils."

#: attribute reads that yield a rank-local value
RANK_SOURCE_ATTRS = {"rank", "process_index", "preempted"}

#: attribute reads that are gang-uniform even off a rank-local receiver
#: (every rank sees the same world size — ``coord.world == 1`` guards are
#: the canonical "no peers to strand" branch)
UNIFORM_ATTRS = {"world"}

#: resolved call targets that yield a rank-local value
RANK_SOURCE_CALLS = {"jax.process_index"}

#: resolved call targets that read a per-rank device value back to the host
READBACK_CALLS = {"jax.device_get"}
READBACK_ATTRS = {"item", "tolist"}

#: environment keys that identify the process (rank-local by definition)
RANK_ENV_KEYS = {"PROCESS_ID", "RANK", "LOCAL_RANK", "NODE_RANK",
                 "PROCESS_INDEX", "TPU_WORKER_ID", "CLOUD_TPU_TASK_ID"}

#: exception types whose handler body runs only on the rank that hit the
#: (rank-local) I/O fault — control flow inside is rank-divergent
IO_EXCEPTIONS = {"OSError", "IOError", "FileNotFoundError", "NotADirectoryError",
                 "PermissionError", "TimeoutError", "ConnectionError",
                 "BlockingIOError", "InterruptedError", "StopIteration",
                 "EOFError"}

# the call graph parses the same cross-file surface as FX006's consumption
# set and the project digest (core.iter_context_files) — files there are
# *context* even when out of lint scope: a guarded ``self.save()`` in the
# engine is only known collective because checkpoint.py's vote is visible


@dataclasses.dataclass
class Taint:
    """One lattice element: ``kind`` selects the reporting rule.

    ``kind == "rank"`` — plain rank-divergent value (FX007 shapes);
    ``kind == "mod"``  — a modulo over a rank-local counter (the FX009
    step-keyed trigger shape; it stays "mod" through comparisons and
    boolean algebra so ``step % k == 0 and step != last`` keeps the
    specific diagnosis).
    """

    kind: str
    reason: str


@dataclasses.dataclass
class FuncInfo:
    """One function in the project call graph."""

    qualname: str           # e.g. "fleetx_tpu/core/checkpoint.py::save_checkpoint"
    relpath: str
    node: ast.AST           # FunctionDef | AsyncFunctionDef
    aliases: dict
    cls: Optional[str] = None   # enclosing class name, if a method
    in_scope: bool = True       # False for context-only (tools/tasks) modules


# --------------------------------------------------------------------- CFG

ENTRY = "<entry>"
EXIT = "<exit>"


class CFG:
    """Statement-granularity control-flow graph of one function body.

    Nodes are ``id(stmt)`` keys (plus the ``ENTRY``/``EXIT`` sentinels);
    edges follow structured control flow, ``break``/``continue`` jump to
    their loop's follow/head, ``return`` goes to ``EXIT`` and ``raise``
    goes to the nearest enclosing handler set (or ``EXIT`` when none).
    Only explicit ``raise`` statements get exceptional edges — implicit
    exception paths out of arbitrary calls are out of scope (documented
    in docs/static_analysis.md "Scope and limits").
    """

    def __init__(self, fn: ast.AST):
        self.succ: Dict[object, Set[object]] = {ENTRY: set(), EXIT: set()}
        self.stmts: Dict[object, ast.stmt] = {}
        entry = self._seq(fn.body, EXIT, loops=[], tries=[], finals=[])
        self.succ[ENTRY].add(entry)

    # -- construction -------------------------------------------------------
    def _key(self, stmt: ast.stmt) -> object:
        self.stmts[id(stmt)] = stmt
        self.succ.setdefault(id(stmt), set())
        return id(stmt)

    def _seq(self, stmts: List[ast.stmt], follow: object,
             loops: list, tries: list, finals: list) -> object:
        """Wire a statement sequence; returns the entry key (or ``follow``)."""
        entry = follow
        for stmt in reversed(stmts):
            entry = self._stmt(stmt, entry, loops, tries, finals)
        return entry

    def _stmt(self, stmt: ast.stmt, follow: object,
              loops: list, tries: list, finals: list) -> object:
        key = self._key(stmt)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            self.succ[key].add(follow)   # a def is one opaque statement
        elif isinstance(stmt, ast.If):
            self.succ[key].add(self._seq(stmt.body, follow, loops, tries,
                                         finals))
            self.succ[key].add(self._seq(stmt.orelse, follow, loops, tries,
                                         finals) if stmt.orelse else follow)
        elif isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            # loop head evaluates the test/iter each round
            body = self._seq(stmt.body, key, loops + [(key, follow)], tries,
                             finals)
            self.succ[key].add(body)
            exit_edge = (self._seq(stmt.orelse, follow, loops, tries, finals)
                         if stmt.orelse else follow)
            self.succ[key].add(exit_edge)
        elif isinstance(stmt, ast.Break):
            # an abrupt exit runs every enclosing finally first — routing
            # through the innermost finalbody (not straight to the target)
            # is what lets `try: ... finally: barrier("x_exit")` CLOSE a
            # pairing; the over-approximation (flow continues after the
            # finally) trades a narrow false negative for never flagging
            # the canonical cleanup idiom
            self.succ[key].add(finals[-1] if finals
                               else (loops[-1][1] if loops else follow))
        elif isinstance(stmt, ast.Continue):
            self.succ[key].add(finals[-1] if finals
                               else (loops[-1][0] if loops else follow))
        elif isinstance(stmt, ast.Return):
            self.succ[key].add(finals[-1] if finals else EXIT)
        elif isinstance(stmt, ast.Raise):
            # nearest enclosing try WITH handlers: a handler-less frame
            # (try/finally) must not shadow an outer except
            handlers = next((hs for hs in reversed(tries) if hs), None)
            if handlers:
                for h in handlers:
                    self.succ[key].add(h)
            elif finals:
                self.succ[key].add(finals[-1])
            else:
                self.succ[key].add(EXIT)
        elif isinstance(stmt, ast.Try):
            final_entry = (self._seq(stmt.finalbody, follow, loops, tries,
                                     finals)
                           if stmt.finalbody else follow)
            inner_finals = (finals + [final_entry] if stmt.finalbody
                            else finals)
            handler_entries = [self._seq(h.body, final_entry, loops, tries,
                                         inner_finals)
                               for h in stmt.handlers]
            after_body = (self._seq(stmt.orelse, final_entry, loops, tries,
                                    inner_finals)
                          if stmt.orelse else final_entry)
            body = self._seq(stmt.body, after_body, loops,
                             tries + [handler_entries], inner_finals)
            self.succ[key].add(body)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            self.succ[key].add(self._seq(stmt.body, follow, loops, tries,
                                         finals))
        elif isinstance(stmt, ast.Match):
            matched = False
            for case in stmt.cases:
                self.succ[key].add(self._seq(case.body, follow, loops,
                                             tries, finals))
                matched = True
            if not matched:
                self.succ[key].add(follow)
            self.succ[key].add(follow)  # no case may match
        else:
            self.succ[key].add(follow)
        return key

    # -- queries ------------------------------------------------------------
    def reachable(self, start: object,
                  blocked: Optional[Set[object]] = None) -> Set[object]:
        """Keys reachable from ``start`` (exclusive) without passing
        through a ``blocked`` node."""
        blocked = blocked or set()
        seen: Set[object] = set()
        stack = [s for s in self.succ.get(start, ()) if s not in blocked]
        while stack:
            node = stack.pop()
            if node in seen:
                continue
            seen.add(node)
            if node in blocked:
                continue
            stack.extend(self.succ.get(node, ()))
        return seen


# ------------------------------------------------------------ guarded walk

@dataclasses.dataclass
class GuardFrame:
    """One enclosing guard on the walk: the guarding statement and the
    taint (None for uniform guards) of its test."""

    stmt: ast.stmt
    taint: Optional[Taint]


def guarded_statements(fn: ast.AST, taint_of) -> Iterator[
        Tuple[ast.stmt, List[GuardFrame], List[ast.stmt]]]:
    """Yield ``(stmt, guard_stack, loop_stack)`` for every own statement.

    ``taint_of(expr)`` evaluates guard tests; ``guard_stack`` carries every
    enclosing ``if``/``while`` frame (tainted or not, innermost last) plus
    synthetic frames for rank-local I/O exception handlers; ``loop_stack``
    is the enclosing ``for``/``while`` statements.
    """

    def walk(stmts, guards, loops):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            yield stmt, guards, loops
            if isinstance(stmt, ast.If):
                frame = GuardFrame(stmt, taint_of(stmt.test))
                yield from walk(stmt.body, guards + [frame], loops)
                yield from walk(stmt.orelse, guards + [frame], loops)
            elif isinstance(stmt, ast.While):
                frame = GuardFrame(stmt, taint_of(stmt.test))
                yield from walk(stmt.body, guards + [frame], loops + [stmt])
                yield from walk(stmt.orelse, guards, loops)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                yield from walk(stmt.body, guards, loops + [stmt])
                yield from walk(stmt.orelse, guards, loops)
            elif isinstance(stmt, ast.Try):
                yield from walk(stmt.body, guards, loops)
                for h in stmt.handlers:
                    frame = _handler_frame(stmt, h)
                    hg = guards + [frame] if frame else guards
                    yield from walk(h.body, hg, loops)
                yield from walk(stmt.orelse, guards, loops)
                yield from walk(stmt.finalbody, guards, loops)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                yield from walk(stmt.body, guards, loops)
            elif isinstance(stmt, ast.Match):
                for case in stmt.cases:
                    yield from walk(case.body, guards, loops)

    yield from walk(fn.body, [], [])


def _handler_names(handler: ast.ExceptHandler) -> List[str]:
    t = handler.type
    elts = t.elts if isinstance(t, ast.Tuple) else ([] if t is None else [t])
    out = []
    for e in elts:
        path = analysis.dotted(e)
        if path:
            out.append(path.rsplit(".", 1)[-1])
    return out


def _handler_frame(try_stmt: ast.Try,
                   handler: ast.ExceptHandler) -> Optional[GuardFrame]:
    """A synthetic rank-taint frame for rank-local I/O exception handlers."""
    names = _handler_names(handler)
    hits = [n for n in names if n in IO_EXCEPTIONS]
    if hits:
        return GuardFrame(try_stmt, Taint(
            "rank", f"inside a rank-local I/O handler (except {hits[0]})"))
    return GuardFrame(try_stmt, None)


# ------------------------------------------------------------- the engine

class Dataflow:
    """All cross-function facts the FX007-FX009 rules consume, built once
    per :class:`~fleetx_tpu.lint.core.Project` and cached on it."""

    def __init__(self, project):
        self.project = project
        self.functions: Dict[int, FuncInfo] = {}
        self._local_defs: Dict[str, Dict[str, FuncInfo]] = {}
        self._methods: Dict[Tuple[str, str, str], FuncInfo] = {}
        self._by_global: Dict[str, FuncInfo] = {}
        self._reexports: Dict[str, str] = {}
        self._taints: Dict[int, Dict[str, Taint]] = {}
        self._cfgs: Dict[int, CFG] = {}
        self._returns_rank: Dict[int, Optional[str]] = {}
        self.collective_chain: Dict[int, List[str]] = {}
        self._collect()
        self._summarize()

    # -- collection ---------------------------------------------------------
    def _module_dotted(self, relpath: str) -> str:
        dotted = relpath[:-3].replace("/", ".")
        if dotted.endswith(".__init__"):
            dotted = dotted[:-len(".__init__")]
        return dotted

    def _iter_sources(self):
        """(relpath, tree, aliases, in_scope) for scope + context modules."""
        from fleetx_tpu.lint.core import iter_context_files

        seen = set()
        for m in self.project.modules:
            seen.add(m.relpath)
            yield m.relpath, m.tree, analysis.module_aliases(m), True
        for f in iter_context_files(self.project.root):
            rel = self.project.relpath(f)
            if rel in seen:
                continue
            seen.add(rel)
            try:
                tree = ast.parse(f.read_text(encoding="utf-8"))
            except (SyntaxError, OSError, UnicodeDecodeError, ValueError):
                continue
            yield rel, tree, analysis.import_aliases(tree), False

    def _collect(self) -> None:
        for relpath, tree, aliases, in_scope in self._iter_sources():
            dotted = self._module_dotted(relpath)
            local: Dict[str, FuncInfo] = {}
            self._local_defs[relpath] = local

            def visit(node, cls, prefix):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        qual = f"{relpath}::{prefix}{child.name}"
                        info = FuncInfo(qual, relpath, child, aliases,
                                        cls=cls, in_scope=in_scope)
                        self.functions[id(child)] = info
                        local[child.name] = info
                        if cls is None and not prefix:
                            self._by_global[f"{dotted}.{child.name}"] = info
                        if cls is not None:
                            self._methods[(relpath, cls, child.name)] = info
                        visit(child, cls, f"{prefix}{child.name}.")
                    elif isinstance(child, ast.ClassDef):
                        visit(child, child.name, f"{prefix}{child.name}.")
                    else:
                        visit(child, cls, prefix)

            visit(tree, None, "")
            # re-exports: `from x import f` at module top level makes
            # `<this module>.f` an alias for `x.f`
            for node in tree.body:
                if isinstance(node, ast.ImportFrom) and node.module \
                        and not node.level:
                    for a in node.names:
                        self._reexports[f"{dotted}.{a.asname or a.name}"] = \
                            f"{node.module}.{a.name}"

    def _deref(self, dotted: Optional[str]) -> Optional[FuncInfo]:
        for _ in range(6):  # bounded re-export chase
            if dotted is None:
                return None
            hit = self._by_global.get(dotted)
            if hit is not None:
                return hit
            nxt = self._reexports.get(dotted)
            if nxt == dotted:
                return None
            dotted = nxt
        return None

    # -- call resolution ----------------------------------------------------
    def resolve_call(self, call: ast.Call,
                     finfo: FuncInfo) -> Optional[FuncInfo]:
        """The project function a call resolves to, through local scope,
        ``self.``-method dispatch and the module's imports — or None."""
        func = call.func
        if isinstance(func, ast.Name):
            local = self._local_defs.get(finfo.relpath, {}).get(func.id)
            if local is not None:
                return local
            return self._deref(finfo.aliases.get(func.id))
        if isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name) and \
                    func.value.id in ("self", "cls") and finfo.cls:
                return self._methods.get(
                    (finfo.relpath, finfo.cls, func.attr))
            return self._deref(analysis.resolve(func, finfo.aliases))
        return None

    # -- collective summaries ----------------------------------------------
    def direct_collective(self, call: ast.Call,
                          aliases: dict) -> Optional[str]:
        """Why this call IS a gang collective, or None."""
        func = call.func
        if isinstance(func, ast.Attribute) and func.attr in COORD_METHODS:
            return f"gang primitive '.{func.attr}()'"
        resolved = analysis.resolve(func, aliases)
        if resolved in LAX_COLLECTIVES:
            return f"device collective '{resolved}'"
        if resolved and resolved.startswith(MULTIHOST_PREFIX):
            return f"multihost rendezvous '{resolved}'"
        return None

    def _own_calls(self, fn: ast.AST) -> Iterator[ast.Call]:
        for stmt in analysis.own_statements(fn):
            for expr in analysis.statement_exprs(stmt):
                for node in analysis.walk_exprs(expr):
                    if isinstance(node, ast.Call):
                        yield node

    def _summarize(self) -> None:
        """Fixpoints: may-perform-collective chains + rank-local returns."""
        edges: Dict[int, Set[int]] = {}
        for fid, info in self.functions.items():
            callees: Set[int] = set()
            for call in self._own_calls(info.node):
                desc = self.direct_collective(call, info.aliases)
                if desc and fid not in self.collective_chain:
                    self.collective_chain[fid] = [desc]
                target = self.resolve_call(call, info)
                if target is not None:
                    callees.add(id(target.node))
            edges[fid] = callees
        changed = True
        while changed:
            changed = False
            for fid, callees in edges.items():
                if fid in self.collective_chain:
                    continue
                for cid in callees:
                    chain = self.collective_chain.get(cid)
                    if chain is None:
                        continue
                    name = self.functions[cid].node.name
                    new = [f"{name}()"] + chain
                    if len(new) > 6:
                        # cap the DISPLAYED chain only — propagation must
                        # never stop, or deep engine call chains (fit ->
                        # rollback -> save -> commit vote is already 6)
                        # would silently fall out of coverage
                        new = new[:2] + ["..."] + new[-1:]
                    self.collective_chain[fid] = new
                    changed = True
                    break
        # rank-local return summaries, to fixpoint: each pass may add
        # summaries that retaint other functions' environments, so the
        # per-function taint cache is dropped between passes (and after
        # the last one — rule-time queries must see the final summaries)
        for _ in range(3):
            changed = False
            for fid, info in self.functions.items():
                if self._returns_rank.get(fid):
                    continue
                reason = self._returns_rank_local(info)
                if reason and self._returns_rank.get(fid) != reason:
                    self._returns_rank[fid] = reason
                    changed = True
            self._taints.clear()
            if not changed:
                break

    def collective_of(self, fn: ast.AST) -> Optional[str]:
        """Human chain for a may-collective function ('save() -> ...')."""
        chain = self.collective_chain.get(id(fn))
        if chain is None:
            return None
        return " -> ".join(chain)

    def call_collective(self, call: ast.Call,
                        finfo: FuncInfo) -> Optional[str]:
        """Why evaluating this call (transitively) runs a collective."""
        direct = self.direct_collective(call, finfo.aliases)
        if direct:
            return direct
        target = self.resolve_call(call, finfo)
        if target is not None:
            chain = self.collective_of(target.node)
            if chain:
                return f"'{ast.unparse(call.func)}()' -> {chain}"
        return None

    def _returns_rank_local(self, info: FuncInfo) -> Optional[str]:
        env = self.taints(info)
        for stmt in analysis.own_statements(info.node):
            if isinstance(stmt, ast.Return) and stmt.value is not None:
                t = self.expr_taint(stmt.value, env, info)
                if t is not None:
                    return (f"'{info.node.name}()' returns a rank-local "
                            f"value ({t.reason})")
        return None

    # -- taint --------------------------------------------------------------
    def expr_taint(self, node: ast.AST, env: Dict[str, Taint],
                   finfo: FuncInfo) -> Optional[Taint]:
        """May-taint of one expression under the name environment ``env``."""
        if isinstance(node, ast.Name):
            return env.get(node.id)
        if isinstance(node, ast.Attribute):
            if node.attr in RANK_SOURCE_ATTRS:
                return Taint("rank", f"reads rank-local '.{node.attr}'")
            if node.attr in UNIFORM_ATTRS:
                return None
            return self.expr_taint(node.value, env, finfo)
        if isinstance(node, ast.Call):
            return self._call_taint(node, env, finfo)
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
            left = self.expr_taint(node.left, env, finfo)
            if left is not None:
                counter = ast.unparse(node.left)
                return Taint("mod", f"modulo over rank-local counter "
                                    f"'{counter}' ({left.reason})")
            return self.expr_taint(node.right, env, finfo)
        if isinstance(node, ast.Subscript):
            if self._is_rank_env_subscript(node, finfo):
                return Taint("rank", "rank-keyed environment lookup")
            # fall through to the generic child walk
        if isinstance(node, (ast.Constant, ast.Lambda)):
            return None
        out: Optional[Taint] = None
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.expr, ast.comprehension, ast.keyword)):
                t = self.expr_taint(child, env, finfo)
                if t is not None:
                    if t.kind == "mod":
                        return t      # the specific diagnosis wins
                    out = out or t
        return out

    def _is_rank_env_key(self, node: ast.AST) -> bool:
        return isinstance(node, ast.Constant) and \
            isinstance(node.value, str) and node.value in RANK_ENV_KEYS

    def _is_rank_env_subscript(self, node: ast.Subscript,
                               finfo: FuncInfo) -> bool:
        target = analysis.resolve(node.value, finfo.aliases)
        return target == "os.environ" and self._is_rank_env_key(node.slice)

    def _call_taint(self, call: ast.Call, env: Dict[str, Taint],
                    finfo: FuncInfo) -> Optional[Taint]:
        func = call.func
        if isinstance(func, ast.Attribute):
            if func.attr in SANITIZER_METHODS:
                return None   # an agreement result is gang-uniform
            if func.attr in READBACK_ATTRS and not call.args:
                return Taint("rank", f"device readback '.{func.attr}()' "
                                     "(per-rank under the in-step skip)")
        resolved = analysis.resolve(func, finfo.aliases)
        if resolved in RANK_SOURCE_CALLS:
            return Taint("rank", f"'{resolved}()' is rank-local")
        if resolved in READBACK_CALLS:
            return Taint("rank", f"'{resolved}' reads a per-rank device "
                                 "value (diverges under the in-step skip)")
        if resolved in ("os.getenv", "os.environ.get") and call.args and \
                self._is_rank_env_key(call.args[0]):
            return Taint("rank", "rank-keyed environment lookup")
        if isinstance(func, ast.Name) and func.id == "next":
            return Taint("rank", "per-rank stream read (next())")
        target = self.resolve_call(call, finfo)
        if target is not None:
            reason = self._returns_rank.get(id(target.node))
            if reason:
                return Taint("rank", reason)
        parts = [*call.args, *(kw.value for kw in call.keywords)]
        if isinstance(func, ast.Attribute):
            parts.append(func.value)
        out: Optional[Taint] = None
        for p in parts:
            t = self.expr_taint(p, env, finfo)
            if t is not None:
                if t.kind == "mod":
                    return t
                out = out or t
        return out

    def taints(self, finfo: FuncInfo) -> Dict[str, Taint]:
        """Fixpoint of rank-tainted names inside one function."""
        fid = id(finfo.node)
        cached = self._taints.get(fid)
        if cached is not None:
            return cached
        env: Dict[str, Taint] = {}
        self._taints[fid] = env   # pre-publish: recursion-safe
        for p in (*finfo.node.args.posonlyargs, *finfo.node.args.args,
                  *finfo.node.args.kwonlyargs):
            if p.arg in ("rank", "process_index"):
                env[p.arg] = Taint("rank", f"parameter '{p.arg}' carries "
                                           "the process identity")
        for _ in range(20):   # bounded fixpoint
            if not self._taint_pass(finfo, env):
                break
        return env

    def _taint_pass(self, finfo: FuncInfo, env: Dict[str, Taint]) -> bool:
        changed = False

        def bind(target, taint):
            nonlocal changed
            for name in analysis.target_names(target):
                if name not in env:
                    env[name] = taint
                    changed = True

        def guard_taint(guards):
            for g in reversed(guards):
                if g.taint is not None:
                    return g.taint
            return None

        for stmt, guards, _loops in guarded_statements(
                finfo.node, lambda e: self.expr_taint(e, env, finfo)):
            value = None
            targets: List[ast.AST] = []
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            elif isinstance(stmt, ast.AugAssign):
                targets, value = [stmt.target], stmt.value
                gt = guard_taint(guards)
                if gt is not None and isinstance(stmt.target, ast.Name):
                    # implicit flow, counters only: an increment that only
                    # SOME ranks execute makes the counter itself rank-local
                    # (the exact in-step-skip desync shape)
                    bind(stmt.target, Taint(
                        "rank", f"counter '{stmt.target.id}' advanced under "
                                f"a rank-divergent guard ({gt.reason})"))
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                targets, value = [stmt.target], stmt.iter
            elif isinstance(stmt, ast.Try):
                for h in stmt.handlers:
                    if h.name and any(n in IO_EXCEPTIONS
                                      for n in _handler_names(h)):
                        if h.name not in env:
                            env[h.name] = Taint(
                                "rank", "caught a rank-local I/O exception")
                            changed = True
            if value is not None and targets:
                t = self.expr_taint(value, env, finfo)
                if t is not None:
                    for target in targets:
                        bind(target, t)
        return changed

    # -- CFG ---------------------------------------------------------------
    def cfg(self, finfo: FuncInfo) -> CFG:
        """The function's control-flow graph (built once, cached)."""
        fid = id(finfo.node)
        got = self._cfgs.get(fid)
        if got is None:
            got = self._cfgs[fid] = CFG(finfo.node)
        return got

    # -- scope helpers ------------------------------------------------------
    def scope_functions(self) -> Iterator[FuncInfo]:
        """Functions defined in the linted modules (findings surface here;
        context-only modules feed the call graph silently)."""
        for info in self.functions.values():
            if info.in_scope:
                yield info


def get_dataflow(project) -> Dataflow:
    """The project's dataflow engine, built once and cached (rules share
    the call graph, taint environments and CFGs)."""
    cached = getattr(project, "_lint_dataflow", None)
    if cached is None:
        cached = project._lint_dataflow = Dataflow(project)
    return cached


# ------------------------------------------------- thread/lock lattice
#
# The serving fleet (PRs 10-16) runs a genuinely concurrent runtime: the
# engine loop, the replica accept/handler threads and the router's
# accept/poll/per-connection threads all share mutable state behind ad-hoc
# ``threading.Lock`` discipline.  ``ThreadModel`` extends the call graph
# above with the three facts the FX014-FX016 rules (rules/threads.py) need:
#
# 1. a **runs-on context** per function: thread roots are inferred from
#    ``threading.Thread(target=...)`` call sites (the ``name=`` literal is
#    the context label) and propagated over call edges to a fixpoint.  A
#    context is *multi* when its spawn sits in a loop (per-connection
#    handlers) or its spawner itself runs multiply — two instances of the
#    same multi context race each other;
# 2. a **guarded-attribute map** per class: lock attributes (assigned
#    ``threading.Lock()``/``RLock()``/``tsan.lock()``) and, for every
#    ``self.<attr>`` access, the lock set held — lexically (``with
#    self._lock:``) plus the *intersection* of locks held by all callers
#    (so a helper only ever invoked under the lock counts as guarded);
# 3. **lock acquisition order** and **may-block summaries**, both
#    interprocedural, for the inversion (FX015) and drain-stall (FX016)
#    shapes.
#
# Everything stays a *may* analysis: receiver-typed calls (``backend.
# penalize(...)``) resolve through a unique-method-name fallback (classic
# CHA shortcut) guarded by a blocklist of ubiquitous names, and dynamic
# hand-offs (callables through queues, executors) are invisible —
# documented in docs/static_analysis.md "Scope and limits".

THREAD_FACTORIES = {"threading.Thread"}

#: constructors whose result IS a lock (``with`` on the attr guards state);
#: tsan.lock is the sanitizer-wrapped factory the serving locks use
LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock",
    "fleetx_tpu.observability.tsan.lock",
}

#: constructors whose instances synchronize internally — attribute traffic
#: on them is exempt from FX014 (the lock-free-queue / Event FP guards);
#: deque append/popleft are atomic per CPython's documented guarantees
THREADSAFE_FACTORIES = LOCK_FACTORIES | {
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue", "collections.deque", "threading.Event",
    "threading.Condition", "threading.Semaphore",
    "threading.BoundedSemaphore", "threading.Barrier", "threading.local",
}

#: receiver methods that mutate a container in place — ``self._waiting.
#: append(x)`` is a WRITE to the attribute's object, not a read
MUTATOR_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "update", "setdefault", "pop", "popleft", "popitem", "remove",
    "discard", "clear", "sort", "reverse",
}

#: attribute calls that (may) block the calling thread
BLOCKING_ATTR_CALLS = {"recv", "recvfrom", "recv_into", "accept",
                       "communicate", "wait"}

#: resolved call targets that (may) block
BLOCKING_CALLS = {
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "socket.create_connection", "time.sleep",
    "select.select", "jax.block_until_ready", "jax.device_get",
}

#: names too ubiquitous for unique-method-name call resolution — binding
#: ``pool.submit`` or ``sock.close`` to whatever single project method
#: happens to share the name would wire bogus context edges
UNIQUE_METHOD_BLOCKLIST = {
    "get", "put", "set", "add", "append", "pop", "update", "clear",
    "close", "start", "stop", "run", "join", "wait", "send", "recv",
    "read", "write", "open", "submit", "items", "keys", "values", "copy",
    "count", "index", "insert", "remove", "sort", "reverse", "flush",
    "reset", "acquire", "release", "result", "done", "cancel", "name",
    "step", "next", "save", "load", "extend", "discard", "setdefault",
    "main", "check", "emit", "fit", "eval",
}

#: the implicit context of everything reachable from a non-thread entry
MAIN_CONTEXT = "main"

#: methods whose self-attr writes happen before the object is shared
INIT_METHODS = {"__init__", "__post_init__", "__new__"}


@dataclasses.dataclass(frozen=True)
class LockId:
    """One lock identity: a ``self.<attr>`` lock keyed by class (instance-
    insensitive — two Routers conflate, a deliberate over-approximation)
    or a module-level lock keyed by file."""

    relpath: str
    cls: Optional[str]
    attr: str

    @property
    def label(self) -> str:
        return f"{self.cls or self.relpath}.{self.attr}"


@dataclasses.dataclass
class ThreadSpawn:
    """One ``threading.Thread(target=...)`` site resolved to a project
    function."""

    label: str            # name= literal, else "thread:<qualname>"
    multi: bool           # spawned inside a loop → many live instances
    spawner: FuncInfo
    stmt: ast.stmt
    target: FuncInfo
    lineno: int


@dataclasses.dataclass
class AttrAccess:
    """One ``self.<attr>`` read/write inside a class's methods."""

    owner: Tuple[str, str]        # (relpath, class name)
    attr: str
    kind: str                     # "read" | "write"
    rmw: bool                     # read-modify-write (+=, container mutator)
    func: FuncInfo
    stmt: ast.stmt
    lineno: int
    col: int
    lexical_locks: frozenset      # LockIds held by enclosing `with` frames


@dataclasses.dataclass
class LockPair:
    """``second`` acquired while ``first`` is held (lexical nesting or a
    call made under ``first`` into a function that acquires ``second``)."""

    first: LockId
    second: LockId
    relpath: str
    lineno: int
    in_scope: bool
    via: Optional[str] = None     # callee chain for interprocedural pairs


@dataclasses.dataclass
class BlockingSite:
    """A (may-)blocking call reachable while a lock is held."""

    lock: LockId
    desc: str
    relpath: str
    lineno: int
    col: int
    in_scope: bool


class ThreadModel:
    """Thread-context + lock-discipline facts over one project."""

    def __init__(self, df: Dataflow):
        self.df = df
        self.lock_attrs: Dict[Tuple[str, Optional[str]], Set[str]] = {}
        self.safe_attrs: Dict[Tuple[str, str], Set[str]] = {}
        self.module_locks: Dict[str, Set[str]] = {}
        self.spawns: List[ThreadSpawn] = []
        self.spawns_by_func: Dict[int, List[ThreadSpawn]] = {}
        self.accesses: Dict[Tuple[str, str],
                            Dict[str, List[AttrAccess]]] = {}
        self.lock_pairs: List[LockPair] = []
        self.blocking_sites: List[BlockingSite] = []
        self.block_chain: Dict[int, List[str]] = {}
        self._contexts: Dict[int, Dict[str, bool]] = {}
        self._entry_locks: Dict[int, frozenset] = {}
        self._edges: Dict[int, Set[int]] = {}
        self._calls_held: List[Tuple[int, int, frozenset, int]] = []
        self._direct_blocking: Dict[
            int, List[Tuple[str, ast.Call, frozenset]]] = {}
        self._acquires: Dict[int, Set[LockId]] = {}
        self._method_by_name: Dict[str, Optional[FuncInfo]] = {}
        self._discover_lock_types()
        self._build_method_index()
        self._walk_functions()
        self._propagate_contexts()
        self._propagate_entry_locks()
        self._propagate_acquires()
        self._propagate_blocking()
        self._derive_interprocedural()

    # -- type discovery -----------------------------------------------------
    def _discover_lock_types(self) -> None:
        """Lock/thread-safe attribute sets per class + module-level locks."""
        for info in self.df.functions.values():
            if info.cls is None:
                continue
            key = (info.relpath, info.cls)
            for stmt in analysis.own_statements(info.node):
                target = value = None
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                    target, value = stmt.targets[0], stmt.value
                elif isinstance(stmt, ast.AnnAssign) and stmt.value:
                    target, value = stmt.target, stmt.value
                if not (isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"
                        and isinstance(value, ast.Call)):
                    continue
                resolved = analysis.resolve(value.func, info.aliases)
                if resolved in LOCK_FACTORIES:
                    self.lock_attrs.setdefault(key, set()).add(target.attr)
                if resolved in THREADSAFE_FACTORIES:
                    self.safe_attrs.setdefault(key, set()).add(target.attr)
        # module-level `_LOCK = threading.Lock()` assignments
        for relpath, tree, aliases, _in_scope in self.df._iter_sources():
            names: Set[str] = set()
            for node in tree.body:
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Call):
                    if analysis.resolve(node.value.func,
                                        aliases) in LOCK_FACTORIES:
                        names.add(node.targets[0].id)
            if names:
                self.module_locks[relpath] = names

    def _build_method_index(self) -> None:
        """name → method, for names defined by exactly ONE project class
        and not in the ubiquitous-name blocklist (CHA-by-unique-name)."""
        for info in self.df.functions.values():
            if info.cls is None or info.node.name in UNIQUE_METHOD_BLOCKLIST:
                continue
            name = info.node.name
            if name in self._method_by_name:
                self._method_by_name[name] = None    # ambiguous → unusable
            else:
                self._method_by_name[name] = info

    def _resolve_call(self, call: ast.Call,
                      info: FuncInfo) -> Optional[FuncInfo]:
        """resolve_call, falling back to unique-method-name dispatch for
        receiver-typed calls (``backend.penalize(...)``)."""
        target = self.df.resolve_call(call, info)
        if target is not None:
            return target
        func = call.func
        if isinstance(func, ast.Attribute) and not (
                isinstance(func.value, ast.Name)
                and func.value.id in ("self", "cls")):
            head_is_import = (isinstance(func.value, ast.Name)
                              and func.value.id in info.aliases)
            if not head_is_import:
                return self._method_by_name.get(func.attr)
        return None

    # -- lock expression resolution -----------------------------------------
    def _lock_expr(self, expr: ast.AST,
                   info: FuncInfo) -> Optional[LockId]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and info.cls:
            if expr.attr in self.lock_attrs.get(
                    (info.relpath, info.cls), ()):
                return LockId(info.relpath, info.cls, expr.attr)
        if isinstance(expr, ast.Name) and \
                expr.id in self.module_locks.get(info.relpath, ()):
            return LockId(info.relpath, None, expr.id)
        return None

    # -- per-function walk --------------------------------------------------
    def _walk_functions(self) -> None:
        for fid, info in self.df.functions.items():
            self._edges[fid] = set()
            self._acquires[fid] = set()
            self._walk_body(info, info.node.body, (), in_loop=False)

    def _walk_body(self, info: FuncInfo, stmts: List[ast.stmt],
                   held: tuple, in_loop: bool) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            self._visit_stmt(info, stmt, held, in_loop)
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner = list(held)
                for item in stmt.items:
                    lk = self._lock_expr(item.context_expr, info)
                    if lk is None:
                        continue
                    for outer in inner:
                        if outer != lk:
                            self.lock_pairs.append(LockPair(
                                outer, lk, info.relpath, stmt.lineno,
                                info.in_scope))
                    self._acquires[id(info.node)].add(lk)
                    inner.append(lk)
                self._walk_body(info, stmt.body, tuple(inner), in_loop)
            elif isinstance(stmt, ast.If):
                self._walk_body(info, stmt.body, held, in_loop)
                self._walk_body(info, stmt.orelse, held, in_loop)
            elif isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
                self._walk_body(info, stmt.body, held, True)
                self._walk_body(info, stmt.orelse, held, in_loop)
            elif isinstance(stmt, ast.Try):
                self._walk_body(info, stmt.body, held, in_loop)
                for h in stmt.handlers:
                    self._walk_body(info, h.body, held, in_loop)
                self._walk_body(info, stmt.orelse, held, in_loop)
                self._walk_body(info, stmt.finalbody, held, in_loop)
            elif isinstance(stmt, ast.Match):
                for case in stmt.cases:
                    self._walk_body(info, case.body, held, in_loop)

    def _self_attr_writes(self, stmt: ast.stmt) -> Dict[int, bool]:
        """id(Attribute node) → rmw, for self-attrs this statement writes."""
        out: Dict[int, bool] = {}
        rmw = isinstance(stmt, ast.AugAssign)
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.Delete):
            targets = list(stmt.targets)

        def visit(t, depth):
            if isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    visit(e, depth)
            elif isinstance(t, ast.Starred):
                visit(t.value, depth)
            elif isinstance(t, ast.Subscript):
                # self.X[k] = v mutates the object behind the attr
                visit(t.value, depth + 1)
            elif isinstance(t, ast.Attribute):
                if isinstance(t.value, ast.Name) and t.value.id == "self":
                    out[id(t)] = rmw or depth > 0
                else:
                    # self.a.b = v mutates the object behind self.a
                    visit(t.value, depth + 1)

        for t in targets:
            visit(t, 0)
        return out

    def _visit_stmt(self, info: FuncInfo, stmt: ast.stmt,
                    held: tuple, in_loop: bool) -> None:
        fid = id(info.node)
        held_f = frozenset(held)
        write_nodes = self._self_attr_writes(stmt)
        calls: List[ast.Call] = []
        attrs: List[ast.Attribute] = []
        for expr in analysis.statement_exprs(stmt):
            for node in analysis.walk_exprs(expr):
                if isinstance(node, ast.Call):
                    calls.append(node)
                elif isinstance(node, ast.Attribute) and \
                        isinstance(node.value, ast.Name) and \
                        node.value.id == "self":
                    attrs.append(node)
        # container-mutator receivers count as writes: self.X.append(...)
        for call in calls:
            f = call.func
            if isinstance(f, ast.Attribute) and \
                    f.attr in MUTATOR_METHODS and \
                    isinstance(f.value, ast.Attribute) and \
                    isinstance(f.value.value, ast.Name) and \
                    f.value.value.id == "self":
                write_nodes.setdefault(id(f.value), True)
        if info.cls is not None:
            owner = (info.relpath, info.cls)
            for node in attrs:
                w = write_nodes.get(id(node))
                self.accesses.setdefault(owner, {}).setdefault(
                    node.attr, []).append(AttrAccess(
                        owner, node.attr,
                        "write" if w is not None else "read",
                        bool(w), info, stmt, node.lineno,
                        node.col_offset, held_f))
        for call in calls:
            self._visit_call(info, fid, call, stmt, held_f, in_loop)

    def _visit_call(self, info: FuncInfo, fid: int, call: ast.Call,
                    stmt: ast.stmt, held: frozenset, in_loop: bool) -> None:
        resolved = analysis.resolve(call.func, info.aliases)
        if resolved in THREAD_FACTORIES:
            self._record_spawn(info, call, stmt, in_loop)
            return
        desc = self._direct_blocking_desc(call, info, resolved)
        if desc is not None:
            self._direct_blocking.setdefault(fid, []).append(
                (desc, call, held))
        target = self._resolve_call(call, info)
        if target is not None:
            tid = id(target.node)
            self._edges[fid].add(tid)
            self._calls_held.append((fid, tid, held, call.lineno))

    def _record_spawn(self, info: FuncInfo, call: ast.Call,
                      stmt: ast.stmt, in_loop: bool) -> None:
        target_expr = name = None
        for kw in call.keywords:
            if kw.arg == "target":
                target_expr = kw.value
            elif kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                name = kw.value.value
        target: Optional[FuncInfo] = None
        if isinstance(target_expr, ast.Name):
            target = self.df._local_defs.get(info.relpath, {}).get(
                target_expr.id) or self.df._deref(
                    info.aliases.get(target_expr.id))
        elif isinstance(target_expr, ast.Attribute) and \
                isinstance(target_expr.value, ast.Name) and \
                target_expr.value.id in ("self", "cls") and info.cls:
            target = self.df._methods.get(
                (info.relpath, info.cls, target_expr.attr))
        if target is None:
            return
        spawn = ThreadSpawn(
            label=name or f"thread:{target.qualname}",
            multi=in_loop, spawner=info, stmt=stmt, target=target,
            lineno=call.lineno)
        self.spawns.append(spawn)
        self.spawns_by_func.setdefault(id(info.node), []).append(spawn)

    def _direct_blocking_desc(self, call: ast.Call, info: FuncInfo,
                              resolved: Optional[str]) -> Optional[str]:
        func = call.func
        if isinstance(func, ast.Attribute):
            if func.attr in BLOCKING_ATTR_CALLS:
                return f"'.{func.attr}()'"
            if func.attr in ("get", "join") and not call.args:
                # zero-positional-arg get()/join(): queue/thread waits
                # (dict.get and str.join always take a positional)
                return f"'.{func.attr}()'"
            if func.attr in READBACK_ATTRS and not call.args:
                return f"device readback '.{func.attr}()'"
        if resolved in BLOCKING_CALLS:
            return f"'{resolved}'"
        return None

    # -- fixpoints ----------------------------------------------------------
    def _propagate_contexts(self) -> None:
        ctxs: Dict[int, Dict[str, bool]] = {
            fid: {} for fid in self.df.functions}
        called = {tid for callees in self._edges.values() for tid in callees}
        spawned = {id(sp.target.node) for sp in self.spawns}
        for fid in self.df.functions:
            if fid not in called and fid not in spawned:
                ctxs[fid][MAIN_CONTEXT] = False
        for _ in range(60):   # bounded fixpoint
            changed = False
            for caller, callees in self._edges.items():
                src = ctxs[caller]
                if not src:
                    continue
                for callee in callees:
                    dst = ctxs[callee]
                    for label, multi in src.items():
                        new = dst.get(label, False) or multi
                        if dst.get(label) != new or label not in dst:
                            dst[label] = new
                            changed = True
            for sp in self.spawns:
                s_ctx = ctxs[id(sp.spawner.node)]
                multi = sp.multi or len(s_ctx) > 1 or any(s_ctx.values())
                dst = ctxs[id(sp.target.node)]
                new = dst.get(sp.label, False) or multi
                if dst.get(sp.label) != new or sp.label not in dst:
                    dst[sp.label] = new
                    changed = True
            if not changed:
                break
        self._contexts = ctxs

    def contexts_of(self, fid: int) -> Dict[str, bool]:
        """label → multi for one function; unreached functions default to
        the single main context."""
        return self._contexts.get(fid) or {MAIN_CONTEXT: False}

    def _propagate_entry_locks(self) -> None:
        """Intersection (over all call paths) of locks held at entry."""
        called = {tid for callees in self._edges.values() for tid in callees}
        entry: Dict[int, Optional[frozenset]] = {}
        for fid in self.df.functions:
            if fid not in called:
                entry[fid] = frozenset()
        for _ in range(12):   # meets only shrink sets — converges fast
            changed = False
            for caller, callee, held, _ln in self._calls_held:
                ce = entry.get(caller)
                if ce is None:
                    continue
                at_site = ce | held
                cur = entry.get(callee)
                new = at_site if cur is None else (cur & at_site)
                if new != cur:
                    entry[callee] = new
                    changed = True
            if not changed:
                break
        self._entry_locks = {fid: s for fid, s in entry.items()
                             if s is not None}

    def entry_locks_of(self, fid: int) -> frozenset:
        return self._entry_locks.get(fid, frozenset())

    def locks_at(self, access: AttrAccess) -> frozenset:
        """Locks guarding one access: lexical frames ∪ caller intersection."""
        return access.lexical_locks | self.entry_locks_of(
            id(access.func.node))

    def _propagate_acquires(self) -> None:
        for _ in range(30):
            changed = False
            for caller, callees in self._edges.items():
                acc = self._acquires[caller]
                before = len(acc)
                for c in callees:
                    acc |= self._acquires.get(c, set())
                if len(acc) != before:
                    changed = True
            if not changed:
                break

    def _propagate_blocking(self) -> None:
        for fid, sites in self._direct_blocking.items():
            self.block_chain[fid] = [sites[0][0]]
        changed = True
        while changed:
            changed = False
            for fid, callees in self._edges.items():
                if fid in self.block_chain:
                    continue
                for cid in callees:
                    chain = self.block_chain.get(cid)
                    if chain is None:
                        continue
                    new = [f"{self.df.functions[cid].node.name}()"] + chain
                    if len(new) > 5:
                        new = new[:2] + ["..."] + new[-1:]
                    self.block_chain[fid] = new
                    changed = True
                    break

    def _derive_interprocedural(self) -> None:
        """Lock pairs and blocking sites through calls made under a lock."""
        for caller, callee, held, lineno in self._calls_held:
            if not held:
                continue
            info = self.df.functions[caller]
            callee_info = self.df.functions[callee]
            for inner in self._acquires.get(callee, ()):
                for outer in held:
                    if outer != inner:
                        self.lock_pairs.append(LockPair(
                            outer, inner, info.relpath, lineno,
                            info.in_scope,
                            via=f"{callee_info.node.name}()"))
            chain = self.block_chain.get(callee)
            if chain:
                desc = " -> ".join(
                    [f"{callee_info.node.name}()"] + chain[-2:])
                for lk in held:
                    self.blocking_sites.append(BlockingSite(
                        lk, desc, info.relpath, lineno, 0, info.in_scope))
        for fid, sites in self._direct_blocking.items():
            info = self.df.functions[fid]
            for desc, call, held in sites:
                for lk in held:
                    self.blocking_sites.append(BlockingSite(
                        lk, desc, info.relpath, call.lineno,
                        call.col_offset, info.in_scope))

    # -- FX014 helpers ------------------------------------------------------
    def is_init_access(self, access: AttrAccess) -> bool:
        return access.func.node.name in INIT_METHODS

    def happens_before_spawn(self, access: AttrAccess, label: str) -> bool:
        """True when ``access`` is ordered before the spawn of ``label`` in
        the same function (the init-before-spawn publish pattern: start()
        binds the listener, then spawns the accept thread)."""
        for sp in self.spawns_by_func.get(id(access.func.node), ()):
            if sp.label != label:
                continue
            if sp.stmt is access.stmt:
                return True   # `self._thread = threading.Thread(...)`
            cfg = self.df.cfg(access.func)
            a, s = id(access.stmt), id(sp.stmt)
            if a not in cfg.stmts or s not in cfg.stmts:
                continue
            if s in cfg.reachable(a) and a not in cfg.reachable(s):
                return True
        return False

    def conflict(self, w: AttrAccess,
                 o: AttrAccess) -> Optional[Tuple[str, str]]:
        """(ctx_of_w, ctx_of_o) when the write ``w`` and access ``o`` can
        interleave from different threads with no common lock, else None."""
        if self.locks_at(w) & self.locks_at(o):
            return None
        cw = self.contexts_of(id(w.func.node))
        co = self.contexts_of(id(o.func.node))
        for la, ma in cw.items():
            for lb, _mb in co.items():
                if la == lb and not ma:
                    continue            # same single thread: ordered
                if w is o and not w.rmw:
                    continue            # one atomic store racing itself
                if la == MAIN_CONTEXT and self.happens_before_spawn(w, lb):
                    continue
                if lb == MAIN_CONTEXT and self.happens_before_spawn(o, la):
                    continue
                return la, lb
        return None


def get_thread_model(project) -> ThreadModel:
    """The project's thread/lock lattice, built once and cached (the three
    FX014-FX016 rules share contexts, guarded-attr sets and summaries)."""
    cached = getattr(project, "_lint_thread_model", None)
    if cached is None:
        cached = project._lint_thread_model = ThreadModel(
            get_dataflow(project))
    return cached
