"""fleetx-lint — JAX/TPU-aware static analysis for the fleetx_tpu tree.

The reference FleetX only ships a docstring checker; at TPU scale the
dominant failure class is the *semantic* bug that tracing hides until hours
into a pjit run (PAPERS.md: the pjit/TPUv4 scaling paper and the MPMD
pipeline paper both call out sharding/tracing mistakes).  This package is an
AST-based rule framework that catches those classes at commit time:

- host syncs (``.item()``/``float``/``print``) inside jitted code,
- reads of donated buffers after a ``donate_argnums`` call,
- PRNG key reuse without an interleaved ``jax.random.split``,
- ``PartitionSpec`` axis names that the mesh never declares,
- Python ``if``/``while`` on traced values,
- config keys no code consumes (and code sections no config provides),
- and — v2, on the interprocedural dataflow engine in ``dataflow.py``
  (call graph + CFG + rank-taint lattice) — the gang-collective lockstep
  rules: collectives under rank-divergent guards (FX007), unmatched
  agreement pairings / unilateral loop exits (FX008), step-keyed gang
  triggers (FX009) and loop-varying jit retrace hazards (FX010),
- the shardcheck rules over the partition-rule registry
  (``parallel/rules.py``): every YAML-zoo config's ``eval_shape``-derived
  param tree fully + unambiguously matched with divisible sharded dims
  (FX011/FX012, driven by ``parallel/shardcheck.py`` +
  ``tools/shardcheck.py``), and no hand-wired spec table outside the
  registry (FX013),
- plus the docstring conventions previously enforced by
  ``codestyle/check_docstrings.py``, unified under the same registry,
  suppression syntax and exit-code convention.

Usage: ``python tools/lint.py [paths...]`` — see ``docs/static_analysis.md``.
Suppress a single finding with ``# fleetx: noqa[rule-name] -- reason``;
accept a legacy backlog with a baseline file (``tools/lint.py
--write-baseline``).
"""

from fleetx_tpu.lint.core import (  # noqa: F401
    Finding,
    LintResult,
    Project,
    Rule,
    SourceModule,
    all_rules,
    register,
    run_lint,
)
from fleetx_tpu.lint.reporters import (  # noqa: F401
    render_json,
    render_sarif,
    render_text,
)
