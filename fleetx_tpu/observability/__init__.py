"""Unified telemetry: metrics registry, span tracer, and sinks.

The package the ROADMAP's "as fast as the hardware allows" goal measures
itself with (docs/observability.md). Three layers:

- ``metrics``  — counters/gauges/windowed histograms + derived
  tokens-per-sec / step-time EWMA / data-stall / MFU arithmetic;
- ``trace``    — ``span()`` host spans emitting Chrome-trace JSON, nested
  under ``jax.profiler.TraceAnnotation``, plus the re-armable
  ``ProfilerWindow`` for XLA traces;
- ``sinks``    — rank-0-gated JSONL / CSV / Prometheus-textfile emitters.

``Observability`` ties them together for the engines: built from the
``Observability:`` YAML block (``utils/config.py``), it owns the tracer
lifecycle, the sink fan-out and the derived-metric state, and is a cheap
no-op when the block is absent or disabled.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Optional

from fleetx_tpu.observability import flight as flight_mod
from fleetx_tpu.observability import gang as gang_mod
from fleetx_tpu.observability.flight import FlightRecorder  # noqa: F401
from fleetx_tpu.observability.memory import (  # noqa: F401
    MemoryMonitor, sample_memory_stats)
from fleetx_tpu.observability.metrics import (  # noqa: F401
    Counter, DerivedMetrics, Gauge, Histogram, MetricsRegistry, get_registry,
    mfu)
from fleetx_tpu.observability.sinks import (  # noqa: F401
    CsvSink, JsonlSink, PrometheusTextfileSink, Sink, build_sinks)
from fleetx_tpu.observability.trace import (  # noqa: F401
    ProfilerWindow, Tracer, _process_index, get_tracer, set_tracer, span)
from fleetx_tpu.utils.log import logger

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "DerivedMetrics",
    "get_registry", "mfu", "Sink", "JsonlSink", "CsvSink",
    "PrometheusTextfileSink", "build_sinks", "Tracer", "ProfilerWindow",
    "span", "get_tracer", "set_tracer", "Observability", "FlightRecorder",
    "MemoryMonitor", "sample_memory_stats",
]


def _process_count() -> int:
    try:
        import jax  # deferred: package import stays jax-free (router reuse)

        return jax.process_count()
    except (ImportError, RuntimeError):  # backend not initialised yet
        return 1


class Observability:
    """Engine-facing facade over registry + tracer + sinks.

    ``Observability(cfg_block)`` with a falsy/disabled block yields an
    object whose every method is a no-op, so the engines call it
    unconditionally and pay nothing when telemetry is off.
    """

    def __init__(self, cfg: Optional[dict] = None,
                 default_output_dir: str = "./output"):
        cfg = dict(cfg or {})
        self.enabled = bool(cfg.get("enable"))
        self.output_dir = str(cfg.get("output_dir")
                              or os.path.join(default_output_dir, "telemetry"))
        # explicit None checks: ewma_alpha 0 (no smoothing) is a valid value
        alpha = cfg.get("ewma_alpha")
        self.ewma_alpha = 0.1 if alpha is None else float(alpha)
        # the process-wide registry: checkpoint.py and the inference path
        # record into the same one, so engine records see their timings
        self.registry = get_registry()
        self.sinks: list[Sink] = []
        self.tracer: Optional[Tracer] = None
        self._trace_path: Optional[str] = None
        self.derived: Optional[DerivedMetrics] = None
        # gang mode (docs/observability.md "Multi-host"): per-rank sinks +
        # cross-rank merging piggybacked on the loop-control vote; OFF by
        # default so single-process records stay byte-identical to PR 1
        self.gang_enabled = bool(cfg.get("gang"))
        self.rank = _process_index()
        self.world = _process_count()
        self._gang_sink: Optional[Sink] = None
        self._pending_snaps: list[dict] = []
        self._stash_window = 0
        # performance introspection (docs/performance.md): decomposition
        # of closed profiler windows into the perf stream; on by default
        # whenever telemetry is — it costs nothing until a window closes
        perf_cfg = dict(cfg.get("perf") or {})
        self.perf_enabled = self.enabled and bool(perf_cfg.get("enable",
                                                               True))
        self.perf_top_k = int(perf_cfg.get("top_k") or 5)
        self._perf_sink: Optional[Sink] = None
        # crash flight recorder: on whenever telemetry is (an in-memory
        # ring that only touches disk when the run dies); a disabled
        # facade clears any previously-installed recorder, mirroring the
        # Resilience facade's engine-scoped-globals stance
        flight_cfg = dict(cfg.get("flight") or {})
        flight_on = flight_cfg.get("enable")
        self.flight: Optional[FlightRecorder] = None
        if self.enabled and (True if flight_on is None else bool(flight_on)):
            flight_dir = (os.environ.get(flight_mod.ENV_DIR)
                          or os.path.join(self.output_dir, "flight"))
            self.flight = FlightRecorder(
                flight_dir, rank=self.rank, world=self.world,
                capacity=int(flight_cfg.get("capacity")
                             or flight_mod.DEFAULT_CAPACITY))
        flight_mod.install(self.flight)
        if not self.enabled:
            return
        window = cfg.get("histogram_window")
        self.registry.set_default_window(1024 if window is None
                                         else int(window))
        self.sinks = build_sinks(
            cfg.get("sinks") or ["jsonl"], self.output_dir,
            # gang mode: every rank writes its own rank-suffixed files
            # (the per-rank inputs tools/metrics_report.py merges) instead
            # of the rank-0-gated single file
            rank0_only=not self.gang_enabled,
            suffix=f".rank{self.rank}" if self.gang_enabled else "")
        trace_cfg = dict(cfg.get("trace") or {})
        if trace_cfg.get("enable", True):
            self.tracer = Tracer(
                max_events=int(trace_cfg.get("max_events") or 200_000))
            fname = str(trace_cfg.get("path") or "trace.json")
            path = (fname if os.path.isabs(fname)
                    else os.path.join(self.output_dir, fname))
            rank = _process_index()
            if rank:
                # each host writes its own file (shared storage: same path
                # from every process would clobber); merge in Perfetto by pid
                root, ext = os.path.splitext(path)
                path = f"{root}.rank{rank}{ext or '.json'}"
            self._trace_path = path
            set_tracer(self.tracer)
        logger.info("observability enabled → %s (sinks: %s%s)",
                    self.output_dir,
                    [type(s).__name__ for s in self.sinks],
                    ", tracing" if self.tracer else "")

    # -- spans ---------------------------------------------------------------
    def span(self, name: str, **args: Any):
        """A recorded span when enabled, else a zero-cost null context."""
        if not self.enabled:
            return contextlib.nullcontext()
        return span(name, **args)

    def timed_span(self, name: str, **args: Any):
        """Span composed with ``registry.timer``: one region feeds the trace,
        the ``name`` histogram and the ``<name>_seconds_total`` counter."""
        if not self.enabled:
            return contextlib.nullcontext()
        stack = contextlib.ExitStack()
        stack.enter_context(span(name, **args))
        stack.enter_context(self.registry.timer(name))
        return stack

    # -- derived metrics -----------------------------------------------------
    def init_derived(self, flops_per_token: Optional[float],
                     n_devices: int) -> None:
        """Create the DerivedMetrics layer once the module/mesh are known."""
        import jax

        from fleetx_tpu.utils.hardware import peak_flops

        self.derived = DerivedMetrics(
            flops_per_token=flops_per_token,
            peak_flops_per_chip=peak_flops(jax.devices()[0]),
            n_devices=n_devices, ewma_alpha=self.ewma_alpha)
        # the registry is process-wide: baseline the stall integral so a
        # fresh engine's first window doesn't inherit prior engines' stalls
        self.derived._last_stall_total = self.stall_seconds_total()

    def stall_seconds_total(self) -> float:
        """Monotone host-blocked time: data fetch + host-to-device copy."""
        return (self.registry.counter("data_fetch_seconds_total").value
                + self.registry.counter("shard_batch_seconds_total").value)

    # -- record fan-out ------------------------------------------------------
    def emit(self, record: dict) -> None:
        """Fan one step record out to every sink (never raises).

        Gang mode stamps the record with this rank's identity and the
        schema version before it lands in the rank-suffixed files, and
        mirrors a slim form into the flight ring so a crash dump shows
        the final windows' numbers next to the final spans.
        """
        if not self.enabled:
            return
        if self.gang_enabled:
            from fleetx_tpu.observability.schema import SCHEMA_VERSION

            record = dict(record, rank=self.rank, world=self.world,
                          schema_version=SCHEMA_VERSION)
        if self.flight is not None:
            self.flight.record(
                "metrics", "window", step=record.get("step"),
                loss=record.get("loss"),
                step_time=record.get("step_time"))
        for sink in self.sinks:
            try:
                sink.emit(record)
            except OSError as e:  # a full disk must not kill training
                logger.warning("sink %s emit failed: %s",
                               type(sink).__name__, e)

    # -- perf introspection (docs/performance.md) ----------------------------
    def emit_perf(self, report: dict) -> None:
        """Land one trace-decomposition report in the perf metrics stream.

        The full report appends to ``perf.jsonl`` next to
        ``metrics.jsonl`` (its own file: decomposition records have a
        different shape than step records and would fail the step-record
        schema gate ``tools/metrics_report.py`` applies); a slim summary
        goes to the flight ring and the gauge surface
        (``perf_bwd_scan_ms_per_layer`` & friends) so a crash dump or a
        Prometheus scrape shows the last window's decomposition. Never
        raises.
        """
        if not self.perf_enabled:
            return
        from fleetx_tpu.observability import perf as perf_mod

        slim = perf_mod.summary(report)
        for key in ("fwd_scan_ms_per_layer", "bwd_scan_ms_per_layer",
                    "gap_ms", "step_ms"):
            if slim.get(key) is not None:
                self.registry.gauge(f"perf_{key}").set(slim[key])
        if self.flight is not None:
            self.flight.record("perf", "decomposition", **slim)
        if self._perf_sink is None:
            # rank-suffixed like the tracer path: every rank may close a
            # profiler window, and N processes appending to one shared
            # file would interleave/tear lines
            fname = (f"perf.rank{self.rank}.jsonl" if self.rank
                     else "perf.jsonl")
            self._perf_sink = JsonlSink(
                os.path.join(self.output_dir, fname))
        try:
            self._perf_sink.emit({"ts": time.time(), **report})
        except OSError as e:  # a full disk must not kill training
            logger.warning("perf sink emit failed: %s", e)

    # -- gang aggregation (docs/observability.md "Multi-host") ---------------
    def gang_stash(self, record: dict) -> None:
        """Queue one window's record for the next loop-control vote.

        The stash counter is the window-alignment key: lockstep loop
        iterations mean every rank's N-th stash describes the same gang
        window even when step counters diverge under the in-step skip.
        """
        self._pending_snaps.append(gang_mod.snapshot(
            record, self.registry, self.rank, self._stash_window))
        self._stash_window += 1

    def gang_take_pending(self) -> list:
        """Drain the stashed snapshots (the vote payload's ``obs`` field)."""
        pending, self._pending_snaps = self._pending_snaps, []
        return pending

    def gang_merge_emit(self, votes: dict) -> None:
        """Rank 0: merge every rank's piggybacked snapshots into
        gang-scoped records and append them to ``metrics.gang.jsonl``.

        A separate file rather than interleaving with rank 0's local
        records: the merged stream has different aggregation semantics
        (summed counters, slowest-rank throughput) and mixing the two
        would double-count in any downstream summary.
        """
        snaps = {r: f.get("obs") for r, f in votes.items()
                 if isinstance(f, dict) and f.get("obs")}
        if not snaps:
            return
        merged = gang_mod.merge_snapshots(snaps, world=self.world)
        if not merged:
            return
        if self._gang_sink is None:
            self._gang_sink = JsonlSink(
                os.path.join(self.output_dir, "metrics.gang.jsonl"))
        for record in merged:
            try:
                self._gang_sink.emit(record)
            except OSError as e:  # a full disk must not kill training
                logger.warning("gang sink emit failed: %s", e)

    def install_arrival_hook(self) -> None:
        """Route coordination arrival censuses into the skew estimator
        (call once the DerivedMetrics layer exists)."""
        if self.derived is None:
            return

        def _on_arrivals(arrivals: dict) -> None:
            self.derived.update_arrivals(arrivals)
            own = self.derived.rank_skew().get(self.rank)
            if own is not None:
                self.registry.gauge("rank_skew").set(own)

        self._arrival_hook = _on_arrivals
        gang_mod.set_arrival_hook(_on_arrivals)

    def own_skew(self) -> Optional[float]:
        """This rank's rolling arrival skew in seconds (None off-gang)."""
        if self.derived is None:
            return None
        return self.derived.rank_skew().get(self.rank)

    def flight_dump(self, reason: str) -> None:
        """Dump the flight ring (no-op without a recorder; never raises)."""
        if self.flight is not None:
            flight_mod.dump(reason)

    def flush(self) -> None:
        """Durable-ize sinks and write the Chrome trace snapshot."""
        if not self.enabled:
            return
        for sink in self.sinks:
            sink.flush()
        if self._gang_sink is not None:
            self._gang_sink.flush()
        if self._perf_sink is not None:
            self._perf_sink.flush()
        if self.tracer is not None and self._trace_path and \
                self.tracer.events:
            self.tracer.save(self._trace_path)

    def close(self) -> None:
        """Flush + close sinks, release the tracer and the gang hooks."""
        if not self.enabled:
            return
        self.flush()
        for sink in self.sinks:
            sink.close()
        self.sinks = []
        if self._gang_sink is not None:
            self._gang_sink.close()
            self._gang_sink = None
        if self._perf_sink is not None:
            self._perf_sink.close()
            self._perf_sink = None
        if get_tracer() is self.tracer:
            set_tracer(None)
        if flight_mod.get_recorder() is self.flight:
            flight_mod.install(None)
        # identity-guarded like the tracer/recorder: closing an old facade
        # must not uninstall a newer engine's skew hook
        if gang_mod.get_arrival_hook() is getattr(self, "_arrival_hook",
                                                  None):
            gang_mod.set_arrival_hook(None)
