"""Unified telemetry: metrics registry, span tracer, and sinks.

The package the ROADMAP's "as fast as the hardware allows" goal measures
itself with (docs/observability.md). Three layers:

- ``metrics``  — counters/gauges/windowed histograms + derived
  tokens-per-sec / step-time EWMA / data-stall / MFU arithmetic;
- ``trace``    — ``span()`` host spans emitting Chrome-trace JSON, nested
  under ``jax.profiler.TraceAnnotation``, plus the re-armable
  ``ProfilerWindow`` for XLA traces;
- ``sinks``    — rank-0-gated JSONL / CSV / Prometheus-textfile emitters.

``Observability`` ties them together for the engines: built from the
``Observability:`` YAML block (``utils/config.py``), it owns the tracer
lifecycle, the sink fan-out and the derived-metric state, and is a cheap
no-op when the block is absent or disabled.
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Optional

import jax

from fleetx_tpu.observability.metrics import (  # noqa: F401
    Counter, DerivedMetrics, Gauge, Histogram, MetricsRegistry, get_registry,
    mfu)
from fleetx_tpu.observability.sinks import (  # noqa: F401
    CsvSink, JsonlSink, PrometheusTextfileSink, Sink, build_sinks)
from fleetx_tpu.observability.trace import (  # noqa: F401
    ProfilerWindow, Tracer, _process_index, get_tracer, set_tracer, span)
from fleetx_tpu.utils.log import logger

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "DerivedMetrics",
    "get_registry", "mfu", "Sink", "JsonlSink", "CsvSink",
    "PrometheusTextfileSink", "build_sinks", "Tracer", "ProfilerWindow",
    "span", "get_tracer", "set_tracer", "Observability",
]


class Observability:
    """Engine-facing facade over registry + tracer + sinks.

    ``Observability(cfg_block)`` with a falsy/disabled block yields an
    object whose every method is a no-op, so the engines call it
    unconditionally and pay nothing when telemetry is off.
    """

    def __init__(self, cfg: Optional[dict] = None,
                 default_output_dir: str = "./output"):
        cfg = dict(cfg or {})
        self.enabled = bool(cfg.get("enable"))
        self.output_dir = str(cfg.get("output_dir")
                              or os.path.join(default_output_dir, "telemetry"))
        # explicit None checks: ewma_alpha 0 (no smoothing) is a valid value
        alpha = cfg.get("ewma_alpha")
        self.ewma_alpha = 0.1 if alpha is None else float(alpha)
        # the process-wide registry: checkpoint.py and the inference path
        # record into the same one, so engine records see their timings
        self.registry = get_registry()
        self.sinks: list[Sink] = []
        self.tracer: Optional[Tracer] = None
        self._trace_path: Optional[str] = None
        self.derived: Optional[DerivedMetrics] = None
        if not self.enabled:
            return
        window = cfg.get("histogram_window")
        self.registry.set_default_window(1024 if window is None
                                         else int(window))
        self.sinks = build_sinks(cfg.get("sinks") or ["jsonl"],
                                 self.output_dir)
        trace_cfg = dict(cfg.get("trace") or {})
        if trace_cfg.get("enable", True):
            self.tracer = Tracer(
                max_events=int(trace_cfg.get("max_events") or 200_000))
            fname = str(trace_cfg.get("path") or "trace.json")
            path = (fname if os.path.isabs(fname)
                    else os.path.join(self.output_dir, fname))
            rank = _process_index()
            if rank:
                # each host writes its own file (shared storage: same path
                # from every process would clobber); merge in Perfetto by pid
                root, ext = os.path.splitext(path)
                path = f"{root}.rank{rank}{ext or '.json'}"
            self._trace_path = path
            set_tracer(self.tracer)
        logger.info("observability enabled → %s (sinks: %s%s)",
                    self.output_dir,
                    [type(s).__name__ for s in self.sinks],
                    ", tracing" if self.tracer else "")

    # -- spans ---------------------------------------------------------------
    def span(self, name: str, **args: Any):
        """A recorded span when enabled, else a zero-cost null context."""
        if not self.enabled:
            return contextlib.nullcontext()
        return span(name, **args)

    def timed_span(self, name: str, **args: Any):
        """Span composed with ``registry.timer``: one region feeds the trace,
        the ``name`` histogram and the ``<name>_seconds_total`` counter."""
        if not self.enabled:
            return contextlib.nullcontext()
        stack = contextlib.ExitStack()
        stack.enter_context(span(name, **args))
        stack.enter_context(self.registry.timer(name))
        return stack

    # -- derived metrics -----------------------------------------------------
    def init_derived(self, flops_per_token: Optional[float],
                     n_devices: int) -> None:
        """Create the DerivedMetrics layer once the module/mesh are known."""
        from fleetx_tpu.utils.hardware import peak_flops

        self.derived = DerivedMetrics(
            flops_per_token=flops_per_token,
            peak_flops_per_chip=peak_flops(jax.devices()[0]),
            n_devices=n_devices, ewma_alpha=self.ewma_alpha)
        # the registry is process-wide: baseline the stall integral so a
        # fresh engine's first window doesn't inherit prior engines' stalls
        self.derived._last_stall_total = self.stall_seconds_total()

    def stall_seconds_total(self) -> float:
        """Monotone host-blocked time: data fetch + host-to-device copy."""
        return (self.registry.counter("data_fetch_seconds_total").value
                + self.registry.counter("shard_batch_seconds_total").value)

    # -- record fan-out ------------------------------------------------------
    def emit(self, record: dict) -> None:
        """Fan one step record out to every sink (never raises)."""
        if not self.enabled:
            return
        for sink in self.sinks:
            try:
                sink.emit(record)
            except OSError as e:  # a full disk must not kill training
                logger.warning("sink %s emit failed: %s",
                               type(sink).__name__, e)

    def flush(self) -> None:
        """Durable-ize sinks and write the Chrome trace snapshot."""
        if not self.enabled:
            return
        for sink in self.sinks:
            sink.flush()
        if self.tracer is not None and self._trace_path and \
                self.tracer.events:
            self.tracer.save(self._trace_path)

    def close(self) -> None:
        """Flush + close sinks and release the active tracer."""
        if not self.enabled:
            return
        self.flush()
        for sink in self.sinks:
            sink.close()
        self.sinks = []
        if get_tracer() is self.tracer:
            set_tracer(None)
