"""Host-side span tracer emitting Chrome-trace-event JSON.

Two layers, both cheap enough to leave on in production:

- ``span("name")`` — a context manager / decorator that records a Chrome
  "complete" event (``ph: "X"``) into the active ``Tracer`` AND enters
  ``jax.profiler.TraceAnnotation``, so when a ``jax.profiler`` window is
  open the host spans line up with the XLA timeline (the per-stage traces
  the MPMD pipeline work, arXiv:2412.14374, uses to find bubbles).
- ``ProfilerWindow`` — the config-gated ``jax.profiler`` trace window that
  used to live as inline flags in ``eager_engine.fit``. The inline version
  had two bugs this class fixes: (1) ``profiler_enabled = False`` after one
  window made a second ``fit()`` on the same engine silently unprofilable —
  the window is now re-armed per fit; (2) ``stop_trace`` ran without
  draining in-flight device work, truncating the tail of the trace —
  ``maybe_stop`` blocks on a sync value first.

The Chrome JSON (``{"traceEvents": [...]}``) loads directly in
https://ui.perfetto.dev or ``chrome://tracing``. Timestamps/durations are
microseconds per the trace-event spec; ``pid`` is the JAX process index so
multi-host traces merge cleanly.
"""

from __future__ import annotations

import functools
import json
import os
import threading
import time
from typing import Any, Optional

from fleetx_tpu.observability import flight
from fleetx_tpu.utils.log import logger

# jax is imported inside the functions that touch the profiler/backend so
# importing this module (and the observability package) stays jax-free —
# the stdlib-only serving router reuses the package's sinks/schema


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except (ImportError, RuntimeError):  # backend not initialised yet
        return 0


class Tracer:
    """Collects span events; ``save()`` writes one Chrome-trace JSON file."""

    def __init__(self, max_events: int = 200_000):
        self._events: list[dict] = []
        self._lock = threading.Lock()
        self._max_events = int(max_events)
        self._dropped = 0

    def add_event(self, name: str, ts_us: float, dur_us: float,
                  args: Optional[dict] = None) -> None:
        """Record one complete ('X') event; drops past the event cap."""
        evt = {
            "name": name, "ph": "X", "ts": ts_us, "dur": dur_us,
            "pid": _process_index(), "tid": threading.get_ident() & 0xFFFF,
        }
        if args:
            evt["args"] = args
        with self._lock:
            if len(self._events) >= self._max_events:
                self._dropped += 1
                return
            self._events.append(evt)

    @property
    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._dropped = 0

    def to_chrome_trace(self) -> dict:
        """The Perfetto/chrome://tracing JSON object for all events."""
        meta = {"dropped_events": self._dropped} if self._dropped else {}
        return {"traceEvents": self.events, "displayTimeUnit": "ms",
                **({"otherData": meta} if meta else {})}

    def save(self, path: str) -> str:
        """Write the trace (rank-0 file naming is the caller's concern —
        each process writes its own events; pids disambiguate on merge)."""
        if self._dropped:
            logger.warning("tracer dropped %d events past the %d-event cap",
                           self._dropped, self._max_events)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        logger.info("chrome trace written: %s (%d events — open in "
                    "https://ui.perfetto.dev)", path, len(self._events))
        return path


# Active tracer: span() records into it when set. Default None keeps span()
# at pure-TraceAnnotation cost for code paths with observability off.
_active_tracer: Optional[Tracer] = None


def set_tracer(tracer: Optional[Tracer]) -> Optional[Tracer]:
    """Install the active tracer; returns the previous one (restorable)."""
    global _active_tracer
    prev = _active_tracer
    _active_tracer = tracer
    return prev


def get_tracer() -> Optional[Tracer]:
    return _active_tracer


class span:
    """``with span("train_step", step=3): ...`` or ``@span("load")``.

    Records a complete event into the active tracer (if any) and nests the
    region under ``jax.profiler.TraceAnnotation`` so host work is visible
    inside XLA profiler windows. Nesting falls out of the trace-event model:
    an inner span's ``[ts, ts+dur]`` lies within its parent's on the same
    tid, which Perfetto renders as a nested slice.
    """

    __slots__ = ("name", "args", "_t0", "_ts", "_annotation")

    def __init__(self, name: str, **args: Any):
        self.name = name
        self.args = args or None

    def __enter__(self):
        import jax

        self._annotation = jax.profiler.TraceAnnotation(self.name)
        self._annotation.__enter__()
        # wall-clock anchor captured at ENTRY (multi-process traces share
        # the epoch, and an outer span's ts always precedes its children's);
        # duration from perf_counter for sub-µs stability
        self._ts = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        self._annotation.__exit__(exc_type, exc, tb)
        tracer = _active_tracer
        if tracer is not None:
            tracer.add_event(self.name, self._ts * 1e6, dur * 1e6, self.args)
        # spans are the flight recorder's timeline backbone: a crash dump
        # shows exactly which phase each rank was in (no-op when no
        # recorder is installed — one None check). Span args ride NESTED:
        # span() accepts arbitrary keywords, and a user arg named "kind"
        # or "t" must not collide with the event's own fields.
        if flight.get_recorder() is not None:
            extra = {"args": self.args} if self.args else {}
            flight.note("span", self.name,
                        dur_ms=round(dur * 1000.0, 3), **extra)
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with span(self.name, **(self.args or {})):
                return fn(*a, **kw)
        return wrapper


class ProfilerWindow:
    """Config-gated ``jax.profiler`` trace window, re-armable per fit.

    States: ``armed`` → (step >= start) → ``active`` → (step >= stop) →
    ``done``; ``arm()`` at the top of every ``fit()`` resets ``done`` back
    to ``armed`` so each fit gets its own window (the old inline flags
    cleared ``profiler_enabled`` forever after one window).
    """

    def __init__(self, cfg: Optional[dict] = None):
        prof = dict(cfg or {})
        self.enabled = bool(prof.get("enable"))
        sched = list(prof.get("scheduler") or [])

        def _int(key, default):
            v = prof.get(key, default)
            return default if v is None else int(v)

        self.start_step = _int("start_step", int(sched[0]) if sched else 3)
        self.stop_step = _int("stop_step", int(sched[1]) if len(sched) > 1
                              else self.start_step + 5)
        self.output_dir = (prof.get("output_dir")
                           or prof.get("profiler_log") or "./profiler_log")
        # reference Profiler's "detailed" flag: also emit a standalone
        # perfetto trace file next to the xplane dump
        self.detailed = bool(prof.get("detailed"))
        # post-window hook (docs/performance.md): the engine installs the
        # trace-decomposition callback here so every closed window is
        # analyzed automatically; called with the dump directory
        self.on_stop = None
        self._active = False
        self._done = False

    @property
    def active(self) -> bool:
        return self._active

    def arm(self) -> None:
        """Reset for a new fit: a completed window may run again."""
        self._done = False

    def maybe_start(self, step: int) -> bool:
        """Open the window when armed and ``step`` has reached start_step."""
        if (not self.enabled or self._active or self._done
                or step < self.start_step):
            return False
        import jax

        jax.profiler.start_trace(self.output_dir,
                                 create_perfetto_trace=self.detailed)
        self._active = True
        logger.info("profiler trace started → %s", self.output_dir)
        return True

    def maybe_stop(self, step: int, sync: Any = None) -> bool:
        """Close the window once ``step`` passes stop_step (drains first)."""
        if not self._active or step < self.stop_step:
            return False
        self.stop(sync=sync)
        return True

    def stop(self, sync: Any = None) -> None:
        """Close an open window, draining device work first so the trace
        tail isn't truncated (the old inline stop skipped the sync)."""
        if not self._active:
            return
        import jax

        if sync is not None:
            jax.block_until_ready(sync)
        jax.profiler.stop_trace()
        self._active = False
        self._done = True
        logger.info("profiler trace written to %s", self.output_dir)
        if self.on_stop is not None:
            try:
                self.on_stop(self.output_dir)
            except Exception as e:  # noqa: BLE001 — analysis is best-effort
                logger.warning("profiler on_stop hook failed: %s: %s",
                               type(e).__name__, e)
