"""Pluggable metric-record emitters.

One record = one flat dict per logging window (the engine builds it from
``DerivedMetrics`` + the loss/lr scalars). Sinks are deliberately dumb —
append a line, rewrite a textfile — so a crashed run's output is still
parseable up to the last flushed record.

- ``JsonlSink``  — one JSON object per line; the canonical machine format
  (``tools/metrics_report.py`` and the BENCH_* comparisons read it).
- ``CsvSink``    — spreadsheet-friendly; columns fixed by the first record.
- ``PrometheusTextfileSink`` — node-exporter textfile-collector format,
  atomically rewritten per flush so a scraper never reads a torn file.

``build_sinks`` is rank-0 gated via ``jax.process_index()``: on a multi-host
fleet only one process writes, everyone else gets a no-op list. jax is
imported lazily inside that gate — the module itself stays stdlib-only so
the jax-free serving router can reuse ``JsonlSink`` for its fleet stream.
"""

from __future__ import annotations

import csv
import json
import os
import tempfile
from typing import Optional

from fleetx_tpu.utils.log import logger


class Sink:
    """Emitter protocol: ``emit(record)`` per window, ``close()`` at exit."""

    def emit(self, record: dict) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


def _coerce(v):
    """One JSON-safe value: numpy/jax scalars unboxed, containers recursed
    (perf decomposition records nest phase/contributor dicts), everything
    else stringified."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if hasattr(v, "item"):
        return v.item()
    if isinstance(v, dict):
        return {str(k): _coerce(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_coerce(x) for x in v]
    return str(v)


def _jsonable(record: dict) -> dict:
    """Coerce numpy/jax scalars so json/csv writers never choke."""
    return {k: _coerce(v) for k, v in record.items()}


class JsonlSink(Sink):
    """One JSON object per line, append-only, line-buffered."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f = open(path, "a", buffering=1)  # line-buffered: crash-safe

    def emit(self, record: dict) -> None:
        """Append one record as a JSON line."""
        self._f.write(json.dumps(_jsonable(record)) + "\n")

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.close()


class CsvSink(Sink):
    """Header comes from the first record; later records are projected onto
    those columns (extra keys dropped, missing keys empty)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f = open(path, "a", buffering=1, newline="")
        self._writer = csv.writer(self._f)  # stdlib quoting/escaping
        self._columns: Optional[list[str]] = None
        if os.path.getsize(path):
            with open(path, newline="") as f:  # resumed run: keep the header
                head = next(csv.reader(f), None)
            if head:
                self._columns = head

    def emit(self, record: dict) -> None:
        """Append one CSV row (header fixed by the first record)."""
        record = _jsonable(record)
        if self._columns is None:
            self._columns = list(record)
            self._writer.writerow(self._columns)
        self._writer.writerow(
            ["" if record.get(c) is None else record.get(c, "")
             for c in self._columns])

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.close()


class PrometheusTextfileSink(Sink):
    """Latest-value gauges in textfile-collector format.

    Each flush rewrites the whole file via tempfile+rename (atomic on
    POSIX), the contract node-exporter's textfile collector expects.
    """

    PREFIX = "fleetx_"

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    def emit(self, record: dict) -> None:
        """Atomically rewrite the textfile with the record's numbers."""
        lines = []
        for k, v in _jsonable(record).items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue  # prometheus carries numbers only
            name = self.PREFIX + "".join(
                c if c.isalnum() or c == "_" else "_" for c in k)
            lines.append(f"# TYPE {name} gauge\n{name} {v}\n")
        d = os.path.dirname(os.path.abspath(self.path))
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".prom.tmp")
        try:
            with os.fdopen(fd, "w") as f:
                f.writelines(lines)
            os.replace(tmp, self.path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise


_SINK_TYPES = {
    "jsonl": (JsonlSink, "metrics.jsonl"),
    "csv": (CsvSink, "metrics.csv"),
    "prometheus": (PrometheusTextfileSink, "metrics.prom"),
}


def build_sinks(sink_names, output_dir: str,
                rank0_only: bool = True, suffix: str = "") -> list[Sink]:
    """Instantiate sinks under ``output_dir``; non-zero ranks get ``[]``.

    Unknown names warn and are skipped — a typo in YAML must not kill a
    multi-hour training run at its first logging window.

    ``suffix`` is inserted before the file extension (gang mode passes
    ``.rank<i>`` so every rank writes its own ``metrics.rank<i>.jsonl``
    instead of the rank-0-gated single file — the per-rank inputs
    ``tools/metrics_report.py`` merges).
    """
    if rank0_only:
        try:
            import jax  # deferred: the jax-free router path never gets here
            if jax.process_index() != 0:
                return []
        except (ImportError, RuntimeError):  # no jax / backend uninitialised
            pass
    sinks: list[Sink] = []
    for name in sink_names or []:
        entry = _SINK_TYPES.get(str(name).lower())
        if entry is None:
            logger.warning("unknown observability sink %r (known: %s)",
                           name, sorted(_SINK_TYPES))
            continue
        cls, fname = entry
        if suffix:
            root, ext = os.path.splitext(fname)
            fname = f"{root}{suffix}{ext}"
        sinks.append(cls(os.path.join(output_dir, fname)))
    return sinks
