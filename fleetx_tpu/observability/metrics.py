"""Process-local metrics registry + derived training metrics.

The reference FleetX logs only formatted per-step lines
(``language_module.py:58-67``); nothing downstream can consume them. Here
every signal is a first-class, machine-readable metric:

- ``Counter`` / ``Gauge`` / ``Histogram`` primitives collected in a
  ``MetricsRegistry`` (one per process; a module-level default registry is
  shared by the engines, ``core/checkpoint.py`` and the inference path).
- ``Histogram`` keeps a bounded sample window and reports p50/p95/p99 —
  enough for request latencies and step-time spread without a t-digest dep.
- ``DerivedMetrics`` turns raw window measurements into the quantities the
  ROADMAP's "fast as the hardware allows" goal needs tracked: tokens/sec,
  step-time EWMA, data-stall fraction, and MFU from
  ``utils/hardware.py``'s ``peak_flops`` / ``gpt_flops_per_token``
  (arXiv:2204.06514 treats MFU as the primary tracked quantity).

Everything here is host-side Python — nothing is jitted, nothing touches
device state, so recording a metric costs nanoseconds against a
multi-millisecond train step.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Optional


class Counter:
    """Monotonically increasing count (events, tokens, bytes)."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        self._value += amount  # fleetx: noqa[FX014] -- documented lock-free design (module docstring): a float += under the GIL may at worst lose a tick; metrics tolerate that, a per-inc lock on the train-loop hot path does not

    @property
    def value(self) -> float:
        return self._value

    def reset(self) -> None:
        self._value = 0.0


class Gauge:
    """Last-written value (loss scale, queue depth, HBM headroom)."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value: Optional[float] = None

    def set(self, value: float) -> None:
        self._value = float(value)

    @property
    def value(self) -> Optional[float]:
        return self._value

    def reset(self) -> None:
        self._value = None


class Histogram:
    """Windowed sample buffer reporting count/mean/min/max and quantiles.

    The window is a bounded deque: old samples fall off, so long runs report
    recent behaviour rather than an all-time average. Totals (``total_count``
    / ``total_sum``) survive window eviction and ``reset()`` only clears the
    window, so rates stay computable across flushes.
    """

    __slots__ = ("name", "_window", "total_count", "total_sum")

    def __init__(self, name: str, window: int = 1024):
        self.name = name
        self._window: deque = deque(maxlen=max(int(window), 1))
        self.total_count = 0
        self.total_sum = 0.0

    def record(self, value: float) -> None:
        """Append one sample to the window and the all-time totals."""
        v = float(value)
        self._window.append(v)
        self.total_count += 1
        self.total_sum += v

    def quantile(self, q: float) -> Optional[float]:
        """Linear-interpolated quantile over the current window."""
        if not self._window:
            return None
        xs = sorted(self._window)
        if len(xs) == 1:
            return xs[0]
        pos = q * (len(xs) - 1)
        lo = int(math.floor(pos))
        hi = min(lo + 1, len(xs) - 1)
        frac = pos - lo
        return xs[lo] * (1.0 - frac) + xs[hi] * frac

    def summary(self) -> dict:
        """count/mean/min/max/p50/p95/p99 of the current window."""
        xs = list(self._window)
        if not xs:
            return {"count": 0}
        return {
            "count": len(xs),
            "mean": sum(xs) / len(xs),
            "min": min(xs),
            "max": max(xs),
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
        }

    def reset(self) -> None:
        self._window.clear()


class MetricsRegistry:
    """Get-or-create home for every metric in a process.

    Thread-safe on creation (the async-checkpoint thread and the train loop
    may both touch it); individual updates are plain float ops and need no
    lock under the GIL.
    """

    def __init__(self, histogram_window: int = 1024):
        self._lock = threading.Lock()
        self._histogram_window = int(histogram_window)
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}

    # -- get-or-create -------------------------------------------------------
    def counter(self, name: str) -> Counter:
        with self._lock:
            if name not in self._counters:
                self._counters[name] = Counter(name)
            return self._counters[name]

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            if name not in self._gauges:
                self._gauges[name] = Gauge(name)
            return self._gauges[name]

    def histogram(self, name: str, window: Optional[int] = None) -> Histogram:
        with self._lock:
            if name not in self._histograms:
                self._histograms[name] = Histogram(
                    name, window or self._histogram_window)
            return self._histograms[name]

    def set_default_window(self, window: int) -> None:
        """Default window for histograms created from now on (the shared
        registry outlives any one Observability config)."""
        with self._lock:
            self._histogram_window = max(int(window), 1)

    # -- convenience ---------------------------------------------------------
    def timer(self, name: str):
        """``with registry.timer("phase"): ...`` records seconds into the
        ``phase`` histogram and bumps the ``phase_seconds_total`` counter
        (the counter is what data-stall fractions integrate over)."""
        return _Timer(self, name)

    # -- snapshots -----------------------------------------------------------
    def snapshot(self) -> dict:
        """Flat, JSON-ready view: counters/gauges as scalars, histograms as
        their summary dicts."""
        out: dict[str, Any] = {}
        # the lock covers the dict iteration: counter()/histogram() insert
        # from the watchdog thread, and a resize mid-iteration raises
        with self._lock:
            for c in self._counters.values():
                out[c.name] = c.value
            for g in self._gauges.values():
                out[g.name] = g.value
            for h in self._histograms.values():
                out[h.name] = h.summary()
        return out

    def reset_window(self) -> None:
        """Clear histogram windows (counters and gauges persist)."""
        with self._lock:
            for h in self._histograms.values():
                h.reset()

    def reset(self) -> None:
        """Full reset — counters, gauges and histogram windows."""
        with self._lock:
            for c in self._counters.values():
                c.reset()
            for g in self._gauges.values():
                g.reset()
            for h in self._histograms.values():
                h.reset()
                h.total_count = 0
                h.total_sum = 0.0


class _Timer:
    __slots__ = ("_registry", "_name", "_t0")

    def __init__(self, registry: MetricsRegistry, name: str):
        self._registry = registry
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        self._registry.histogram(self._name).record(dt)
        self._registry.counter(self._name + "_seconds_total").inc(dt)
        return False


# ---------------------------------------------------------------------------
# Default per-process registry (checkpoint.py and the engines share it)
# ---------------------------------------------------------------------------

_default_registry: Optional[MetricsRegistry] = None
_default_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The shared per-process registry (lazily created)."""
    global _default_registry
    with _default_lock:
        if _default_registry is None:
            _default_registry = MetricsRegistry()
        return _default_registry


# ---------------------------------------------------------------------------
# Derived metrics: throughput / EWMA / stall fraction / MFU
# ---------------------------------------------------------------------------

def mfu(tokens_per_sec: Optional[float], flops_per_token: Optional[float],
        peak_flops_per_chip: Optional[float], n_devices: int) -> Optional[float]:
    """Model FLOPs utilisation: achieved model FLOP/s over the fleet's peak.

    ``None`` when any input is unknown — on CPU ``peak_flops`` has no entry,
    and a non-LM module has no FLOPs-per-token estimate. Null, not 0: an
    unknown utilisation must never read as a measured-zero regression.
    """
    if not tokens_per_sec or not flops_per_token or not peak_flops_per_chip:
        return None
    return (tokens_per_sec * flops_per_token
            / (peak_flops_per_chip * max(n_devices, 1)))


class DerivedMetrics:
    """Per-logging-window derivation of throughput/MFU/stall signals.

    The engine feeds one ``update()`` per logging window with raw
    measurements; this layer owns the EWMA state and the stall-time
    bookkeeping so the engine stays free of metric arithmetic.
    """

    def __init__(self, flops_per_token: Optional[float] = None,
                 peak_flops_per_chip: Optional[float] = None,
                 n_devices: int = 1, ewma_alpha: float = 0.1):
        self.flops_per_token = flops_per_token
        self.peak_flops_per_chip = peak_flops_per_chip
        self.n_devices = max(int(n_devices), 1)
        self.ewma_alpha = float(ewma_alpha)
        self._ewma: Optional[float] = None
        self._last_stall_total = 0.0
        # per-rank arrival-skew EWMAs (gang mode): rank → seconds behind
        # the median arrival at collective rendezvous points
        self._skew: dict[int, float] = {}

    def update(self, step_time: float, global_batch_size: int,
               tokens_per_sample: Optional[int] = None,
               steps_in_window: int = 1,
               stall_seconds_total: float = 0.0) -> dict:
        """Derive one record's worth of metrics.

        ``step_time`` — mean seconds per optimizer step over the window;
        ``stall_seconds_total`` — a monotone counter of host-blocked seconds
        (data fetch + host-to-device transfer); the delta since the previous
        window, spread over the window's wall time, is the stall fraction.
        """
        step_time = max(float(step_time), 1e-12)
        a = self.ewma_alpha
        self._ewma = (step_time if self._ewma is None
                      else a * step_time + (1.0 - a) * self._ewma)

        samples_per_sec = global_batch_size / step_time
        tokens_per_sec = (samples_per_sec * tokens_per_sample
                          if tokens_per_sample else None)

        window_wall = step_time * max(int(steps_in_window), 1)
        stall_delta = max(stall_seconds_total - self._last_stall_total, 0.0)
        self._last_stall_total = stall_seconds_total
        data_stall_frac = min(stall_delta / max(window_wall, 1e-12), 1.0)

        return {
            "step_time": step_time,
            "step_time_ewma": self._ewma,
            "samples_per_sec": samples_per_sec,
            "tokens_per_sec": tokens_per_sec,
            "data_stall_frac": data_stall_frac,
            "mfu": mfu(tokens_per_sec, self.flops_per_token,
                       self.peak_flops_per_chip, self.n_devices),
        }

    # -- cross-rank skew (docs/observability.md "Multi-host") ---------------
    def update_arrivals(self, arrivals: dict) -> None:
        """Fold one collective rendezvous' arrival census into the rolling
        per-rank skew estimate.

        ``arrivals`` maps rank → publish wall-clock timestamp at one
        agreement (``resilience/coordination.py`` feeds these through the
        ``observability.gang`` arrival hook). Skew is the EWMA of each
        rank's offset from the *median* arrival: a persistently positive
        skew names a straggler while the run is still healthy, instead of
        the post-mortem census a 600 s ``CoordinationTimeout`` yields
        after the run is already dead.
        """
        if not arrivals or len(arrivals) < 2:
            return
        ts = sorted(float(t) for t in arrivals.values())
        mid = len(ts) // 2
        median = ts[mid] if len(ts) % 2 else (ts[mid - 1] + ts[mid]) / 2.0
        a = self.ewma_alpha if self.ewma_alpha > 0 else 1.0
        for rank, t in arrivals.items():
            skew = float(t) - median
            prev = self._skew.get(int(rank))
            self._skew[int(rank)] = (skew if prev is None
                                     else a * skew + (1.0 - a) * prev)

    def rank_skew(self) -> dict:
        """rank → rolling seconds behind (+) / ahead (−) of the median."""
        return dict(self._skew)

    def slowest_rank(self) -> Optional[int]:
        """The rank with the largest positive skew, or None before any
        arrival census has been observed."""
        if not self._skew:
            return None
        return max(self._skew, key=lambda r: self._skew[r])
