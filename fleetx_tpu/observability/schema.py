"""Schema validation for metrics JSONL records — stdlib only, no deps.

One shared definition of "a valid step record", used by the unit tests and
by ``tools/metrics_report.py`` (which exits non-zero on any violation so it
can gate bench runs). Deliberately small: required keys with type sets,
optional keys type-checked when present, unknown keys allowed (records are
forward-extensible).
"""

from __future__ import annotations

import json
from typing import Any, Iterable, Optional

_NUM = (int, float)
_NULLABLE_NUM = (int, float, type(None))

#: version carried by records with cross-rank context (gang mode): plain
#: single-process records carry no version key and count as version 1 —
#: ``tools/metrics_report.py`` refuses to mix versions in one report
SCHEMA_VERSION = 2

# key → (allowed types, required?)
STEP_RECORD_SCHEMA: dict[str, tuple[tuple, bool]] = {
    "step": ((int,), True),
    "ts": (_NUM, True),
    "loss": (_NUM, True),
    "step_time": (_NUM, True),
    "tokens_per_sec": (_NULLABLE_NUM, True),
    "mfu": (_NULLABLE_NUM, True),  # null on chips without a peak table entry
    "step_time_ewma": (_NUM, False),
    "samples_per_sec": (_NULLABLE_NUM, False),
    "data_stall_frac": (_NUM, False),
    "epoch": ((int,), False),
    "lr": (_NUM, False),
    "global_batch_size": ((int,), False),
    # gang-mode context (docs/observability.md "Multi-host"): per-rank
    # records carry rank/world/schema_version; rank-0's merged records add
    # the scope marker, the step-time spread with rank attribution and the
    # rolling straggler skew
    "schema_version": ((int,), False),
    "rank": ((int,), False),
    "world": ((int,), False),
    "scope": ((str,), False),
    "ranks_reported": ((int,), False),
    "step_time_min": (_NUM, False),
    "step_time_median": (_NUM, False),
    "step_time_max": (_NUM, False),
    "step_time_min_rank": ((int,), False),
    "step_time_max_rank": ((int,), False),
    "rank_skew": (_NUM, False),
    "rank_skew_max": (_NUM, False),
    "rank_skew_max_rank": ((int,), False),
    "barrier_wait_ms_mean": (_NUM, False),
    "barrier_wait_ms_max": (_NUM, False),
    "barrier_wait_ms_max_rank": ((int,), False),
    # HBM attribution (docs/performance.md): measured peak next to the
    # auto_layout prediction's relative error; ``hbm_stats`` is the
    # explicit availability marker — backends without ``memory_stats()``
    # say "unavailable" instead of faking a zero peak
    "hbm_stats": ((str,), False),
    "hbm_peak_bytes": (_NULLABLE_NUM, False),
    "hbm_model_error": (_NULLABLE_NUM, False),
}


_NULLABLE_INT = (int, type(None))

# serving-runtime records (docs/serving.md "SLO metrics"): one snapshot
# per replica flush — ``ServingEngine.serving_snapshot()`` emits exactly
# this shape, ``tools/serve.py --metrics-out`` appends it as JSONL, and
# the router's ``stats`` verb returns it verbatim. TTFT / inter-token
# quantiles are null until the first request completes, and the scheduler
# gauges are null (with ``scheduler_gauges: "unavailable"``) until the
# first step runs — same null-not-zero stance as ``mfu``/``hbm_stats``.
SERVING_RECORD_SCHEMA: dict[str, tuple[tuple, bool]] = {
    "ts": (_NUM, True),
    "scope": ((str,), True),
    "schema_version": ((int,), False),
    "requests_admitted": ((int,), True),
    "requests_completed": ((int,), True),
    "requests_refused": ((int,), True),
    # lazy-lifecycle counters (PR 18): pool-pressure swap-outs and which
    # decode attention program this engine compiled ("paged_kernel" when
    # the Pallas kernel's support predicates admitted the config/mesh,
    # "gather" for the dense fallback)
    "requests_preempted": ((int,), False),
    # deadline plane (docs/serving.md "Fault tolerance"): in-flight
    # requests shed at a decode tick because their deadline expired
    "deadline_sheds": ((int,), False),
    "decode_path": ((str,), False),
    "queue_depth": (_NULLABLE_INT, True),
    "active_requests": (_NULLABLE_INT, True),
    "page_occupancy": (_NULLABLE_NUM, True),
    "kv_fragmentation": (_NULLABLE_NUM, False),
    # explicit availability marker for the four scheduler gauges above:
    # "ok" once the engine has stepped, "unavailable" before (a genuine
    # 0.0 occupancy and "never measured" must not collapse to one value)
    "scheduler_gauges": ((str,), False),
    "tokens_total": ((int,), True),
    "tokens_per_sec": (_NULLABLE_NUM, True),
    "ttft_p50_s": (_NULLABLE_NUM, True),
    "ttft_p99_s": (_NULLABLE_NUM, True),
    "itl_p50_s": (_NULLABLE_NUM, True),
    "itl_p99_s": (_NULLABLE_NUM, True),
    # full windowed histogram summaries (count/mean/min/max/p50/p95/p99)
    # — the router pools these count-weighted into the fleet record
    "ttft": ((dict,), False),
    "itl": ((dict,), False),
    # fleet-economics context (PR 16): chips this replica occupies and
    # completions per chip; slo_attainment is null until a window fills
    "chips": ((int,), False),
    "requests_per_chip": (_NULLABLE_NUM, False),
    "slo_attainment": (_NULLABLE_NUM, False),
    "replica": ((str,), False),
}

# fleet records (docs/serving.md "Observability"): the router's periodic
# merge of every reporting replica's serving snapshot — counters summed,
# TTFT/ITL pooled count-weighted with the worst replica attributed,
# requests-per-chip over the fleet's total chips. ``replicas_reported``
# records actual coverage (a draining/crashed replica just doesn't
# report), mirroring ``ranks_reported`` in the gang records.
FLEET_RECORD_SCHEMA: dict[str, tuple[tuple, bool]] = {
    "ts": (_NUM, True),
    "scope": ((str,), True),            # always "fleet"
    "schema_version": ((int,), False),
    "replicas_total": ((int,), True),
    "replicas_reported": ((int,), True),
    "requests_admitted": ((int,), True),
    "requests_completed": ((int,), True),
    "requests_refused": ((int,), True),
    "tokens_total": ((int,), True),
    "tokens_per_sec": (_NULLABLE_NUM, True),
    "chips_total": ((int,), True),
    "requests_per_chip": (_NULLABLE_NUM, True),
    "queue_depth": (_NULLABLE_INT, False),
    "active_requests": (_NULLABLE_INT, False),
    "page_occupancy_mean": (_NULLABLE_NUM, False),
    "page_occupancy_max": (_NULLABLE_NUM, False),
    "page_occupancy_max_replica": ((str,), False),
    "ttft_mean_s": (_NULLABLE_NUM, False),
    "ttft_p99_s": (_NULLABLE_NUM, False),
    "ttft_p99_replica": ((str,), False),
    "itl_mean_s": (_NULLABLE_NUM, False),
    "itl_p99_s": (_NULLABLE_NUM, False),
    "itl_p99_replica": ((str,), False),
    "slo_attainment": (_NULLABLE_NUM, False),
    # fleet-summed deadline sheds (docs/serving.md "Fault tolerance")
    "deadline_sheds": ((int,), False),
    # router-side dispatch counters (serving/router.py)
    "dispatched_total": ((int,), False),
    "redispatched_total": ((int,), False),
    "penalties_total": ((int,), False),
    "drain_refusals_total": ((int,), False),
    "no_backend_total": ((int,), False),
    "completed_total": ((int,), False),
    # breaker/hedging counters + the per-backend breaker-state map
    # ("host:port" → closed|open|half_open) — the chaos drill reads the
    # open→half_open→closed walk off the fleet record stream
    "breaker_opens_total": ((int,), False),
    "breaker_closes_total": ((int,), False),
    "hedges_total": ((int,), False),
    "hedge_cancels_total": ((int,), False),
    "breakers": ((dict,), False),
}

#: registry metric names the serving runtime owns (docs/observability.md):
#: request-latency histograms + scheduler gauges, all in the PR 1 registry
SERVING_METRIC_NAMES = (
    "serving_ttft", "serving_inter_token", "serving_prefill_step",
    "serving_decode_step", "serving_queue_depth", "serving_active_requests",
    "serving_page_occupancy", "serving_kv_fragmentation",
    "serving_requests_total", "serving_requests_completed",
    "serving_requests_refused", "serving_tokens_total",
    # deadline-admission plane (docs/serving.md "Fault tolerance"):
    # classified refusals + in-flight sheds at decode-tick boundaries
    "serving_deadline_sheds", "serving_refusals_overloaded",
    "serving_refusals_unmeetable",
)

#: registry names the SLO layer owns (observability/slo.py) — per-target
#: gauges/counters append ``.<class>.<target>`` suffixes to these stems
SLO_METRIC_NAMES = (
    "slo_attainment", "slo_burn_rate", "slo_breaches_total",
    "slo_evaluations_total",
)


def record_schema_version(record: dict) -> int:
    """A record's schema version (absent → 1, the pre-gang layout)."""
    v = record.get("schema_version")
    return 1 if v is None else int(v)


def validate_serving_record(record: Any) -> list[str]:
    """Errors for one serving snapshot record; empty list means valid."""
    return _validate_against(record, SERVING_RECORD_SCHEMA)


def validate_fleet_record(record: Any) -> list[str]:
    """Errors for one router-merged fleet record; empty list means valid."""
    return _validate_against(record, FLEET_RECORD_SCHEMA)


def validate_record(record: Any) -> list[str]:
    """Errors for one parsed step record; empty list means valid."""
    return _validate_against(record, STEP_RECORD_SCHEMA)


def _validate_against(record: Any, schema: dict) -> list[str]:
    """The shared required/typed/NaN key check behind both validators."""
    if not isinstance(record, dict):
        return [f"record is {type(record).__name__}, expected object"]
    errors = []
    for key, (types, required) in schema.items():
        if key not in record:
            if required:
                errors.append(f"missing required key {key!r}")
            continue
        v = record[key]
        # bool is an int subclass; a boolean loss is a bug, not a number
        if isinstance(v, bool) or not isinstance(v, types):
            names = "|".join(t.__name__ for t in types)
            errors.append(f"key {key!r}: {type(v).__name__} "
                          f"(value {v!r}), expected {names}")
            continue
        if isinstance(v, float) and v != v:  # NaN never validates
            errors.append(f"key {key!r} is NaN")
    return errors


def validate_lines(lines: Iterable[str], max_errors: int = 20,
                   validator=validate_record) -> tuple[int, list[str]]:
    """Validate JSONL text lines → (record_count, errors).

    Errors carry 1-based line numbers; collection stops at ``max_errors``
    so a totally corrupt file doesn't produce megabytes of complaints.
    ``validator`` picks the schema (step records by default; pass
    ``validate_serving_record`` / ``validate_fleet_record`` for the
    serving streams).
    """
    count = 0
    errors: list[str] = []
    for lineno, line in enumerate(lines, start=1):
        if not line.strip():
            continue
        count += 1
        try:
            record = json.loads(line)
        except json.JSONDecodeError as e:
            errors.append(f"line {lineno}: invalid JSON ({e})")
        else:
            errors.extend(f"line {lineno}: {msg}"
                          for msg in validator(record))
        if len(errors) >= max_errors:
            errors.append("... (further errors suppressed)")
            break
    return count, errors


def validate_jsonl(path: str, max_errors: int = 20,
                   validator=validate_record) -> tuple[int, list[str]]:
    with open(path) as f:
        return validate_lines(f, max_errors=max_errors, validator=validator)


def load_valid_records(path: str, validator=validate_record) -> list[dict]:
    """Parse + validate; raises ``ValueError`` listing every violation."""
    count, errors = validate_jsonl(path, validator=validator)
    if errors:
        raise ValueError(f"{path}: {len(errors)} schema violation(s):\n  "
                         + "\n  ".join(errors))
    with open(path) as f:
        return [json.loads(l) for l in f if l.strip()]


def chrome_trace_errors(trace: Any) -> list[str]:
    """Structural check for a Chrome-trace JSON dict (Perfetto-loadable)."""
    if not isinstance(trace, dict):
        return [f"trace is {type(trace).__name__}, expected object"]
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["missing/invalid 'traceEvents' list"]
    errors = []
    for i, evt in enumerate(events):
        if not isinstance(evt, dict):
            errors.append(f"event {i}: not an object")
            continue
        for key, types in (("name", (str,)), ("ph", (str,)),
                           ("ts", _NUM), ("pid", (int,)), ("tid", (int,))):
            if not isinstance(evt.get(key), types):
                errors.append(f"event {i}: bad {key!r}: {evt.get(key)!r}")
        if evt.get("ph") == "X" and not isinstance(evt.get("dur"), _NUM):
            errors.append(f"event {i}: complete event without numeric 'dur'")
        if len(errors) >= 20:
            errors.append("... (further errors suppressed)")
            break
    return errors
